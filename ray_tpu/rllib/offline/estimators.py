"""Off-policy estimators (OPE) — evaluate a target policy from logged data.

Reference: rllib/offline/estimators/ (off_policy_estimator.py,
importance_sampling.py, weighted_importance_sampling.py, direct_method.py,
doubly_robust.py): given behavior-policy episodes (SampleBatches carrying
``action_prob``), estimate the TARGET policy's value without running it:

- ``ImportanceSampling``  — per-episode product of likelihood ratios times
  the discounted return (unbiased, high variance);
- ``WeightedImportanceSampling`` — ratios self-normalized across episodes
  (biased, much lower variance);
- ``DirectMethod``        — fitted-Q evaluation: a Q-model trained on the
  logged transitions by TD under the target policy, evaluated at the
  episode starts;
- ``DoublyRobust``        — DM baseline plus importance-corrected TD
  residuals (unbiased if EITHER the ratios or the Q-model are right).

The target policy is anything exposing ``action_probs(obs_batch) ->
[B, A]`` (discrete); helpers adapt our Algorithm objects. The Q-model for
DM/DR is a small jitted JAX MLP (the reference uses a torch FQE model).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)

ACTION_PROB = "action_prob"


def _split_episodes(batch: SampleBatch) -> list[dict]:
    """Split a flat batch into per-episode column dicts (EPS_ID order).
    One pass: a per-row full-mask scan would be O(episodes * rows)."""
    if len(batch) == 0:
        return []
    if EPS_ID not in batch and DONES not in batch:
        raise ValueError(
            "off-policy estimation needs either EPS_ID or DONES columns to "
            f"split episodes; batch has {sorted(batch.keys())}"
        )
    if EPS_ID in batch:
        ids = np.asarray(batch[EPS_ID])
        index_groups: dict = {}
        for i, eid in enumerate(ids.tolist()):
            index_groups.setdefault(eid, []).append(i)
        cols = {k: np.asarray(v) for k, v in batch.items()}
        return [
            {k: v[idx] for k, v in cols.items()}
            for idx in (np.asarray(g) for g in index_groups.values())
        ]
    # No episode ids: split on DONES.
    dones = np.asarray(batch[DONES]).astype(bool)
    bounds = np.flatnonzero(dones) + 1
    episodes = []
    start = 0
    for end in list(bounds) + ([len(dones)] if not dones[-1] else []):
        if end > start:
            episodes.append({k: np.asarray(v)[start:end] for k, v in batch.items()})
        start = end
    return episodes


def _ratios(policy, ep: dict) -> np.ndarray:
    """Per-step target/behavior likelihood ratios."""
    probs = np.asarray(policy.action_probs(np.asarray(ep[OBS], np.float32)))
    acts = np.asarray(ep[ACTIONS]).astype(int)
    target_p = probs[np.arange(len(acts)), acts]
    behavior_p = np.asarray(ep[ACTION_PROB], np.float64)
    return target_p / np.maximum(behavior_p, 1e-8)


def _discounted_return(rewards: np.ndarray, gamma: float) -> float:
    g = 0.0
    for r in reversed(np.asarray(rewards, np.float64)):
        g = r + gamma * g
    return float(g)


class OffPolicyEstimator:
    def __init__(self, policy, gamma: float = 0.99):
        self.policy = policy
        self.gamma = gamma

    def estimate(self, batch: SampleBatch) -> dict:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Trajectory-wise IS (reference: importance_sampling.py)."""

    def estimate(self, batch: SampleBatch) -> dict:
        episodes = _split_episodes(batch)
        values, behavior = [], []
        for ep in episodes:
            rho = float(np.prod(_ratios(self.policy, ep)))
            g = _discounted_return(ep[REWARDS], self.gamma)
            values.append(rho * g)
            behavior.append(g)
        return {
            "v_target": float(np.mean(values)),
            "v_behavior": float(np.mean(behavior)),
            "num_episodes": len(values),
        }


class WeightedImportanceSampling(OffPolicyEstimator):
    """Self-normalized IS (reference: weighted_importance_sampling.py)."""

    def estimate(self, batch: SampleBatch) -> dict:
        weights, returns = [], []
        for ep in _split_episodes(batch):
            weights.append(float(np.prod(_ratios(self.policy, ep))))
            returns.append(_discounted_return(ep[REWARDS], self.gamma))
        weights = np.asarray(weights, np.float64)
        returns = np.asarray(returns, np.float64)
        denom = max(weights.sum(), 1e-8)
        return {
            "v_target": float((weights * returns).sum() / denom),
            "v_behavior": float(returns.mean()),
            "num_episodes": len(returns),
        }


class _FQEModel:
    """Minimal fitted-Q evaluation model: jitted MLP trained by TD under
    the TARGET policy (reference: fqe_torch_model.py)."""

    def __init__(self, obs_dim: int, n_actions: int, policy, gamma: float,
                 lr: float = 1e-3, hiddens=(64, 64), seed: int = 0):
        import jax
        import optax

        from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _mlp_params

        self._apply = _mlp_apply
        self.policy = policy
        self.gamma = gamma
        self.n_actions = n_actions
        self.params = _mlp_params(jax.random.PRNGKey(seed), obs_dim, tuple(hiddens), n_actions)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)

        def update(params, opt_state, obs, acts, rew, dones, next_obs, next_pi):
            import jax.numpy as jnp

            q_next = _mlp_apply(jax.lax.stop_gradient(params), next_obs)
            v_next = jnp.sum(next_pi * q_next, axis=-1)
            y = rew + gamma * (1.0 - dones) * v_next
            y = jax.lax.stop_gradient(y)

            def loss_fn(p):
                q = _mlp_apply(p, obs)
                q_sa = jnp.take_along_axis(q, acts[:, None], 1)[:, 0]
                return jnp.mean(jnp.square(q_sa - y))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(update)

    def train(self, batch: SampleBatch, iterations: int = 200, batch_size: int = 256, seed: int = 0):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        obs = np.asarray(batch[OBS], np.float32)
        acts = np.asarray(batch[ACTIONS]).astype(np.int32)
        rew = np.asarray(batch[REWARDS], np.float32)
        dones = np.asarray(batch[DONES], np.float32)
        nobs = np.asarray(batch[NEXT_OBS], np.float32)
        next_pi = np.asarray(self.policy.action_probs(nobs), np.float32)
        n = len(obs)
        loss = None
        for _ in range(iterations):
            idx = rng.integers(0, n, min(batch_size, n))
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state,
                jnp.asarray(obs[idx]), jnp.asarray(acts[idx]), jnp.asarray(rew[idx]),
                jnp.asarray(dones[idx]), jnp.asarray(nobs[idx]), jnp.asarray(next_pi[idx]),
            )
        return float(loss) if loss is not None else float("nan")

    def v(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        q = np.asarray(self._apply(self.params, jnp.asarray(np.asarray(obs, np.float32))))
        pi = np.asarray(self.policy.action_probs(obs))
        return (pi * q).sum(-1)

    def q(self, obs: np.ndarray, acts: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        qv = np.asarray(self._apply(self.params, jnp.asarray(np.asarray(obs, np.float32))))
        return qv[np.arange(len(acts)), np.asarray(acts).astype(int)]


def _episode_folds(episodes: list, n_splits: int):
    """Yield (train_batch, eval_episodes) per fold — the reference trains
    the FQE model on a DISJOINT split (k-fold) so the estimate is not
    optimistically biased by the model memorizing the evaluated rewards."""
    n_splits = max(1, min(n_splits, len(episodes)))
    folds = [episodes[i::n_splits] for i in range(n_splits)]
    for i, eval_eps in enumerate(folds):
        train_eps = [ep for j, fold in enumerate(folds) if j != i for ep in fold]
        if not train_eps:  # n_splits == 1: degenerate, train == eval
            train_eps = eval_eps
        train = SampleBatch({
            k: np.concatenate([ep[k] for ep in train_eps])
            for k in train_eps[0]
        })
        yield train, eval_eps


class DirectMethod(OffPolicyEstimator):
    """FQE value of the episode-start states, k-fold: each fold is scored
    by a Q-model trained on the OTHER folds (reference: direct_method.py +
    ope_utils train/test splits)."""

    def __init__(self, policy, gamma: float = 0.99, fqe_iterations: int = 300,
                 n_splits: int = 2):
        super().__init__(policy, gamma)
        self.fqe_iterations = fqe_iterations
        self.n_splits = n_splits
        self.model: _FQEModel | None = None  # last fold's model (introspection)

    def _fit_fold(self, train: SampleBatch, seed: int) -> "_FQEModel":
        obs = np.asarray(train[OBS], np.float32)
        n_actions = int(np.asarray(self.policy.action_probs(obs[:1])).shape[-1])
        model = _FQEModel(obs.shape[-1], n_actions, self.policy, self.gamma, seed=seed)
        model.train(train, iterations=self.fqe_iterations, seed=seed)
        self.model = model
        return model

    def _fold_values(self, model: "_FQEModel", eval_eps: list) -> list:
        starts = np.stack([ep[OBS][0] for ep in eval_eps])
        return list(model.v(starts))

    def estimate(self, batch: SampleBatch) -> dict:
        episodes = _split_episodes(batch)
        values: list = []
        for fold_i, (train, eval_eps) in enumerate(_episode_folds(episodes, self.n_splits)):
            model = self._fit_fold(train, seed=fold_i)
            values += self._fold_values(model, eval_eps)
        return {
            "v_target": float(np.mean(values)),
            "num_episodes": len(values),
        }


class DoublyRobust(DirectMethod):
    """DR = DM baseline + per-step importance-corrected TD residuals
    (reference: doubly_robust.py, Jiang & Li 2016); k-fold like DM."""

    def _fold_values(self, model: "_FQEModel", eval_eps: list) -> list:
        values = []
        for ep in eval_eps:
            obs = np.asarray(ep[OBS], np.float32)
            acts = np.asarray(ep[ACTIONS]).astype(int)
            rew = np.asarray(ep[REWARDS], np.float64)
            ratios = _ratios(self.policy, ep)
            v_hat = model.v(obs)
            q_hat = model.q(obs, acts)
            # Backward recursion: V_DR(t) = v(s) + rho_t (r + gamma V_DR(t+1) - q(s,a))
            v_dr = 0.0
            for t in reversed(range(len(obs))):
                v_dr = v_hat[t] + ratios[t] * (rew[t] + self.gamma * v_dr - q_hat[t])
            values.append(float(v_dr))
        return values


class AlgorithmPolicyAdapter:
    """Adapt a trained discrete Algorithm (DQN family etc.) or a logits fn
    to the ``action_probs`` protocol the estimators expect."""

    def __init__(self, probs_fn: Callable):
        self._fn = probs_fn

    def action_probs(self, obs_batch) -> np.ndarray:
        return np.asarray(self._fn(np.asarray(obs_batch, np.float32)))
