"""Offline RL IO.

Analog of the reference's rllib/offline/ (json_writer.py, json_reader.py,
dataset_reader.py): write rollouts as JSON-lines episode rows; read them back
as SampleBatches with discounted return-to-go targets for offline losses
(BC/MARWIL); or read from a ray_tpu.data Dataset.
"""

from __future__ import annotations

import glob as glob_mod
import json
import os
from typing import Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    OBS,
    REWARDS,
    VALUE_TARGETS,
    SampleBatch,
)


class JsonWriter:
    """Append SampleBatches to JSON-lines files (reference: json_writer.py)."""

    def __init__(self, path: str, max_file_size_rows: int = 100_000):
        self.path = path
        os.makedirs(path, exist_ok=True)
        # Continue numbering past existing files so a second writer on the
        # same directory creates new files instead of appending duplicates.
        existing = sorted(glob_mod.glob(os.path.join(path, "output-*.json")))
        self._file_idx = (
            max(int(os.path.basename(f)[len("output-") : -len(".json")]) for f in existing) + 1
            if existing
            else 0
        )
        self._rows_in_file = 0
        self._max_rows = max_file_size_rows
        self._f = None

    def _ensure_file(self):
        if self._f is None or self._rows_in_file >= self._max_rows:
            if self._f is not None:
                self._f.close()
            self._f = open(
                os.path.join(self.path, f"output-{self._file_idx:05d}.json"), "a"
            )
            self._file_idx += 1
            self._rows_in_file = 0

    def write(self, batch: SampleBatch):
        self._ensure_file()
        n = len(batch)
        keys = list(batch.keys())
        for i in range(n):
            row = {}
            for k in keys:
                v = batch[k][i]
                row[k] = v.tolist() if hasattr(v, "tolist") else v
            self._f.write(json.dumps(row) + "\n")
            self._rows_in_file += 1
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


def _rows_to_batch(rows: list[dict]) -> SampleBatch:
    if not rows:
        return SampleBatch()
    keys = rows[0].keys()
    return SampleBatch({k: np.asarray([r[k] for r in rows]) for k in keys})


def _add_return_targets(batch: SampleBatch, gamma: float) -> SampleBatch:
    """Discounted return-to-go per episode → VALUE_TARGETS (what offline
    losses regress the value head on)."""
    if VALUE_TARGETS in batch or REWARDS not in batch:
        return batch
    rewards = np.asarray(batch[REWARDS], dtype=np.float64)
    dones = np.asarray(batch.get(DONES, np.zeros(len(rewards), bool)), dtype=bool)
    returns = np.zeros_like(rewards)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = rewards[i] + gamma * acc
        returns[i] = acc
    batch[VALUE_TARGETS] = returns.astype(np.float32)
    return batch


class JsonReader:
    """Load JSON-lines rollout files; serve shuffled minibatches
    (reference: json_reader.py)."""

    def __init__(self, inputs, gamma: float = 0.99, seed: int = 0):
        paths = [inputs] if isinstance(inputs, str) else list(inputs)
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files += sorted(glob_mod.glob(os.path.join(p, "*.json")))
            else:
                files += sorted(glob_mod.glob(p))
        if not files:
            raise FileNotFoundError(f"no offline data files under {paths}")
        rows: list[dict] = []
        for fpath in files:
            with open(fpath) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        self.batch = _add_return_targets(_rows_to_batch(rows), gamma)
        self._rng = np.random.default_rng(seed)

    def next(self, batch_size: Optional[int] = None) -> SampleBatch:
        n = len(self.batch)
        if batch_size is None or batch_size >= n:
            return self.batch
        idx = self._rng.choice(n, size=batch_size, replace=False)
        return SampleBatch({k: np.asarray(v)[idx] for k, v in self.batch.items()})


class DatasetReader:
    """Offline data from a ray_tpu.data Dataset of row dicts
    (reference: offline/dataset_reader.py)."""

    def __init__(self, dataset, gamma: float = 0.99, seed: int = 0):
        rows = dataset.take_all()
        self.batch = _add_return_targets(_rows_to_batch(rows), gamma)
        self._rng = np.random.default_rng(seed)

    next = JsonReader.next


class ExternalInputReader:
    """Input reader over a live ``PolicyServerInput`` — train directly from
    external simulators.

    Reference parity: there ``PolicyServerInput`` IS an input reader plugged
    in via ``config.input_`` (``"input": lambda ioctx: PolicyServerInput(...)``,
    rllib/env/policy_server_input.py), so offline-capable algorithms consume
    client-driven episodes instead of files. Here the same seam: the first
    ``next()`` blocks until ``min_episodes`` external episodes have
    completed; every later call drains whatever episodes have finished since
    (min 1, so nothing sits stale). Return targets are computed per drained
    fragment and the rows land in a preallocated FIFO ``ReplayBuffer``
    window (O(fresh) writes, no full-window copies). Sampling is uniform
    with replacement at exactly ``batch_size`` rows, so the training batch
    shape is static from the first step — no per-fold XLA retraces.
    """

    def __init__(
        self,
        server,
        gamma: float = 0.99,
        seed: int = 0,
        min_episodes: int = 1,
        window_rows: int = 50_000,
        poll_interval_s: float = 0.05,
        timeout_s: float = 60.0,
    ):
        from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

        self._server = server
        self._gamma = gamma
        self._min_episodes = min_episodes
        self._poll = poll_interval_s
        self._timeout = timeout_s
        self._window = ReplayBuffer(window_rows, seed=seed)

    def next(self, batch_size: Optional[int] = None) -> SampleBatch:
        import time as _time

        deadline = _time.monotonic() + self._timeout
        while True:
            # One call drains every completed episode held by the server;
            # after the initial fill, any single finished episode is folded
            # immediately rather than waiting for min_episodes again.
            need = self._min_episodes if len(self._window) == 0 else 1
            fresh = self._server.next_batch(need)
            if fresh is not None:
                fresh = _add_return_targets(fresh, self._gamma)
                n = len(fresh)
                cap = self._window.capacity
                if n > cap:
                    # One drain can exceed the window (many sims ran before
                    # training started): keep only the newest rows —
                    # ReplayBuffer.add would otherwise wrap/clobber (or
                    # raise past 2x capacity).
                    fresh = SampleBatch(
                        {k: np.asarray(v)[n - cap:] for k, v in fresh.items()}
                    )
                self._window.add(fresh)
            if len(self._window) > 0:
                break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"no external episodes completed within {self._timeout}s"
                )
            _time.sleep(self._poll)
        if batch_size is None:
            batch_size = len(self._window)
        return self._window.sample(batch_size)


def make_input_reader(input_, gamma: float = 0.99, seed: int = 0, **reader_kwargs):
    """Dispatch config.input_ to the right reader — shared by every
    offline-capable algorithm (MARWIL/BC, CQL, CRR): a ray_tpu.data Dataset,
    a live PolicyServerInput (external simulators), or json path(s).

    ``reader_kwargs`` (config.offline_data(input_reader_kwargs=...)) reach
    the constructed reader — e.g. ``timeout_s``/``min_episodes``/
    ``window_rows`` for slow external simulators."""
    if hasattr(input_, "take_all"):
        return DatasetReader(input_, gamma=gamma, seed=seed, **reader_kwargs)
    if hasattr(input_, "next_batch"):
        return ExternalInputReader(input_, gamma=gamma, seed=seed, **reader_kwargs)
    return JsonReader(input_, gamma=gamma, seed=seed, **reader_kwargs)


from ray_tpu.rllib.offline.estimators import (  # noqa: F401,E402
    AlgorithmPolicyAdapter,
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    OffPolicyEstimator,
    WeightedImportanceSampling,
)
