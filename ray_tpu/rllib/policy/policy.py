"""Legacy Policy facade over the new-stack RLModule.

The reference ships a full legacy policy layer (`rllib/policy/policy.py:175`
`class Policy`, `compute_single_action:466`, `compute_actions:630`,
`compute_log_likelihoods:674`, `postprocess_trajectory:710`,
`get_weights:906` / `set_weights:921`, `get_state:971` / `set_state:1046`,
`export_checkpoint:1128`, `from_checkpoint:265`) that external-serving
paths (PolicyClient/Server), offline evaluation, and user code built
against. This build is new-stack-first — the numerics live in
`core/rl_module.py` as pure functions — so `Policy` here is a thin
stateful VIEW over (spec, params): the classic API surface, with every
forward delegating to the same jitted pure functions the rollout workers
and learners use. No second model implementation exists to drift.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch, compute_gae


class Policy:
    """Stateful view over an RLModuleSpec + params pytree.

    Construct directly, via :meth:`from_spaces`, or snapshot a trained
    algorithm with ``algo.get_policy()`` (weights are copied at call time —
    call again after more training for fresh ones).

    ``obs_filter_state`` carries the training-time observation filter
    (MeanStdFilter running statistics): a policy trained behind a filter
    must see filtered observations at inference too, so every
    ``compute_*`` call applies it before the forward.
    """

    def __init__(self, spec, params, observation_space=None, action_space=None, config: Optional[dict] = None, obs_filter_state: Optional[dict] = None):
        self.spec = spec
        self.params = params
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = dict(config or {})
        self._obs_filter_state = obs_filter_state
        self._rng_seed = int(self.config.get("seed", 0))
        self._calls = 0

    def _filter_obs(self, obs: np.ndarray) -> np.ndarray:
        if self._obs_filter_state is None:
            return obs
        from ray_tpu.rllib.connectors import MeanStdFilter

        f = MeanStdFilter()
        f.set_state(self._obs_filter_state)
        return np.asarray(f.transform(obs), np.float32)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_spaces(cls, observation_space, action_space, config: Optional[dict] = None) -> "Policy":
        import jax

        from ray_tpu.rllib.core.rl_module import RLModuleSpec, init_params

        cfg = dict(config or {})
        spec = RLModuleSpec.from_spaces(
            observation_space, action_space, hiddens=tuple(cfg.get("hiddens", (64, 64)))
        )
        params = init_params(jax.random.PRNGKey(int(cfg.get("seed", 0))), spec)
        return cls(spec, params, observation_space, action_space, cfg)

    @classmethod
    def from_checkpoint(cls, path: str) -> "Policy":
        """Reference: Policy.from_checkpoint (rllib/policy/policy.py:265)."""
        with open(os.path.join(path, "policy_state.pkl"), "rb") as f:
            state = pickle.load(f)
        return cls(
            state["spec"],
            state["weights"],
            config=state.get("config"),
            obs_filter_state=state.get("obs_filter"),
        )

    # -- inference ---------------------------------------------------------

    def _next_rng(self):
        import jax

        self._calls += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._rng_seed), self._calls)

    def compute_actions(
        self, obs_batch, explore: bool = True, **kwargs
    ) -> Tuple[np.ndarray, List, Dict[str, np.ndarray]]:
        """Batch inference → (actions, state_outs, extra_fetches).

        Reference signature/semantics: rllib/policy/policy.py:630 — extra
        fetches carry per-sample ``action_logp`` and ``vf_preds``.
        """
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        obs = jnp.asarray(self._filter_obs(np.asarray(obs_batch, np.float32)))
        actions, logp, value = rl_module.sample_actions(
            self.params, obs, self._next_rng(), self.spec, explore
        )
        return (
            np.asarray(actions),
            [],
            {"action_logp": np.asarray(logp), "vf_preds": np.asarray(value)},
        )

    def compute_single_action(self, obs, explore: bool = True, **kwargs):
        """Reference: rllib/policy/policy.py:466. Returns
        (action, state_outs, info)."""
        actions, state, info = self.compute_actions(
            np.asarray(obs, np.float32)[None], explore=explore
        )
        a = actions[0]
        info = {k: v[0] for k, v in info.items()}
        return (a.item() if self.spec.discrete else a), state, info

    def compute_log_likelihoods(self, actions, obs_batch) -> np.ndarray:
        """Reference: rllib/policy/policy.py:674 — log p(a|s) under the
        current params for externally chosen actions."""
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        obs = jnp.asarray(self._filter_obs(np.asarray(obs_batch, np.float32)))
        acts = jnp.asarray(np.asarray(actions))
        logp, _, _ = rl_module.action_logp_and_entropy(self.params, obs, acts, self.spec)
        return np.asarray(logp)

    # -- trajectory postprocessing ----------------------------------------

    def postprocess_trajectory(
        self, sample_batch: SampleBatch, last_value: float = 0.0
    ) -> SampleBatch:
        """GAE advantages/value targets in place of the reference's
        per-policy postprocess_fn (rllib/policy/policy.py:710); requires
        ``vf_preds`` (filled by compute_actions) and rewards/dones.
        ``last_value`` bootstraps a mid-episode fragment cut."""
        return compute_gae(
            sample_batch,
            last_value,
            gamma=float(self.config.get("gamma", 0.99)),
            lambda_=float(self.config.get("lambda", 0.95)),
        )

    # -- weights / state ---------------------------------------------------

    def get_weights(self):
        return self.params

    def set_weights(self, weights) -> None:
        self.params = weights

    def get_state(self) -> Dict[str, Any]:
        return {
            "weights": self.params,
            "spec": self.spec,
            "config": self.config,
            "obs_filter": self._obs_filter_state,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["weights"]
        self.spec = state.get("spec", self.spec)
        self.config = dict(state.get("config", self.config))
        self._obs_filter_state = state.get("obs_filter", self._obs_filter_state)

    def export_checkpoint(self, export_dir: str) -> None:
        """Reference: rllib/policy/policy.py:1128."""
        os.makedirs(export_dir, exist_ok=True)
        with open(os.path.join(export_dir, "policy_state.pkl"), "wb") as f:
            pickle.dump(self.get_state(), f)
