"""SampleBatch — columnar rollout data.

Reference: rllib/policy/sample_batch.py:96 (SampleBatch) — a dict of
parallel numpy arrays with concat/shuffle/slice/minibatch utilities. Kept
numpy-first: batches convert to device arrays only at the learner edge.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
LOGPS = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"
NEXT_VF_PREDS = "next_vf_preds"
FRAG_CUT = "frag_cut"  # 1 on the last row of a rollout fragment


class SampleBatch(dict):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def __len__(self) -> int:  # len(batch) == row count, like the reference
        return self.count

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b.count > 0]
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({k: np.concatenate([b[k] for b in batches]) for k in keys})

    def shuffle(self, seed=None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.count)
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def minibatches(self, minibatch_size: int, shuffle: bool = True, seed=None) -> Iterator["SampleBatch"]:
        b = self.shuffle(seed) if shuffle else self
        for start in range(0, b.count, minibatch_size):
            mb = b.slice(start, min(start + minibatch_size, b.count))
            if mb.count == minibatch_size:
                yield mb

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        out = []
        ids = self[EPS_ID]
        start = 0
        for i in range(1, len(ids) + 1):
            if i == len(ids) or ids[i] != ids[start]:
                out.append(self.slice(start, i))
                start = i
        return out


def compute_gae(
    batch: SampleBatch,
    last_value: float,
    gamma: float = 0.99,
    lambda_: float = 0.95,
) -> SampleBatch:
    """Generalized advantage estimation over one rollout fragment
    (reference: rllib/evaluation/postprocessing.py compute_advantages)."""
    rewards = batch[REWARDS].astype(np.float32)
    dones = batch[DONES].astype(np.float32)
    values = batch[VF_PREDS].astype(np.float32)
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    next_value = float(last_value)
    gae = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lambda_ * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = adv + values
    # Bootstrap values for V-trace-style off-policy corrections (IMPALA):
    # next state's value within the fragment, last_value at the cut, 0 at
    # episode ends.
    next_vf = np.empty(n, dtype=np.float32)
    cuts = np.zeros(n, dtype=np.float32)
    if n:
        next_vf[:-1] = values[1:]
        next_vf[-1] = float(last_value)
        next_vf *= 1.0 - dones
        cuts[-1] = 1.0
    batch[NEXT_VF_PREDS] = next_vf
    batch[FRAG_CUT] = cuts
    return batch


class MultiAgentBatch:
    """Minimal multi-agent container (reference: sample_batch.py:1221)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch]):
        self.policy_batches = policy_batches

    @property
    def count(self) -> int:
        return sum(b.count for b in self.policy_batches.values())
