from ray_tpu.rllib.policy.policy import Policy  # noqa: F401
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch, compute_gae  # noqa: F401
