from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch, compute_gae  # noqa: F401
