"""Connector implementations (reference: rllib/connectors/connector.py base +
agent/{mean_std_filter,clip,flatten}.py, action/clip.py)."""

from __future__ import annotations

import numpy as np


class AgentConnector:
    """obs batch [N, ...] -> obs batch. Override __call__ (+ state hooks for
    stateful connectors)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # Stateful connectors override these; stateless return None / ignore.
    def get_state(self):
        return None

    def set_state(self, state):
        pass

    def merge_states(self, states: list):
        """Combine per-worker states (driver-side reduce)."""
        pass


class ActionConnector:
    def __call__(self, actions: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ClipObservations(AgentConnector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class FlattenObservations(AgentConnector):
    def __call__(self, obs):
        return np.asarray(obs).reshape(len(obs), -1)


class MeanStdFilter(AgentConnector):
    """Running per-feature normalization (reference:
    rllib/utils/filter.py MeanStdFilter as an agent connector): Welford
    accumulation per worker, merged across workers with the Chan formula when
    weights sync."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._count = 0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        n = obs.shape[0]
        if n:
            # Vectorized batch statistics folded in with the Chan formula —
            # this runs on every env step, a per-row Python loop would
            # dominate rollout cost.
            b_mean = obs.mean(axis=0)
            b_m2 = ((obs - b_mean) ** 2).sum(axis=0)
            if self._mean is None:
                self._count, self._mean, self._m2 = n, b_mean, b_m2
            else:
                total = self._count + n
                delta = b_mean - self._mean
                self._mean = self._mean + delta * n / total
                self._m2 = self._m2 + b_m2 + delta * delta * self._count * n / total
                self._count = total
        return self.transform(obs)

    def transform(self, obs):
        """Normalize WITHOUT updating statistics (evaluation path)."""
        if self._mean is None or self._count < 2:
            return np.asarray(obs, np.float32)
        std = np.sqrt(self._m2 / (self._count - 1)) + 1e-8
        out = (np.asarray(obs, np.float64) - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {
            "count": self._count,
            "mean": None if self._mean is None else self._mean.copy(),
            "m2": None if self._m2 is None else self._m2.copy(),
        }

    def set_state(self, state):
        self._count = state["count"]
        self._mean = None if state["mean"] is None else np.array(state["mean"])
        self._m2 = None if state["m2"] is None else np.array(state["m2"])

    def merge_states(self, states: list):
        """Chan parallel-variance merge of per-worker accumulations."""
        count, mean, m2 = 0, None, None
        for st in states:
            if not st or st["count"] == 0 or st["mean"] is None:
                continue
            if mean is None:
                count, mean, m2 = st["count"], np.array(st["mean"]), np.array(st["m2"])
                continue
            n2 = st["count"]
            delta = st["mean"] - mean
            total = count + n2
            mean = mean + delta * n2 / total
            m2 = m2 + st["m2"] + delta * delta * count * n2 / total
            count = total
        self._count, self._mean, self._m2 = count, mean, m2


class ClipActions(ActionConnector):
    def __init__(self, low, high):
        self.low, self.high = np.asarray(low), np.asarray(high)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class ConnectorPipeline:
    """Ordered list of connectors applied in sequence."""

    def __init__(self, connectors: list):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def transform(self, x):
        for c in self.connectors:
            x = c.transform(x) if hasattr(c, "transform") else c(x)
        return x

    def get_state(self):
        return [c.get_state() if isinstance(c, AgentConnector) else None for c in self.connectors]

    def set_state(self, states):
        for c, st in zip(self.connectors, states):
            if isinstance(c, AgentConnector) and st is not None:
                c.set_state(st)
