"""Connector implementations (reference: rllib/connectors/connector.py:320
``ConnectorPipeline``, agent/pipeline.py:21 ``AgentConnectorPipeline``,
action/pipeline.py, agent/{mean_std_filter,clip,flatten,view_requirement}.py,
action/{clip,normalize}.py).

Agent connectors shape observation batches on the way INTO the policy;
action connectors shape sampled actions on the way OUT to the env. Both
compose into serializable pipelines used by rollout AND eval workers
(evaluation/rollout_worker.py builds one of each per worker)."""

from __future__ import annotations

import numpy as np


class AgentConnector:
    """obs batch [N, ...] -> obs batch. Override __call__ (+ state hooks for
    stateful connectors)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, obs: np.ndarray) -> np.ndarray:
        """Apply WITHOUT updating learned statistics (evaluation path).
        Temporal-context connectors (frame stacking) still advance their
        buffers here — episode context is not a learned statistic."""
        return self(obs)

    def peek(self, obs: np.ndarray) -> np.ndarray:
        """Apply with NO state change at all — not even temporal buffers.
        Used for out-of-band forwards over an observation the stepping loop
        will shape again (bootstrap values at fragment boundaries), which
        must not double-push frames."""
        return self.transform(obs)

    def on_episode_done(self, done_mask) -> None:
        """Per-slot episode boundary hook (frame stacks reset here)."""

    # Stateful connectors override these; stateless return None / ignore.
    def get_state(self):
        return None

    def set_state(self, state):
        pass

    def merge_states(self, states: list):
        """Combine per-worker states (driver-side reduce)."""
        pass


class ActionConnector:
    def __call__(self, actions: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class ClipObservations(AgentConnector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class FlattenObservations(AgentConnector):
    def __call__(self, obs):
        return np.asarray(obs).reshape(len(obs), -1)


class MeanStdFilter(AgentConnector):
    """Running per-feature normalization (reference:
    rllib/utils/filter.py MeanStdFilter as an agent connector): Welford
    accumulation per worker, merged across workers with the Chan formula when
    weights sync."""

    def __init__(self, clip: float = 10.0):
        self.clip = clip
        self._count = 0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        n = obs.shape[0]
        if n:
            # Vectorized batch statistics folded in with the Chan formula —
            # this runs on every env step, a per-row Python loop would
            # dominate rollout cost.
            b_mean = obs.mean(axis=0)
            b_m2 = ((obs - b_mean) ** 2).sum(axis=0)
            if self._mean is None:
                self._count, self._mean, self._m2 = n, b_mean, b_m2
            else:
                total = self._count + n
                delta = b_mean - self._mean
                self._mean = self._mean + delta * n / total
                self._m2 = self._m2 + b_m2 + delta * delta * self._count * n / total
                self._count = total
        return self.transform(obs)

    def transform(self, obs):
        """Normalize WITHOUT updating statistics (evaluation path)."""
        if self._mean is None or self._count < 2:
            return np.asarray(obs, np.float32)
        std = np.sqrt(self._m2 / (self._count - 1)) + 1e-8
        out = (np.asarray(obs, np.float64) - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {
            "count": self._count,
            "mean": None if self._mean is None else self._mean.copy(),
            "m2": None if self._m2 is None else self._m2.copy(),
        }

    def set_state(self, state):
        self._count = state["count"]
        self._mean = None if state["mean"] is None else np.array(state["mean"])
        self._m2 = None if state["m2"] is None else np.array(state["m2"])

    def merge_states(self, states: list):
        """Chan parallel-variance merge of per-worker accumulations."""
        count, mean, m2 = 0, None, None
        for st in states:
            if not st or st["count"] == 0 or st["mean"] is None:
                continue
            if mean is None:
                count, mean, m2 = st["count"], np.array(st["mean"]), np.array(st["m2"])
                continue
            n2 = st["count"]
            delta = st["mean"] - mean
            total = count + n2
            mean = mean + delta * n2 / total
            m2 = m2 + st["m2"] + delta * delta * count * n2 / total
            count = total
        self._count, self._mean, self._m2 = count, mean, m2


class ObsPreprocessor(AgentConnector):
    """Arbitrary stateless observation preprocessing stage (reference:
    agent/obs_preproc.py ObsPreprocessorConnector). ``fn`` maps an obs batch
    to an obs batch and must be picklable (it ships to the workers)."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, obs):
        return self.fn(obs)


class FrameStack(AgentConnector):
    """Stack the last ``num_frames`` observations per env slot along the
    last axis (reference: frame-stacking via view requirements /
    trajectory view API). Stateful per EPISODE, not per dataset: buffers
    advance in both train and eval (transform == __call__ for temporal
    context), and ``on_episode_done`` re-seeds finished slots so frames
    never leak across episodes — the first obs of a new episode is
    repeated ``num_frames`` times, the standard Atari convention."""

    def __init__(self, num_frames: int = 4):
        if num_frames < 1:
            raise ValueError("num_frames must be >= 1")
        self.num_frames = num_frames
        self._frames: np.ndarray | None = None  # [N, k, ...feature]
        self._reseed: np.ndarray | None = None  # slots to re-seed next call

    def _advanced(self, obs):
        """(frames, reseed) as they would be after pushing ``obs``."""
        obs = np.asarray(obs)
        n = obs.shape[0]
        if self._frames is None or self._frames.shape[0] != n:
            return np.repeat(obs[:, None], self.num_frames, axis=1), np.zeros(n, bool)
        frames = np.roll(self._frames, -1, axis=1)
        frames[:, -1] = obs
        reseed = self._reseed.copy()
        if reseed.any():
            idx = np.where(reseed)[0]
            frames[idx] = obs[idx][:, None]
            reseed[:] = False
        return frames, reseed

    @staticmethod
    def _stacked(frames):
        # [N, k, ...F] -> [N, ...F*k] on the last axis
        return np.concatenate(list(frames.transpose(1, 0, *range(2, frames.ndim))), axis=-1)

    def __call__(self, obs):
        self._frames, self._reseed = self._advanced(obs)
        return self._stacked(self._frames)

    def peek(self, obs):
        frames, _ = self._advanced(obs)
        return self._stacked(frames)

    def on_episode_done(self, done_mask):
        if self._reseed is not None:
            self._reseed |= np.asarray(done_mask, dtype=bool)

    def get_state(self):
        return {
            "frames": None if self._frames is None else self._frames.copy(),
            "reseed": None if self._reseed is None else self._reseed.copy(),
        }

    def set_state(self, state):
        self._frames = None if state["frames"] is None else np.array(state["frames"])
        self._reseed = None if state["reseed"] is None else np.array(state["reseed"])


class ViewRequirementConnector(AgentConnector):
    """Coerce the observation batch to the policy's declared view
    (reference: agent/view_requirement.py ViewRequirementAgentConnector):
    cast to ``dtype``, optionally flatten features, and VALIDATE the final
    feature size against the module spec's input dim — a shape mismatch
    fails here with the pipeline's name attached instead of deep inside a
    jitted forward."""

    def __init__(self, input_dim: int | None = None, flatten: bool = True, dtype=np.float32):
        self.input_dim = input_dim
        self.flatten = flatten
        self.dtype = dtype

    def __call__(self, obs):
        obs = np.asarray(obs, dtype=self.dtype)
        if self.flatten and obs.ndim > 2:
            obs = obs.reshape(obs.shape[0], -1)
        if self.input_dim is not None and obs.shape[-1] != self.input_dim:
            raise ValueError(
                f"view requirement mismatch: policy expects feature dim "
                f"{self.input_dim}, connector output has {obs.shape[-1]}"
            )
        return obs


class ClipActions(ActionConnector):
    def __init__(self, low, high):
        self.low, self.high = np.asarray(low), np.asarray(high)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class UnsquashActions(ActionConnector):
    """Map policy outputs in [-1, 1] to the env's Box bounds (reference:
    action/normalize.py NormalizeActionsConnector / unsquash_action): the
    affine stretch of tanh-squashed gaussian samples."""

    def __init__(self, low, high):
        self.low, self.high = np.asarray(low), np.asarray(high)

    def __call__(self, actions):
        a = np.clip(actions, -1.0, 1.0)
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)


class ConvertToNumpy(ActionConnector):
    """Device arrays -> host numpy before the env sees them (reference:
    action/pipeline.py ConvertToNumpyConnector)."""

    def __call__(self, actions):
        return np.asarray(actions)


class ConnectorPipeline:
    """Ordered list of connectors applied in sequence (reference:
    connectors/connector.py:320). Mutable composition (append/prepend/
    insert/remove by class name) + whole-pipeline state and serialization
    round-trips."""

    def __init__(self, connectors: list):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def transform(self, x):
        for c in self.connectors:
            x = c.transform(x) if hasattr(c, "transform") else c(x)
        return x

    def peek(self, x):
        for c in self.connectors:
            x = c.peek(x) if hasattr(c, "peek") else c(x)
        return x

    def on_episode_done(self, done_mask):
        for c in self.connectors:
            if hasattr(c, "on_episode_done"):
                c.on_episode_done(done_mask)

    # -- composition ---------------------------------------------------------

    def append(self, connector):
        self.connectors.append(connector)
        return self

    def prepend(self, connector):
        self.connectors.insert(0, connector)
        return self

    def _index_of(self, name: str) -> int:
        for i, c in enumerate(self.connectors):
            if type(c).__name__ == name:
                return i
        raise ValueError(f"no connector named {name!r} in {self}")

    def insert_before(self, name: str, connector):
        self.connectors.insert(self._index_of(name), connector)
        return self

    def insert_after(self, name: str, connector):
        self.connectors.insert(self._index_of(name) + 1, connector)
        return self

    def remove(self, name: str):
        del self.connectors[self._index_of(name)]
        return self

    def __repr__(self):
        inner = ", ".join(type(c).__name__ for c in self.connectors)
        return f"{type(self).__name__}([{inner}])"

    # -- state & serialization ----------------------------------------------

    def get_state(self):
        return [c.get_state() if hasattr(c, "get_state") else None for c in self.connectors]

    def set_state(self, states):
        for c, st in zip(self.connectors, states):
            if st is not None and hasattr(c, "set_state"):
                c.set_state(st)

    def serialize(self) -> bytes:
        """Structure AND state in one blob: a deserialized pipeline resumes
        exactly (filters keep their running statistics, frame stacks their
        buffers). Reference: Connector.to_state/from_state."""
        import cloudpickle

        return cloudpickle.dumps({"connectors": self.connectors, "cls": type(self).__name__})

    @staticmethod
    def deserialize(blob: bytes) -> "ConnectorPipeline":
        import cloudpickle

        data = cloudpickle.loads(blob)
        cls = {c.__name__: c for c in (ConnectorPipeline, AgentConnectorPipeline, ActionConnectorPipeline)}[
            data["cls"]
        ]
        return cls(data["connectors"])


class AgentConnectorPipeline(ConnectorPipeline):
    """Observation-side pipeline (reference: agent/pipeline.py:21)."""


class ActionConnectorPipeline(ConnectorPipeline):
    """Action-side pipeline (reference: action/pipeline.py)."""
