"""Connectors — observation/action transformation pipelines.

Analog of the reference's rllib/connectors/{agent,action}/: small composable
transforms between the environment and the policy. Agent connectors shape
raw observations into what the jitted module expects (normalization, clipping,
flattening); action connectors shape module outputs back for the env
(clipping/unsquashing). Stateful connectors (MeanStdFilter) carry running
statistics that sync across rollout workers with the weights — states ride
the same broadcast path, keeping everything mesh-friendly (pure arrays).
"""

from ray_tpu.rllib.connectors.connector import (  # noqa: F401
    ActionConnector,
    ActionConnectorPipeline,
    AgentConnector,
    AgentConnectorPipeline,
    ClipActions,
    ClipObservations,
    ConnectorPipeline,
    ConvertToNumpy,
    FlattenObservations,
    FrameStack,
    MeanStdFilter,
    ObsPreprocessor,
    UnsquashActions,
    ViewRequirementConnector,
)
