"""Model catalog (analog of reference rllib/models/catalog.py ModelCatalog).

The reference's catalog maps (obs space, action space, model config) onto a
framework network (FCNet / VisionNet / ...). Here the same decision produces
an RLModuleSpec — the pure-JAX module family in core/rl_module.py: flat
observations get the FCNet-style MLP torso, 3D image observations get the
VisionNet-style conv stack (default filters by input size, overridable via
``model_config["conv_filters"]``).
"""

from __future__ import annotations

from ray_tpu.rllib.core.rl_module import (  # noqa: F401
    RLModuleSpec,
    default_conv_filters,
)

MODEL_DEFAULTS: dict = {
    "fcnet_hiddens": (64, 64),
    "fcnet_activation": "tanh",
    "conv_filters": None,
}


class ModelCatalog:
    @staticmethod
    def get_model_spec(observation_space, action_space, model_config: dict | None = None) -> RLModuleSpec:
        cfg = {**MODEL_DEFAULTS, **(model_config or {})}
        spec = RLModuleSpec.from_spaces(
            observation_space,
            action_space,
            hiddens=tuple(cfg["fcnet_hiddens"]),
            conv_filters=cfg["conv_filters"],
        )
        if cfg["fcnet_activation"] != spec.activation:
            import dataclasses

            spec = dataclasses.replace(spec, activation=cfg["fcnet_activation"])
        return spec
