"""ray_tpu — a TPU-native distributed runtime + ML toolkit.

A brand-new framework with the capability set of the reference (Ray: core
task/actor/object runtime plus Train/Tune/Data/Serve/RLlib-class libraries),
designed around JAX/XLA/pjit/Pallas: TPU chips and ICI slices are first-class
schedulable resources, and the accelerator collective plane is gang-scheduled
actor groups materialising a ``jax.sharding.Mesh`` (XLA collectives over ICI)
instead of NCCL process groups.

Public API analog of python/ray/_private/worker.py:1106 (init), :2409 (get),
:2524 (put), :2587 (wait), :2919 (remote).
"""

from __future__ import annotations

import threading

from ray_tpu import exceptions  # noqa: F401
from ray_tpu.actor import ActorClass, ActorHandle, method  # noqa: F401
from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator  # noqa: F401
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

_init_lock = threading.Lock()
_global_node = None
# Set by the chained excepthook when an exception escapes the driver script;
# shutdown() (usually via atexit) then records the job as FAILED.
_uncaught_exception = False
_hooks_installed = False


def _install_driver_hooks():
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    import atexit
    import sys

    import threading as _threading

    prev_hook = sys.excepthook

    def _excepthook(tp, value, tb):
        global _uncaught_exception
        _uncaught_exception = True
        prev_hook(tp, value, tb)

    sys.excepthook = _excepthook

    prev_thread_hook = _threading.excepthook

    def _thread_excepthook(hook_args):
        global _uncaught_exception
        if hook_args.exc_type is not SystemExit:
            _uncaught_exception = True
        prev_thread_hook(hook_args)

    _threading.excepthook = _thread_excepthook
    # Known gap: `sys.exit(1)` raises SystemExit, which the interpreter
    # handles without calling sys.excepthook — such drivers are recorded
    # SUCCEEDED here; the job-submission layer (which sees the real exit
    # code) is authoritative for submitted jobs.
    atexit.register(shutdown)


def init(
    address=None,
    *,
    num_cpus: int | None = None,
    num_tpus: int | None = None,
    resources: dict | None = None,
    object_store_memory: int | None = None,
    namespace: str = "",
    labels: dict | None = None,
    runtime_env: dict | None = None,
    ignore_reinit_error: bool = False,
    _system_config: dict | None = None,
):
    """Start (or connect to) a cluster and attach this process as a driver."""
    global _global_node
    import os

    from ray_tpu._private import worker_context
    from ray_tpu._private.core_worker import DRIVER, CoreWorker
    from ray_tpu._private.node import Node

    # Honor RAY_TPU_JAX_CONFIG_PLATFORMS in the DRIVER too (workers apply
    # it in worker_main): a sitecustomize-pinned jax_platforms config BEATS
    # the JAX_PLATFORMS env var, so the pin must be re-applied here.
    from ray_tpu._private.jax_platform import apply_forced_jax_platforms

    apply_forced_jax_platforms()

    if address is None and os.environ.get("RAY_TPU_ADDRESS"):
        # Set by `ray_tpu job submit` driver subprocesses and operators —
        # mirrors the reference's RAY_ADDRESS behavior.
        address = os.environ["RAY_TPU_ADDRESS"]
    if isinstance(address, str) and address.startswith("ray_tpu://"):
        # Thin-client mode (reference: ray.init("ray://...") Ray Client).
        from ray_tpu.util.client import connect as _client_connect

        with _init_lock:
            if worker_context.get_core_worker_if_initialized() is not None:
                if ignore_reinit_error:
                    return worker_context.get_core_worker()
                raise RuntimeError(
                    "ray_tpu.init() called twice; pass ignore_reinit_error=True"
                )
            _client_connect(address, namespace=namespace)
        _install_driver_hooks()
        return worker_context.get_core_worker()
    if address == "auto":
        address = os.environ.get("RAY_TPU_ADDRESS")
        if address is None:
            try:
                with open("/tmp/ray_tpu/ray_current_cluster") as f:
                    import json as _json

                    info = _json.load(f)
                address = "%s:%d" % tuple(info["gcs_address"])
            except Exception:
                raise ConnectionError(
                    'init(address="auto") found no running cluster '
                    "(no RAY_TPU_ADDRESS and no /tmp/ray_tpu/ray_current_cluster)"
                ) from None

    with _init_lock:
        if worker_context.get_core_worker_if_initialized() is not None:
            if ignore_reinit_error:
                return worker_context.get_core_worker()
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")

        if address is None:
            node = Node(
                head=True,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
                object_store_memory=object_store_memory,
                labels=labels,
                _system_config=_system_config,
            )
            _global_node = node
            gcs_address = node.gcs_address
            raylet_address = node.raylet.address
            arena_name = node.raylet.arena_name
            node_id = node.raylet.node_id
            session_dir = node.session_dir
        else:
            # Connect to an existing cluster: find a raylet (prefer local host).
            from ray_tpu._private.rpc import RpcClient

            gcs_address = tuple(address) if not isinstance(address, str) else _parse_addr(address)
            gcs = RpcClient(gcs_address, label="gcs")
            nodes_resp = gcs.call("get_nodes")
            alive = [n for n in nodes_resp["nodes"].values() if n["state"] == "ALIVE"]
            if not alive:
                gcs.close()
                raise RuntimeError("no alive nodes in cluster")
            target = alive[0]
            raylet_address = tuple(target["address"])
            arena_name = target["arena_name"]
            node_id = target["node_id"]
            session_dir = "/tmp/ray_tpu/driver"
            gcs.close()

        cw = CoreWorker(
            mode=DRIVER,
            gcs_address=gcs_address,
            raylet_address=raylet_address,
            arena_name=arena_name,
            node_id=node_id,
            session_dir=session_dir,
            namespace=namespace,
            job_runtime_env=runtime_env,
        )
        worker_context.set_core_worker(cw)
    from ray_tpu.util import tracing as _tracing

    if _tracing.tracing_enabled():
        _tracing._publish_flag_if_connected()
    _install_driver_hooks()
    return cw


def _parse_addr(address: str) -> tuple:
    host, port = address.rsplit(":", 1)
    return (host, int(port))


def shutdown():
    global _global_node
    from ray_tpu._private import worker_context

    with _init_lock:
        cw = worker_context.get_core_worker_if_initialized()
        if cw is not None:
            cw.shutdown(job_state="FAILED" if _uncaught_exception else "SUCCEEDED")
            worker_context.set_core_worker(None)
        if _global_node is not None:
            _global_node.stop()
            _global_node = None


def is_initialized() -> bool:
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker_if_initialized() is not None


def remote(*args, **kwargs):
    """``@ray_tpu.remote`` decorator for functions and classes."""

    def make(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **kwargs)
        return RemoteFunction(obj, **kwargs)

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return make


def get(refs, *, timeout: float | None = None):
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker().get(refs, timeout=timeout)


def put(value, *, tensor_transport: str | None = None) -> ObjectRef:
    """Store ``value`` and return an ObjectRef.

    ``tensor_transport="collective"`` keeps a ``jax.Array`` resident on this
    process's devices (experimental/device_object/): only a small descriptor
    enters the store, and consumers resolve it out of band — same-process
    gets hand back the live array, same-mesh actors transfer over a
    ``util.collective`` group, and everything else falls back to the
    host-shm path transparently.
    """
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker().put(value, tensor_transport=tensor_transport)


def wait(refs, *, num_returns: int = 1, timeout: float | None = None, fetch_local: bool = True):
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker().wait(
        refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def cancel(object_ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Cancel the task producing ``object_ref`` (reference: ``ray.cancel``,
    python/ray/_private/worker.py:2773 / core_worker.cc CancelTask).

    Best-effort and asynchronous: pending tasks are dequeued (at the raylet,
    the owner's lease staging, or the actor's call queue), a running task is
    interrupted with :class:`~ray_tpu.exceptions.TaskCancelledError` at its
    next Python bytecode boundary, and ``force=True`` kills the executing
    worker process outright. ``recursive=True`` also cancels the task's
    children. ``ray_tpu.get`` on the task's returns raises
    ``TaskCancelledError`` once the cancel lands; a task that already
    finished is unaffected. ``force=True`` on an actor task raises
    ``ValueError`` (kill the actor instead), matching the reference.
    """
    from ray_tpu._private import worker_context

    if not isinstance(object_ref, ObjectRef):
        raise TypeError(
            f"ray_tpu.cancel() expects an ObjectRef, got {type(object_ref).__name__}"
        )
    worker_context.get_core_worker().cancel(object_ref, force=force, recursive=recursive)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    # Bounded AND best-effort: a wedged GCS/worker must not block the
    # caller forever (a Tune controller hung here for 90 minutes when a
    # recycled worker port swallowed the GCS's kill_self relay), and kill
    # has never raised on slow delivery — swallow the timeout, the GCS
    # actor reaper finishes the job.
    import logging

    from ray_tpu._private import rpc as _rpc

    try:
        # retries=0: acall retries TimeoutError internally, which would turn
        # this into a ~4x10s worst case; a single attempt keeps the total
        # bound at 10s, and a dropped kill is finished by the reaper anyway.
        cw.gcs.call(
            "kill_actor",
            {"actor_id": actor.actor_id, "no_restart": no_restart},
            timeout=10,
            retries=0,
        )
    except (TimeoutError, _rpc.ConnectionLost):
        logging.getLogger(__name__).warning(
            "kill(%s) did not confirm within the timeout; actor teardown "
            "continues asynchronously", actor.actor_id[:8],
        )


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    resp = cw.gcs.call("get_actor", {"name": name, "namespace": namespace or cw.namespace})
    if not resp.get("found"):
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(resp["info"]["actor_id"], name=name)


def nodes() -> list:
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    return list(cw.gcs.call("get_nodes")["nodes"].values())


def cluster_resources() -> dict:
    from ray_tpu._private.state import GlobalState

    return GlobalState().cluster_resources()


def available_resources() -> dict:
    from ray_tpu._private.state import GlobalState

    return GlobalState().available_resources()


def timeline(filename: str | None = None) -> list:
    """Chrome-trace timeline of executed tasks (reference: ``ray.timeline``,
    python/ray/_private/state.py:831); open the dump in chrome://tracing."""
    from ray_tpu._private.state import timeline as _timeline

    return _timeline(filename)


def get_runtime_context():
    from ray_tpu.runtime_context import get_runtime_context as _grc

    return _grc()


__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
]
