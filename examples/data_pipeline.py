"""Streaming data pipeline: read -> transform -> shuffle -> device batches.

Blocks flow through the bounded-memory streaming executor; iter_jax_batches
double-buffers host->device transfer for the training loop.

Run: python examples/data_pipeline.py
"""


def main():
    import numpy as np

    import ray_tpu
    from ray_tpu import data

    ray_tpu.init(num_cpus=2)
    ds = (
        data.range(10_000)
        .map(lambda row: {"id": row["id"], "x": float(row["id"]) / 10_000})
        .filter(lambda row: row["id"] % 3 != 0)
        .random_shuffle(seed=7)
    )
    total = 0
    for batch in ds.iter_batches(batch_size=1024):
        total += len(batch["id"])
    print("rows after filter:", total)
    print("per-op stats:\n", ds.stats())

    # Device-ready batches (on TPU these land in HBM, double-buffered).
    ds2 = data.from_items([{"x": np.ones(8, np.float32) * i} for i in range(64)])
    for jb in ds2.iter_jax_batches(batch_size=16):
        assert jb["x"].shape == (16, 8)
    print("jax batches ok")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
