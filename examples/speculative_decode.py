"""Speculative decoding: a small draft model accelerates the big one.

Greedy speculative decoding is EXACT — identical tokens to vanilla
generation — while spending fewer target-model passes the more often the
draft agrees. An UNTRAINED random draft agrees almost never (~31 passes
for 32 tokens); the ceiling demo below uses the target as its own draft,
where every proposal is accepted: 32 tokens in ~7 target passes at k=4.

Run: python examples/speculative_decode.py
"""


def main():
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.models import (
        TransformerConfig,
        generate,
        init_params,
        speculative_generate,
    )

    target_cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=4, n_heads=4, n_kv_heads=2,
        d_ff=256, dtype=jnp.float32, remat=False,
    )
    draft_cfg = TransformerConfig(
        vocab_size=512, d_model=64, n_layers=1, n_heads=4, n_kv_heads=2,
        d_ff=128, dtype=jnp.float32, remat=False,
    )
    target = init_params(jax.random.PRNGKey(0), target_cfg)
    draft = init_params(jax.random.PRNGKey(7), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 512)

    vanilla = np.asarray(generate(target, prompt, target_cfg, max_new_tokens=32))
    spec, rounds = speculative_generate(
        target, draft, prompt, target_cfg, draft_cfg, max_new_tokens=32, k=4
    )
    assert np.array_equal(np.asarray(spec), vanilla), "speculative must be exact"
    print(f"untrained draft: {int(rounds)} target passes for 32 tokens (vanilla: 32)")

    # A perfect draft (the target itself) shows the ceiling.
    _, rounds2 = speculative_generate(
        target, target, prompt, target_cfg, target_cfg, max_new_tokens=32, k=4
    )
    print(f"perfect draft:  {int(rounds2)} target passes for 32 tokens")
    print("exact-output speculative decoding ok")


if __name__ == "__main__":
    main()
