"""Offline RL: behavior-clone a policy from logged episodes (MARWIL/BC).

Generates a small logged dataset from a scripted expert, trains MARWIL on
it with no environment interaction, then probes the learned rule.

Run: python examples/rllib_offline.py
"""


def main():
    import tempfile

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.rllib import MARWILConfig
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.policy.sample_batch import SampleBatch

    ray_tpu.init(num_cpus=2)

    # Log expert data: action = 1 iff obs[0] > 0, reward 1 for following it.
    rng = np.random.default_rng(0)
    n = 2000
    obs = rng.uniform(-1, 1, size=(n, 4)).astype(np.float32)
    actions = (obs[:, 0] > 0).astype(np.int64)
    with tempfile.TemporaryDirectory() as log_dir:
        w = JsonWriter(log_dir)
        w.write(SampleBatch({
            "obs": obs, "actions": actions,
            "rewards": np.ones(n, np.float32), "dones": np.ones(n, bool),
        }))
        w.close()

        cfg = (
            MARWILConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0)
            .training(lr=5e-3, train_batch_size=512, beta=1.0)
            .debugging(seed=0)
        )
        cfg.offline_data(input_=log_dir)
        algo = cfg.build()  # build() constructs AND sets up the algorithm
        try:
            for _ in range(40):
                algo.step()
            probe = rng.uniform(-1, 1, size=(20, 4)).astype(np.float32)
            agree = sum(
                int(algo.compute_single_action(o) == int(o[0] > 0)) for o in probe
            )
            print(f"expert agreement: {agree}/20")
        finally:
            algo.cleanup()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
