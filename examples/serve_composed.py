"""Composed serving: a deployment graph + multiple routed apps + raw ASGI.

Three Serve idioms in one cluster: a Gateway composed of nested bound
deployments (Gateway.bind(Doubler.bind(), Squarer.bind())) fanning each
request out concurrently, a second independently-routed app, and
serve.ingress mounting an ASGI callable. (For a single driver deployment
dispatching sub-routes over one graph, see ray_tpu.serve.DAGDriver.)

Run: python examples/serve_composed.py
"""

import json
import urllib.request


def main():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)  # four deployment replicas + controller + proxy
    serve.start()

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Squarer:
        def __call__(self, x):
            return x * x

    @serve.deployment
    class Gateway:
        """Graph node: fans a request out to bound sub-deployments."""

        def __init__(self, doubler, squarer):
            self.doubler = doubler
            self.squarer = squarer

        def __call__(self, request):
            v = request.json()["v"]
            # Issue both calls BEFORE getting either: the children run
            # concurrently, so request latency is the max, not the sum.
            d_ref = self.doubler.remote(v)
            s_ref = self.squarer.remote(v)
            return {"double": ray_tpu.get(d_ref), "square": ray_tpu.get(s_ref)}

    serve.run(Gateway.bind(Doubler.bind(), Squarer.bind()), route_prefix="/math")

    async def echo_asgi(scope, receive, send):
        if scope["type"] != "http":
            return
        await receive()
        body = json.dumps({"path": scope["path"], "mount": scope["root_path"]}).encode()
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"application/json")]})
        await send({"type": "http.response.body", "body": body, "more_body": False})

    @serve.deployment
    @serve.ingress(echo_asgi)
    class Echo:
        pass

    serve.run(Echo.bind(), route_prefix="/echo", name="echo")

    host, port = serve.http_address()

    def post(path, payload):
        req = urllib.request.Request(
            f"http://{host}:{port}{path}", data=json.dumps(payload).encode()
        )
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    print("math:", post("/math", {"v": 7}))
    print("echo:", json.loads(urllib.request.urlopen(
        f"http://{host}:{port}/echo/sub", timeout=30).read()))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
