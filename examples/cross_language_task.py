"""Run a C++ function on the task plane (ray_tpu.cross_language).

Compiles the example kernels, then calls them as remote tasks: args cross
as msgpack, results are stored language-agnostically (the C++ client can
read them back without Python).

Run: python examples/cross_language_task.py
"""

import os
import subprocess
import tempfile


def main():
    import ray_tpu
    from ray_tpu.cross_language import cpp_function

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    so = os.path.join(tempfile.mkdtemp(), "libxlang_kernels.so")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", so,
         os.path.join(repo, "cpp", "xlang_kernels.cc")],
        check=True,
    )

    ray_tpu.init(num_cpus=2)
    sum_fn = cpp_function("xlang_sum", so)
    wc = cpp_function("xlang_wordcount", so)
    print("sum:", ray_tpu.get(sum_fn.remote([1, 2, 3, 4.5])))
    print("wordcount:", ray_tpu.get(wc.remote("to be or not to be")))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
