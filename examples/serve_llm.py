"""Serve a jit-compiled LM with KV-cache decode behind HTTP.

Two flavors:

- /generate — the simple one-batch path: POST {"tokens": [...]}, buffered
  JSON reply; batched handle calls share the one compiled prefill/decode.
- /chat — continuous batching (serve.llm.LLMDeployment): paged KV cache,
  slot-level admission mid-decode, prefix-cache reuse for shared system
  prompts, per-token SSE streaming; requests carrying the system prompt's
  `serve_prefix_hash` header route to the replica holding its KV blocks.

On TPU the replica pins a chip (@serve.deployment(num_tpus=1)).

Run: python examples/serve_llm.py
"""

import json
import urllib.request


def main():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=2)
    serve.start()

    @serve.deployment
    class LM:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.transformer import TransformerConfig, init_params

            self.cfg = TransformerConfig(
                vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
                d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
            )
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, request):
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models.generate import generate

            body = request.json()
            out = generate(
                self.params,
                jnp.asarray([body["tokens"]], jnp.int32),
                self.cfg,
                max_new_tokens=int(body.get("max_new_tokens", 8)),
                temperature=float(body.get("temperature", 0.0)),
            )
            return {"tokens": np.asarray(out)[0].tolist()}

    serve.run(LM.bind(), route_prefix="/generate")
    host, port = serve.http_address()
    req = urllib.request.Request(
        f"http://{host}:{port}/generate",
        data=json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 6}).encode(),
    )
    print("generated:", json.loads(urllib.request.urlopen(req, timeout=60).read()))

    # --- continuous batching + SSE streaming (serve.llm) ---
    from ray_tpu.serve.llm import LLMDeployment, prefix_route_hint

    chat = serve.deployment(name="Chat")(LLMDeployment).bind(
        dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
             d_ff=128, max_seq_len=64, dtype="float32", remat=False),
        engine_config=dict(num_slots=4, block_size=8, max_model_len=64,
                           prefill_chunk=8),
    )
    serve.run(chat, route_prefix="/chat")
    system = list(range(1, 9))  # one full shared block
    req = urllib.request.Request(
        f"http://{host}:{port}/chat",
        data=json.dumps({"tokens": system + [42], "max_new_tokens": 8}).encode(),
        headers={"serve_prefix_hash": prefix_route_hint(system, 8)},
    )
    resp = urllib.request.urlopen(req, timeout=120)
    toks = []
    for event in resp.read().split(b"\n\n"):
        if event.startswith(b"data: ") and event != b"data: [DONE]":
            toks.append(json.loads(event[6:])["token"])
    print("streamed:", toks)
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
