"""Train PPO on CartPole with rollout workers + a jitted learner.

Run: python examples/rllib_ppo.py [iters]
"""

import sys


def main(iters: int = 3):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    ray_tpu.init(num_cpus=4)
    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=1)
        .training(lr=5e-4, train_batch_size=512)
        .evaluation(evaluation_interval=2, evaluation_duration=3)
        .debugging(seed=0)
    )
    algo = cfg.build()  # build() constructs AND sets up the algorithm
    try:
        for i in range(iters):
            m = algo.step()
            print(
                f"iter {i}: reward={m.get('episode_reward_mean'):.1f} "
                f"eval={m.get('evaluation/episode_reward_mean', float('nan'))}"
            )
    finally:
        algo.cleanup()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
