"""Data-parallel LM training with JaxTrainer.

The flagship path: driver builds a trainer; each worker claims its chips,
joins the collective mesh, and runs the jitted train step (fused LM loss,
Pallas flash attention on TPU). Scale with ScalingConfig(num_workers=N,
use_tpu=True) — the same script drives 1 chip or a pod slice.

Run: python examples/train_transformer.py [steps]
"""

import sys


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.air import session
    from ray_tpu.models.transformer import TransformerConfig, init_params, make_train_step

    cfg = TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=256, max_seq_len=128,
        dtype=jnp.bfloat16 if jax.default_backend() in ("tpu", "axon") else jnp.float32,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0, cfg.vocab_size)
    for i in range(config.get("steps", 5)):
        params, opt_state, loss = step(params, opt_state, {"tokens": tokens})
        session.report({"step": i, "loss": float(loss)})


def main(steps: int = 5):
    import ray_tpu
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    ray_tpu.init(num_cpus=2)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"steps": steps},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path="/tmp/rtpu_example_train"),
    )
    result = trainer.fit()
    print("final loss:", result.metrics.get("loss"))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 5)
