"""Hyperparameter sweep with Tune: ASHA early stopping over trial actors.

Run: python examples/tune_hyperparams.py
"""


def objective(config):
    from ray_tpu import tune

    lr, width = config["lr"], config["width"]
    for step in range(20):
        # Synthetic objective with a known optimum at lr=0.1, width=32.
        score = 1.0 / (1 + abs(lr - 0.1) * 10 + abs(width - 32) / 32) * (step + 1) / 20
        tune.report({"score": score})


def main():
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.tune.schedulers import ASHAScheduler

    ray_tpu.init(num_cpus=4)
    tuner = tune.Tuner(
        objective,
        param_space={
            "lr": tune.loguniform(1e-3, 1.0),
            "width": tune.choice([8, 16, 32, 64]),
        },
        tune_config=tune.TuneConfig(
            num_samples=8,
            metric="score",
            mode="max",
            scheduler=ASHAScheduler(max_t=20, grace_period=4),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result()
    print("best config:", best.config, "score:", best.metrics["score"])
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
