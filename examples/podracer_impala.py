"""Podracer learner/sampler topology on IMPALA (arXiv:2104.06272).

The Podracer shape: ONE learner holding params on its device mesh, a fleet
of CPU env actors feeding rollouts through the object store, and
per-iteration weight sync as ONE device-object group broadcast instead of
K per-worker pytree ships:

- ``learner_mesh=True``   — the learner's jitted update runs on a pjit mesh
  over every local device (batch sharded on the data axis, params
  replicated); on a 1-chip host the mesh is trivial, on a TPU host the
  same config uses all chips.
- ``weight_sync="device_broadcast"`` — the learner packs its params into
  one flat device-resident vector, seals ONE descriptor, and
  ``device_object.broadcast`` fans the payload to every sampler's direct
  mailbox with one group operation (cpu mailbox backend here; the
  tpu backend maps the same seam to an ICI broadcast on hardware).

Run: python examples/podracer_impala.py [iters]
"""

import sys


def main(iters: int = 3):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig

    ray_tpu.init(num_cpus=6)
    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=3, rollout_fragment_length=64)
        .training(
            lr=5e-4,
            train_batch_size=384,
            weight_sync="device_broadcast",
            learner_mesh=True,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        for i in range(iters):
            m = algo.step()
            print(
                f"iter {i}: reward={m.get('episode_reward_mean'):.1f} "
                f"loss={m.get('total_loss', float('nan')):.3f}"
            )
        from ray_tpu.util.collective.p2p import COLL

        print(
            f"group broadcasts fanned out by the learner/driver: "
            f"{COLL.bcast_sends} ({COLL.bcast_send_bytes / 1e6:.1f} MB delivered)"
        )
    finally:
        algo.cleanup()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
