"""A fully NATIVE driver→worker round trip (the N22 C++ user API).

Boots a cluster from Python (the daemons), then hands the raylet address
to a compiled C++ program built on cpp/ray_tpu_api.h — the reference's
`ray::Task(...).Remote()` / `ray::Get()` shape. The C++ driver submits
language="cpp" tasks, the raylet spawns the C++ worker runtime
(cpp/ray_tpu_worker.cc) to execute them, and results are pushed back to
the driver's own owner-side server: once the cluster is up, neither the
driver nor the worker runs any Python.

Run: python examples/cpp_native_driver.py
"""

import os
import subprocess
import tempfile


def main():
    import ray_tpu
    from ray_tpu._private.cpp_worker import cpp_worker_binary
    from ray_tpu._private.worker_context import get_core_worker

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    build = tempfile.mkdtemp()
    so = os.path.join(build, "libxlang_kernels.so")
    driver = os.path.join(build, "api_example")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", so,
         os.path.join(repo, "cpp", "xlang_kernels.cc")],
        check=True,
    )
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", driver,
         os.path.join(repo, "cpp", "api_example.cc"), "-lpthread"],
        check=True,
    )
    # Pre-build the native worker so the first task runs in it (otherwise
    # the pool serves a Python fallback while g++ runs in the background).
    assert cpp_worker_binary() is not None

    ray_tpu.init(num_cpus=2)
    host, port = get_core_worker().raylet.address
    out = subprocess.run(
        [driver, host, str(port), so], capture_output=True, text=True, check=True
    )
    print(out.stdout, end="")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
