"""Headline benchmark.

Measures flagship-transformer training throughput through the full framework
path (JaxTrainer -> worker actor -> collective-plane mesh -> jitted train
step) against a pure-JAX loop in the same process. vs_baseline is the
framework/pure ratio — the BASELINE.md target is >= 0.90 (framework overhead
<= 10%); >1.0 is noise-level win.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

On a TPU host the worker claims the chip (the driver process never imports
jax — by design, see _private/node.py); on CPU it runs a scaled-down config.
"""

from __future__ import annotations

import json
import os
import sys
import time


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.air import session
    from ray_tpu.models.transformer import TransformerConfig, init_params, make_train_step

    on_tpu = jax.default_backend() in ("tpu", "axon")
    # A/B knobs (defaults = the measured-best config; see PERF_NOTES.md):
    #   BENCH_FUSED=0        unfused LM loss (materialized logits)
    #   BENCH_UNROLL=N       layer-scan unroll factor
    #   BENCH_LAG=N          framework-loop metrics lag depth
    #   BENCH_NO_ASYNC_COPY=1  skip per-step copy_to_host_async
    #   BENCH_STEPS=N        timed steps
    fused = os.environ.get("BENCH_FUSED", "1") != "0"
    unroll = int(os.environ.get("BENCH_UNROLL", "8"))
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32000,
            d_model=1024,
            n_layers=8,
            n_heads=16,
            n_kv_heads=16,
            d_ff=2816,
            max_seq_len=1024,
            dtype=jnp.bfloat16,
            remat=False,
            # Single chip, no pp: full unroll lets XLA schedule across layer
            # boundaries (+12% measured on v5e — see TransformerConfig).
            scan_unroll=unroll,
            fused_loss=fused,
        )
        batch, seq, steps = 8, 1024, int(os.environ.get("BENCH_STEPS", "30"))
    else:
        cfg = TransformerConfig(
            vocab_size=1024,
            d_model=128,
            n_layers=2,
            n_heads=4,
            n_kv_heads=4,
            d_ff=256,
            max_seq_len=128,
            dtype=jnp.float32,
            remat=False,
            fused_loss=fused,
            scan_unroll=min(unroll, 2),
        )
        batch, seq, steps = 4, 128, int(os.environ.get("BENCH_STEPS", "10"))

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_arr = {"tokens": tokens}

    # Warmup/compile. Timed regions end with float(loss) — a forced host
    # transfer — rather than block_until_ready: under the axon remote-TPU
    # tunnel block_until_ready can return before the dispatch chain drains
    # (round-1 bench measured a 3 ms "raw" loop because of this), while a
    # host transfer of the last step's loss cannot complete early.
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, batch_arr)
    float(loss)

    # Pure-JAX baseline: tight loop, no framework interaction.
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, batch_arr)
    float(loss)
    raw_s = time.perf_counter() - t0

    # Framework path: same loop, reporting through the air session every
    # step. Losses are copied host-side asynchronously and fetched K steps
    # LATE: a synchronous float() of a recent step pays the device->host
    # round trip per iteration (under the axon remote-TPU tunnel that RTT
    # is milliseconds, and it throttles dispatch depth), while a K-deep lag
    # gives every async copy K full steps to land before it is read — the
    # shape of any well-written async metrics logger. Every loss is still
    # reported, in order.
    import collections

    lag = int(os.environ.get("BENCH_LAG", "4"))
    async_copy = os.environ.get("BENCH_NO_ASYNC_COPY", "0") != "1"
    pending: collections.deque = collections.deque()
    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, batch_arr)
        if async_copy:
            try:
                loss.copy_to_host_async()
            except Exception:
                pass
        pending.append((i, loss))
        if len(pending) > lag:
            pi, pl = pending.popleft()
            session.report({"step": pi, "loss": float(pl)})
    while pending:
        pi, pl = pending.popleft()
        session.report({"step": pi, "loss": float(pl)})
    fw_s = time.perf_counter() - t0

    tok = batch * seq * steps
    session.report(
        {
            "final": True,
            "tokens_per_sec_framework": tok / fw_s,
            "tokens_per_sec_raw": tok / raw_s,
            "ratio": raw_s / fw_s if fw_s > 0 else 0.0,
            "backend": jax.default_backend(),
            "n_params": n_params,
            "device_kind": jax.devices()[0].device_kind,
        }
    )


def main():
    os.environ.setdefault("RAY_TPU_NUM_TPUS", os.environ.get("BENCH_NUM_TPUS", ""))
    import ray_tpu
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    explicit = os.environ.get("RAY_TPU_NUM_TPUS")
    if explicit not in (None, ""):
        n_tpus = int(explicit)
    else:
        n_tpus = 0
        try:
            from ray_tpu._private.node import detect_tpu_chips

            n_tpus = detect_tpu_chips()
        except Exception:
            pass
        # Under the axon tunnel there is one chip but no /dev/accel*; assume
        # TPU when the axon plugin env is present.
        if n_tpus == 0 and os.environ.get("PALLAS_AXON_POOL_IPS"):
            n_tpus = 1
            os.environ["RAY_TPU_NUM_TPUS"] = "1"

    ray_tpu.init(num_cpus=4, num_tpus=n_tpus or None)
    use_tpu = n_tpus > 0
    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(
            num_workers=1, use_tpu=use_tpu, tpu_per_worker=1 if use_tpu else 0
        ),
        run_config=RunConfig(storage_path="/tmp/rtpu_bench"),
    )
    result = trainer.fit()
    m = result.metrics
    ray_tpu.shutdown()
    backend = m.get("backend", "cpu")
    suffix = "_tpu" if backend in ("tpu", "axon") else "_cpu"
    out = {
        "metric": "flagship_transformer_train_tokens_per_sec" + suffix,
        "value": round(m["tokens_per_sec_framework"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(m["ratio"], 4),
    }
    if suffix == "_tpu":
        kind = m.get("device_kind", "")
        out["tokens_per_sec_raw"] = round(m["tokens_per_sec_raw"], 1)
        out["device_kind"] = kind
        out["n_params"] = m.get("n_params", 0)
        peak = _peak_bf16_flops(kind)
        if peak and m.get("n_params"):
            # Model FLOPs utilization: 6 * params * tokens/s over chip peak.
            out["mfu"] = round(6 * m["n_params"] * m["tokens_per_sec_framework"] / peak, 4)
    print(json.dumps(out))


def _peak_bf16_flops(device_kind: str) -> float:
    """Per-chip peak bf16 FLOPs/s by device kind (public spec sheets)."""
    kind = device_kind.lower()
    for key, peak in (
        ("v5 lite", 197e12),
        ("v5e", 197e12),
        ("v5p", 459e12),
        ("v6", 918e12),
        ("v4", 275e12),
        ("v3", 123e12),
        ("v2", 46e12),
    ):
        if key in kind:
            return peak
    return 0.0


if __name__ == "__main__":
    main()
