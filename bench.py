"""Headline benchmark.

Measures flagship-transformer training throughput through the full framework
path (JaxTrainer -> worker actor -> collective-plane mesh -> jitted train
step) against a pure-JAX loop in the same process. vs_baseline is the
framework/pure ratio — the BASELINE.md target is >= 0.90 (framework overhead
<= 10%); >1.0 is noise-level win.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

On a TPU host the worker claims the chip (the driver process never imports
jax — by design, see _private/node.py); on CPU it runs a scaled-down config.
"""

from __future__ import annotations

import json
import os
import sys
import time


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.air import session
    from ray_tpu.models.transformer import TransformerConfig, init_params, make_train_step

    on_tpu = jax.default_backend() in ("tpu", "axon")
    # A/B knobs (defaults = the measured-best config; see PERF_NOTES.md):
    #   BENCH_FUSED=0        unfused LM loss (materialized logits)
    #   BENCH_UNROLL=N       layer-scan unroll factor
    #   BENCH_LAG=N          framework-loop metrics lag depth
    #   BENCH_NO_ASYNC_COPY=1  skip per-step copy_to_host_async
    #   BENCH_STEPS=N        timed steps
    # Interleaved A/B (4 reps each, r4): unfused 90.0k vs fused 87.6k tok/s —
    # at bench shapes the backward's head-matmul recompute (+2·N·D·V FLOPs)
    # outweighs the saved logits bandwidth. fused_loss remains the memory
    # knob for vocab/seq scales where the [N, V] tensor doesn't fit.
    fused = os.environ.get("BENCH_FUSED", "0") != "0"
    unroll = int(os.environ.get("BENCH_UNROLL", "8"))
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32000,
            d_model=1024,
            n_layers=8,
            # head_dim = 128 (the MXU-native width, Llama-style). Identical
            # params/FLOPs to 16 heads of 64, but attention matmuls contract
            # over a full 128-lane tile: interleaved A/B measured 95.5k ->
            # 113.0k tok/s (+18%) switching head_dim 64 -> 128.
            n_heads=8,
            n_kv_heads=8,
            d_ff=2816,
            max_seq_len=1024,
            dtype=jnp.bfloat16,
            remat=False,
            # Single chip, no pp: full unroll lets XLA schedule across layer
            # boundaries (+12% measured on v5e — see TransformerConfig).
            scan_unroll=unroll,
            fused_loss=fused,
        )
        # batch 12: interleaved A/B (r5) measured 124.7k tok/s vs 121.4k at
        # batch 8 and 123.4k at 16 on the same chip — the MFU sweet spot for
        # these shapes.
        batch, seq, steps = (
            int(os.environ.get("BENCH_BATCH", "12")),
            1024,
            int(os.environ.get("BENCH_STEPS", "192")),
        )
    else:
        cfg = TransformerConfig(
            vocab_size=1024,
            d_model=128,
            n_layers=2,
            n_heads=4,
            n_kv_heads=4,
            d_ff=256,
            max_seq_len=128,
            dtype=jnp.float32,
            remat=False,
            fused_loss=fused,
            scan_unroll=min(unroll, 2),
        )
        batch, seq, steps = 4, 128, int(os.environ.get("BENCH_STEPS", "10"))

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0, 1))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_arr = {"tokens": tokens}

    # Warmup/compile. Timed regions end with float(loss) — a forced host
    # transfer — rather than block_until_ready: under the axon remote-TPU
    # tunnel block_until_ready can return before the dispatch chain drains
    # (round-1 bench measured a 3 ms "raw" loop because of this), while a
    # host transfer of the last step's loss cannot complete early.
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, batch_arr)
    float(loss)

    # Measurement: the pure-JAX baseline (tight loop, no framework
    # interaction) and the framework path (same loop, losses reported
    # through the air session) run INTERLEAVED in ABBA-ordered chunks —
    # raw/fw, fw/raw, ... — and the ratio is summed-raw / summed-fw.
    # Sequential windows measured ±0.5-1% run-to-run drift on this chip
    # (thermal + tunnel state), which landed entirely in vs_baseline;
    # alternating chunks cancels linear drift exactly and halves the rest.
    # Each chunk ends with one synchronous host fetch (float(loss) for raw,
    # the logger's batch fetch for fw), so chunk-boundary drain cost is
    # symmetric.
    #
    # Framework logger shape: losses are batched ON DEVICE (one jnp.stack +
    # one async D2H copy per BENCH_LAG steps) and fetched one batch LATE
    # inside a chunk, so each copy has a full batch of steps to land before
    # it is read. Per-step Python cost is a list append. A per-step
    # synchronous float() would pay the device->host RTT every iteration
    # (under the axon remote-TPU tunnel that RTT is milliseconds and it
    # throttles dispatch depth). Every loss is still reported, in order —
    # this is the shape of any well-written training metrics logger,
    # batched host syncs included.
    import collections

    import numpy as np

    # lag >= 1: a batch of 1 degenerates to the per-step async-copy logger.
    # Chunk default: half the steps (one ABBA pair of big windows). Each
    # chunk drain pays one synchronous D2H round trip — ~90ms under the
    # axon tunnel — so fewer, bigger windows keep measured tok/s honest to
    # the steady state while ABBA still cancels linear drift.
    lag = max(1, int(os.environ.get("BENCH_LAG", "16")))
    chunk = max(lag, int(os.environ.get("BENCH_CHUNK", str(max(lag, steps // 2)))))
    async_copy = os.environ.get("BENCH_NO_ASYNC_COPY", "0") != "1"
    rounds = max(2, steps // chunk)
    rounds += rounds % 2  # even round count: raw and fw lead equally often
    steps = rounds * chunk  # per loop

    def _flush(base, arr):
        for j, val in enumerate(np.asarray(arr)):
            session.report({"step": base + j, "loss": float(val)})

    # Precompile the stack/fetch shapes the logger uses (lag and the final
    # partial batch of a chunk) so no compile lands inside a timed window.
    for warm_n in {lag, chunk % lag or lag, 1}:
        np.asarray(jnp.stack([loss] * warm_n))

    def run_raw_chunk():
        nonlocal params, opt_state, loss
        t0 = time.perf_counter()
        for _ in range(chunk):
            params, opt_state, loss = step(params, opt_state, batch_arr)
        float(loss)
        return time.perf_counter() - t0

    fw_step = 0

    def run_fw_chunk():
        nonlocal params, opt_state, loss, fw_step
        tail: list = []
        inflight: collections.deque = collections.deque()
        t0 = time.perf_counter()
        for _ in range(chunk):
            params, opt_state, loss = step(params, opt_state, batch_arr)
            tail.append(loss)
            fw_step += 1
            if len(tail) == lag:
                stacked = jnp.stack(tail)
                tail = []
                if async_copy:
                    try:
                        stacked.copy_to_host_async()
                    except Exception:
                        pass
                inflight.append((fw_step - lag, stacked))
                if len(inflight) > 1:
                    _flush(*inflight.popleft())
        while inflight:
            _flush(*inflight.popleft())
        if tail:
            _flush(fw_step - len(tail), jnp.stack(tail))
        return time.perf_counter() - t0

    raw_s = fw_s = 0.0
    for r in range(rounds):
        if r % 2 == 0:
            raw_s += run_raw_chunk()
            fw_s += run_fw_chunk()
        else:
            fw_s += run_fw_chunk()
            raw_s += run_raw_chunk()

    tok = batch * seq * steps
    session.report(
        {
            "final": True,
            "tokens_per_sec_framework": tok / fw_s,
            "tokens_per_sec_raw": tok / raw_s,
            "ratio": raw_s / fw_s if fw_s > 0 else 0.0,
            "backend": jax.default_backend(),
            "n_params": n_params,
            "device_kind": jax.devices()[0].device_kind,
        }
    )


def main():
    os.environ.setdefault("RAY_TPU_NUM_TPUS", os.environ.get("BENCH_NUM_TPUS", ""))
    import ray_tpu
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    explicit = os.environ.get("RAY_TPU_NUM_TPUS")
    if explicit not in (None, ""):
        n_tpus = int(explicit)
    else:
        n_tpus = 0
        try:
            from ray_tpu._private.node import detect_tpu_chips

            n_tpus = detect_tpu_chips()
        except Exception:
            pass
        # Under the axon tunnel there is one chip but no /dev/accel*; assume
        # TPU when the axon plugin env is present.
        if n_tpus == 0 and os.environ.get("PALLAS_AXON_POOL_IPS"):
            n_tpus = 1
            os.environ["RAY_TPU_NUM_TPUS"] = "1"

    ray_tpu.init(num_cpus=4, num_tpus=n_tpus or None)
    use_tpu = n_tpus > 0
    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(
            num_workers=1, use_tpu=use_tpu, tpu_per_worker=1 if use_tpu else 0
        ),
        run_config=RunConfig(storage_path="/tmp/rtpu_bench"),
    )
    result = trainer.fit()
    m = result.metrics
    ray_tpu.shutdown()
    backend = m.get("backend", "cpu")
    suffix = "_tpu" if backend in ("tpu", "axon") else "_cpu"
    out = {
        "metric": "flagship_transformer_train_tokens_per_sec" + suffix,
        "value": round(m["tokens_per_sec_framework"], 1),
        "unit": "tokens/s",
        # 3 decimals = the measurement's honest precision: with ABBA
        # interleaving the framework/pure ratio's run-to-run spread is
        # ~±5e-4 (measured r5: 1.0001 / 0.9999 back-to-back), so a 4th
        # digit would be reporting noise.
        "vs_baseline": round(m["ratio"], 3),
    }
    if suffix == "_tpu":
        kind = m.get("device_kind", "")
        out["tokens_per_sec_raw"] = round(m["tokens_per_sec_raw"], 1)
        out["device_kind"] = kind
        out["n_params"] = m.get("n_params", 0)
        peak = _peak_bf16_flops(kind)
        if peak and m.get("n_params"):
            # Model FLOPs utilization: 6 * params * tokens/s over chip peak.
            out["mfu"] = round(6 * m["n_params"] * m["tokens_per_sec_framework"] / peak, 4)
    print(json.dumps(out))


def _peak_bf16_flops(device_kind: str) -> float:
    """Per-chip peak bf16 FLOPs/s by device kind (public spec sheets)."""
    kind = device_kind.lower()
    for key, peak in (
        ("v5 lite", 197e12),
        ("v5e", 197e12),
        ("v5p", 459e12),
        ("v6", 918e12),
        ("v4", 275e12),
        ("v3", 123e12),
        ("v2", 46e12),
    ):
        if key in kind:
            return peak
    return 0.0


if __name__ == "__main__":
    main()
