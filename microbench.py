"""Scheduling/object-plane envelope microbenchmark.

Analog of `ray microbenchmark` (reference: python/ray/_private/ray_perf.py:93)
plus envelope stresses from release/benchmarks (queued-task depth, actor
count, object broadcast). Run per round; results land in MICROBENCH_r{N}.json
so the envelope is tracked across rounds (VERDICT r1 #5). Every artifact
includes a `deltas_vs_prev` block diffing against the previous round's JSON
so regressions are named in the artifact itself (VERDICT r5 #8).

Usage: python microbench.py [--round N] [--quick]
       python microbench.py --hop-budget   # per-hop dispatch latency table
       python microbench.py --smoke        # <30s CI sanity pass (tier-1)
       python microbench.py --dag          # classic vs compiled DAG dispatch
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_JAX_CONFIG_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_NUM_TPUS", "0")


def timeit(fn, duration=2.0, multiplier=1, warmup=1):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    return count * multiplier / dt


def basic_suite(results, duration):
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote
    def small():
        return b"ok"

    @ray_tpu.remote
    class Actor:
        def ping(self):
            return b"ok"

    a = Actor.remote()
    ray_tpu.get(a.ping.remote())

    results["task_sync_per_s"] = round(timeit(lambda: ray_tpu.get(small.remote()), duration), 1)
    results["task_async100_per_s"] = round(
        timeit(lambda: ray_tpu.get([small.remote() for _ in range(100)]), duration, 100), 1
    )
    results["actor_call_sync_per_s"] = round(timeit(lambda: ray_tpu.get(a.ping.remote()), duration), 1)
    results["actor_call_async100_per_s"] = round(
        timeit(lambda: ray_tpu.get([a.ping.remote() for _ in range(100)]), duration, 100), 1
    )
    arr = np.zeros(1024 * 1024, dtype=np.uint8)
    results["put_1mib_per_s"] = round(timeit(lambda: ray_tpu.put(arr), duration), 1)
    results["putget_1mib_per_s"] = round(
        timeit(lambda: ray_tpu.get(ray_tpu.put(arr)), duration), 1
    )
    ray_tpu.shutdown()


def hop_budget_suite(results, duration):
    """--hop-budget: measured per-hop dispatch latency budget.

    Runs the sync ping-pong loops with RAY_TPU_HOP_TIMING=1 so every frame
    carries monotonic stage timestamps, then prints/records the per-hop µs
    table per transport path: warm lease (steady-state normal task, raylet
    OFF the path), direct actor call, and the classic raylet-queued path
    (SPREAD forces it) as the before/after contrast."""
    os.environ["RAY_TPU_HOP_TIMING"] = "1"
    try:
        import ray_tpu
        from ray_tpu.util import tracing

        ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

        @ray_tpu.remote
        def small():
            return b"ok"

        @ray_tpu.remote(scheduling_strategy="SPREAD")
        def small_spread():
            return b"ok"

        @ray_tpu.remote
        class Actor:
            def ping(self):
                return b"ok"

        a = Actor.remote()
        ray_tpu.get(a.ping.remote())
        ray_tpu.get(small.remote())
        ray_tpu.get(small_spread.remote())
        tracing.drain_hop_records()  # discard warmup records
        records = []
        for fn in (
            lambda: ray_tpu.get(small.remote()),        # warm lease
            lambda: ray_tpu.get(a.ping.remote()),       # direct actor
            lambda: ray_tpu.get(small_spread.remote()),  # classic raylet path
        ):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < duration:
                fn()
            # Harvest per phase: the owner's hop ring buffer holds 4096
            # records, and a fast later phase would evict an earlier one's.
            records.extend(tracing.drain_hop_records())
        summary = tracing.summarize_hop_records(records)
        results["hop_budget"] = summary
        print(tracing.format_hop_table(summary))
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_HOP_TIMING", None)


def dag_suite(results, duration):
    """--dag: classic dag.execute() vs compiled execution on a 4-stage actor
    pipeline (ISSUE 7 acceptance artifact, DAGBENCH_r{N}.json).

    Runs with RAY_TPU_HOP_TIMING=1 so compiled iterations leave their
    path="compiled" stage stamps, and records the control-plane evidence
    directly: the driver->raylet RPC count and the owned-ObjectRef table
    delta across the compiled loop (both must be 0 per iteration)."""
    os.environ["RAY_TPU_HOP_TIMING"] = "1"
    try:
        import ray_tpu
        from ray_tpu._private import worker_context
        from ray_tpu.dag import InputNode
        from ray_tpu.util import tracing

        ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)

        @ray_tpu.remote
        class Stage:
            def work(self, x):
                return x + 1

        with InputNode() as inp:
            dag = inp
            for _ in range(4):
                dag = Stage.bind().work.bind(dag)

        # Classic path (per-call specs/refs/RPCs; actor gang reused via the
        # per-DAG actor cache).
        assert ray_tpu.get(dag.execute(0)) == 4  # create + warm the gang
        classic_per_s = timeit(lambda: ray_tpu.get(dag.execute(0)), duration)
        results["dag_classic_per_s"] = round(classic_per_s, 1)
        results["dag_classic_latency_ms"] = round(1000.0 / classic_per_s, 3)
        tracing.drain_hop_records()

        # Compiled path: same gang, pre-allocated channels, resident loops.
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(0).get() == 4  # warm the loops
            cw = worker_context.get_core_worker()
            raylet_seq0 = cw.raylet._seq
            owned0 = len(cw.owned)
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < duration:
                assert compiled.execute(0).get() == 4
                n += 1
            dt = time.perf_counter() - t0
            results["dag_compiled_per_s"] = round(n / dt, 1)
            results["dag_compiled_latency_ms"] = round(dt * 1000.0 / n, 3)
            results["dag_compiled_iters"] = n
            # Control-plane evidence for the acceptance claim.
            results["dag_compiled_raylet_rpcs_per_iter"] = round(
                (cw.raylet._seq - raylet_seq0) / n, 6
            )
            results["dag_compiled_new_object_refs_per_iter"] = round(
                (len(cw.owned) - owned0) / n, 6
            )
            results["dag_speedup_vs_classic"] = round(
                results["dag_compiled_per_s"] / classic_per_s, 2
            )
            summary = tracing.summarize_hop_records(tracing.drain_hop_records())
            results["dag_hop_budget"] = summary
            print(tracing.format_hop_table(summary))
        finally:
            compiled.teardown()
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_HOP_TIMING", None)


def pipeline_suite(results, quick=False):
    """--pipeline: 4-stage MPMD pipeline over compiled graphs (ISSUE 12
    acceptance artifact, PIPEBENCH_r{N}.json).

    Arms on identical stacked params / inputs (stage_fn = tanh(h @ w),
    d=16, mb=4 — small activations so control-plane cost, not byte copies,
    is what's measured; a larger-activation shape rides along for honesty):

    - ``classic``: the SAME ``tensor_transport="collective"`` stage actors
      driven by classic dispatch — chained ``.remote`` calls, descriptor
      ObjectRefs, a ``devobj_pull`` round trip per hop (the PR 9 path with
      the full per-call control plane). The apples-to-apples baseline: same
      device-object semantics, classic control plane.
    - ``classic_host``: plain actors, activations through the host object
      plane (inline/plasma) — the pre-device-plane pipeline.
    - ``mpmd``: ``parallel/mpmd_pipeline.py`` — compiled DAG, resident
      loops, descriptor slots, eager out-of-band payload streaming.
    - ``spmd``: single-controller ``pipeline_apply`` (one jitted program on
      the driver's pp mesh) — the parity oracle and the single-process
      reference point (no process boundaries: on this 1-CPU box its raw
      mb/s is NOT the MPMD comparison axis; per-stage meshes/programs are).

    Evidence recorded per the acceptance criteria: bit-exact parity of the
    MPMD outputs vs pipeline_apply, raylet RPCs per iteration (0), store
    object delta (0 — no activation touches the shm object store), stage
    host-transfer delta (0 — no host-fallback resolutions in steady state),
    and measured bubble fraction at M in {4, 16} next to the theoretical
    (S-1)/(M+S-1)."""
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import numpy as np

    import ray_tpu
    from ray_tpu._private import worker_context

    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.mpmd_pipeline import PipelineStageActor, mpmd_pipeline
    from ray_tpu.parallel.pipeline import pipeline_apply

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    n_stages, d, mb = 4, 16, 4
    duration = 1.0 if quick else 3.0
    Ms = (4,) if quick else (4, 16)
    ws = jax.random.normal(jax.random.PRNGKey(1), (n_stages, d, d)) * 0.3
    results["pipeline_shape"] = {"n_stages": n_stages, "d": d, "mb": mb}
    cw = worker_context.get_core_worker()

    def store_objects() -> int:
        return cw.raylet.call("get_state")["store"]["num_objects"]

    def batch(M):
        return jax.random.normal(jax.random.PRNGKey(2), (M * mb, d))

    # ---- spmd arm + the parity reference -------------------------------
    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    x4 = batch(4)
    ref4 = np.asarray(pipeline_apply(stage_fn, ws, x4, mesh, num_microbatches=4))
    for M in Ms:
        x = batch(M)
        rate = timeit(
            lambda: np.asarray(
                pipeline_apply(stage_fn, ws, x, mesh, num_microbatches=M)
            ),
            duration / 2,
        )
        results[f"pipeline_spmd_m{M}_iter_per_s"] = round(rate, 2)
        results[f"pipeline_spmd_m{M}_mb_per_s"] = round(rate * M, 1)

    # ---- classic arm: same tensor_transport actors, classic dispatch ---
    nodes = [
        PipelineStageActor.bind(stage_fn, ws[k], k, n_stages, None)
        for k in range(n_stages)
    ]
    handles = [n.resolve_actor_handle() for n in nodes]
    ray_tpu.get([h.ready.remote() for h in handles], timeout=120)
    ray_tpu.get([h.warmup.remote(jnp.zeros((mb, d))) for h in handles], timeout=120)

    def classic_apply(handles_, x_mbs):
        refs = []
        for m in range(len(x_mbs)):
            r = x_mbs[m]
            for h in handles_:
                r = h.run.remote(r)
            refs.append(r)
        return ray_tpu.get(refs, timeout=120)

    for M in Ms:
        x_mbs = batch(M).reshape(M, mb, d)
        rate = timeit(lambda: classic_apply(handles, x_mbs), duration)
        results[f"pipeline_classic_m{M}_iter_per_s"] = round(rate, 2)
        results[f"pipeline_classic_m{M}_mb_per_s"] = round(rate * M, 1)
    for h in handles:
        ray_tpu.kill(h)

    # ---- classic_host arm: plain actors, host object plane -------------
    @ray_tpu.remote
    class HostStage:
        def __init__(self, fn, params):
            import jax as _jax

            self._fn = _jax.jit(fn)
            self.params = _jax.device_put(params)

        def run(self, h):
            return self._fn(self.params, h)

    host_handles = [HostStage.remote(stage_fn, ws[k]) for k in range(n_stages)]
    classic_apply(host_handles, batch(4).reshape(4, mb, d))  # warm
    for M in Ms:
        x_mbs = batch(M).reshape(M, mb, d)
        rate = timeit(lambda: classic_apply(host_handles, x_mbs), duration)
        results[f"pipeline_classic_host_m{M}_iter_per_s"] = round(rate, 2)
        results[f"pipeline_classic_host_m{M}_mb_per_s"] = round(rate * M, 1)
    for h in host_handles:
        ray_tpu.kill(h)

    # ---- mpmd arm ------------------------------------------------------
    from ray_tpu.experimental.device_object import device_object_stats

    pipe = mpmd_pipeline(
        stage_fn, ws, num_microbatches=4, warmup_x=jnp.zeros((mb, d))
    )
    # Parity oracle: bit-exact vs pipeline_apply on identical params/input.
    out4 = np.asarray(pipe.apply(x4, num_microbatches=4))
    results["pipeline_parity_bitexact"] = bool(np.array_equal(out4, ref4))
    assert results["pipeline_parity_bitexact"], "MPMD output != pipeline_apply"

    for M in Ms:
        x = batch(M)
        pipe.apply(x, num_microbatches=M)  # warm this schedule
        pipe.reset_stage_stats()
        store0 = store_objects()
        stage_stats0 = pipe.stage_devobj_stats()
        driver0 = device_object_stats()
        # Control-plane baselines LAST: the probes above are classic calls
        # (a raylet get_state RPC, ObjectRef-bearing actor calls) and must
        # not count against the measured window.
        raylet_seq0 = cw.raylet._seq
        owned0 = len(cw.owned)
        t0 = time.perf_counter()
        iters = 0
        while time.perf_counter() - t0 < duration:
            pipe.apply(x, num_microbatches=M)
            iters += 1
        dt = time.perf_counter() - t0
        results[f"pipeline_mpmd_m{M}_iter_per_s"] = round(iters / dt, 2)
        results[f"pipeline_mpmd_m{M}_mb_per_s"] = round(iters * M / dt, 1)
        results[f"pipeline_mpmd_m{M}_bubble_measured"] = round(
            pipe.bubble_fraction(), 4
        )
        results[f"pipeline_mpmd_m{M}_bubble_theoretical"] = round(
            (n_stages - 1) / (M + n_stages - 1), 4
        )
        # Control-plane + zero-host-copy evidence (deterministic counters).
        results[f"pipeline_mpmd_m{M}_raylet_rpcs_per_iter"] = round(
            (cw.raylet._seq - raylet_seq0) / iters, 6
        )
        results[f"pipeline_mpmd_m{M}_new_object_refs_per_iter"] = round(
            (len(cw.owned) - owned0) / iters, 6
        )
        results[f"pipeline_mpmd_m{M}_store_objects_delta"] = (
            store_objects() - store0
        )
        stage_stats1 = pipe.stage_devobj_stats()
        results[f"pipeline_mpmd_m{M}_host_transfers_delta"] = sum(
            s1["transfers_host"] - s0["transfers_host"]
            for s0, s1 in zip(stage_stats0, stage_stats1)
        ) + (device_object_stats()["transfers_host"] - driver0["transfers_host"])
        results[f"pipeline_mpmd_m{M}_chan_sends"] = sum(
            s1["chan_sends"] - s0["chan_sends"]
            for s0, s1 in zip(stage_stats0, stage_stats1)
        )
    results["pipeline_speedup_vs_classic"] = round(
        results["pipeline_mpmd_m4_iter_per_s"]
        / results["pipeline_classic_m4_iter_per_s"],
        2,
    )
    results["pipeline_speedup_vs_classic_host"] = round(
        results["pipeline_mpmd_m4_iter_per_s"]
        / results["pipeline_classic_host_m4_iter_per_s"],
        2,
    )
    # Larger-activation shape for honesty (256 KiB activations: byte copies
    # start to dominate both arms and compute equalizes them; the control-
    # plane win above is the claim, this row bounds it).
    if not quick:
        d2, mb2 = 512, 128
        ws2 = jax.random.normal(jax.random.PRNGKey(4), (n_stages, d2, d2)) * 0.05
        pipe2 = mpmd_pipeline(
            stage_fn, ws2, num_microbatches=4,
            warmup_x=jnp.zeros((mb2, d2)),
        )
        x2 = jax.random.normal(jax.random.PRNGKey(5), (4 * mb2, d2))
        pipe2.apply(x2, num_microbatches=4)
        rate = timeit(lambda: pipe2.apply(x2, num_microbatches=4), duration / 2)
        results["pipeline_mpmd_256kib_m4_iter_per_s"] = round(rate, 2)
        pipe2.teardown()
    pipe.teardown()
    ray_tpu.shutdown()


def device_objects_suite(results, duration):
    """--device-objects: device-ref handoff vs host-shm put/get (ISSUE 9
    acceptance artifact, DEVBENCH_r{N}.json).

    Same-process: ``put(arr, tensor_transport="collective")`` seals only a
    ~300-byte descriptor and ``get`` hands back the LIVE array — the
    before/after contrast is the host path's serialize→shm→deserialize
    round trip at 1 MiB / 32 MiB. Control-plane evidence rides along: the
    node store's object count across the device loop (must be 0 — zero shm
    copies of the payload) and the plane's own transfer counters.
    Actor→actor: a tensor_transport holder hands a 1 MiB ref to a consumer
    actor over a shared cpu collective group (on this CPU testbed the p2p
    mailbox rides the GCS KV — a correctness stand-in for the ICI path, so
    absolute throughput is NOT the device-plane claim; zero host-shm
    payload traffic is)."""
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.experimental.device_object import device_object_stats

    ray_tpu.init(num_cpus=4, object_store_memory=512 * 1024 * 1024)
    import jax.numpy as jnp

    cw = worker_context.get_core_worker()

    def store_objects() -> int:
        return cw.raylet.call("get_state")["store"]["num_objects"]

    for mib in (1, 32):
        arr = jnp.zeros(mib * 1024 * 1024 // 4, jnp.float32)
        arr.block_until_ready()
        results[f"host_putget_{mib}mib_per_s"] = round(
            timeit(lambda: ray_tpu.get(ray_tpu.put(arr)), duration), 1
        )

        def dev_roundtrip():
            out = ray_tpu.get(ray_tpu.put(arr, tensor_transport="collective"))
            assert out is arr  # live array, zero payload copies

        before = store_objects()
        t0 = device_object_stats()
        results[f"devobj_putget_{mib}mib_per_s"] = round(timeit(dev_roundtrip, duration), 1)
        t1 = device_object_stats()
        results[f"devobj_putget_{mib}mib_store_objects_delta"] = store_objects() - before
        results[f"devobj_putget_{mib}mib_local_transfers"] = (
            t1["transfers_local"] - t0["transfers_local"]
        )

    # Actor→actor 1 MiB handoff: host-shm path vs device plane + collective.
    @ray_tpu.remote
    class HostHolder:
        def make(self):
            import jax.numpy as jnp

            return jnp.zeros(1024 * 1024 // 4, jnp.float32)

    @ray_tpu.remote(tensor_transport="collective")
    class DevHolder:
        def make(self):
            import jax.numpy as jnp

            return jnp.zeros(1024 * 1024 // 4, jnp.float32)

        def init_collective(self, world_size, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

    @ray_tpu.remote
    class Consumer:
        def init_collective(self, world_size, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

        def consume(self, w):
            return float(w[0])

    from ray_tpu.util import collective as col

    host_holder, dev_holder, consumer = HostHolder.remote(), DevHolder.remote(), Consumer.remote()
    col.create_collective_group(
        [dev_holder, consumer], backend="cpu", group_name="devbench"
    )
    results["handoff_host_1mib_per_s"] = round(
        timeit(
            lambda: ray_tpu.get(consumer.consume.remote(host_holder.make.remote())),
            duration,
        ),
        1,
    )
    results["handoff_devobj_1mib_per_s"] = round(
        timeit(
            lambda: ray_tpu.get(consumer.consume.remote(dev_holder.make.remote())),
            duration,
        ),
        1,
    )
    ray_tpu.shutdown()


def collective_suite(results, quick=False, arms=("tree", "flat")):
    """--collective: ISSUE 15 — learner→fleet weight-sync fan-out A/B, plus
    ISSUE 16 — relay-tree vs flat group broadcast and the tree allreduce
    oracle (COLLBENCH_r{N}.json).

    A tensor_transport learner actor holds a payload_mib flat weight vector
    device-resident; K sampler actors apply it each sync. Baseline arm =
    the K-serial-unicast path every pre-15 sync paid (each sampler's
    resolve does its own devobj_pull → holder serializes PER SAMPLER and
    ships through the group's GCS-KV mailbox). Broadcast arm = ONE
    device_object.broadcast(ref, group): one serialize, concurrent acked
    chunk pushes at every sampler's direct mailbox, samplers resolve from
    their inbox with zero pull round trips. Both arms end in the same
    state (every sampler applied the weights), timed over the same actors
    in the same cluster; the device path's zero-host-store evidence
    (store_objects_delta) rides along. An end-to-end Podracer row (IMPALA
    on CartPole, device_broadcast vs host weight sync) closes the loop."""
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.experimental import device_object
    from ray_tpu.util import collective as col

    fleet = [2] if quick else [2, 4, 8]
    # 8 MiB ≈ a 2M-param f32 model: big enough that the payload path (the
    # thing this issue changes) dominates the K fixed-cost actor round
    # trips both arms share.
    payload_mib = 2 if quick else 8
    reps = 2 if quick else 5
    n = payload_mib * 1024 * 1024 // 4
    ray_tpu.init(num_cpus=16, object_store_memory=512 * 1024 * 1024)
    cw = worker_context.get_core_worker()

    def store_objects() -> int:
        return cw.raylet.call("get_state")["store"]["num_objects"]

    @ray_tpu.remote(tensor_transport="collective")
    class LearnerActor:
        def __init__(self):
            self._version = 0

        def init_collective(self, world_size, rank, backend, group_name):
            col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

        def make_weights(self, n):
            import jax.numpy as jnp

            self._version += 1
            return jnp.full((n,), float(self._version), jnp.float32)

        def residents(self):
            from ray_tpu.experimental.device_object import device_object_stats

            return device_object_stats()["resident_count"]

    @ray_tpu.remote
    class SamplerActor:
        def init_collective(self, world_size, rank, backend, group_name):
            col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

        def apply(self, w):
            # Arg resolution already resolved the descriptor (inbox on the
            # broadcast arm, devobj_pull unicast on the baseline arm).
            return float(w[0])

    results["collective_payload_mib"] = payload_mib
    for K in fleet:
        learner = LearnerActor.remote()
        samplers = [SamplerActor.remote() for _ in range(K)]
        group = f"wsync{K}"
        col.create_collective_group([learner] + samplers, backend="cpu", group_name=group)

        def sync_serial():
            ref = learner.make_weights.remote(n)
            t0 = time.perf_counter()
            for s in samplers:
                ray_tpu.get(s.apply.remote(ref), timeout=120)
            return time.perf_counter() - t0

        def sync_broadcast():
            ref = learner.make_weights.remote(n)
            t0 = time.perf_counter()
            info = device_object.broadcast(ref, group, timeout=120)
            assert len(info["ok_ranks"]) == K, info
            for s in samplers:
                ray_tpu.get(s.apply.remote(ref), timeout=120)
            return time.perf_counter() - t0

        sync_serial()  # warm both code paths + worker jax imports
        sync_broadcast()
        serial = sorted(sync_serial() for _ in range(reps))[reps // 2]
        # Snapshot AFTER the serial arm so the delta certifies the
        # broadcast arm alone.
        before = store_objects()
        bcast = sorted(sync_broadcast() for _ in range(reps))[reps // 2]
        results[f"wsync_serial_k{K}_s"] = round(serial, 4)
        results[f"wsync_broadcast_k{K}_s"] = round(bcast, 4)
        results[f"wsync_serial_k{K}_mib_per_s"] = round(K * payload_mib / serial, 1)
        results[f"wsync_broadcast_k{K}_mib_per_s"] = round(K * payload_mib / bcast, 1)
        results[f"wsync_speedup_k{K}"] = round(serial / bcast, 2)
        results[f"wsync_broadcast_k{K}_store_objects_delta"] = store_objects() - before
        # Ownership protocol: per-sync weight refs were dropped, so the
        # learner's residents must drain back to zero (bounded wait for the
        # async devobj_free pushes).
        deadline = time.monotonic() + 30
        residents = ray_tpu.get(learner.residents.remote())
        while residents > 0 and time.monotonic() < deadline:
            time.sleep(0.2)
            residents = ray_tpu.get(learner.residents.remote())
        results[f"wsync_k{K}_residents_after"] = residents
        for a in [learner] + samplers:
            ray_tpu.kill(a)

    # ---- ISSUE 16: relay-tree vs flat broadcast + tree allreduce oracle ----
    # On this 1-core loopback box raw wire time cannot separate the
    # topologies, so the A/B runs under the PR 10 modeled-link convention:
    # a 64 MiB/s per-process egress gate (p2p.set_modeled_egress) charges
    # every collective push its wire time — the flat root pays K payloads
    # through its gate, the tree root only its log-K children (relays pay
    # theirs in PARALLEL on other processes). Raw loopback rows ride along
    # unmodeled for honesty.
    from ray_tpu.util.collective.p2p import COLL, set_modeled_egress

    MODELED_MIB_S = 64.0
    relay_mib = 1 if quick else 4
    n_relay = relay_mib * 1024 * 1024 // 4
    relay_fleet = [3] if quick else [4, 8]
    relay_reps = 2 if quick else 3

    @ray_tpu.remote
    class RelayMember:
        def init_collective(self, world_size, rank, backend, group_name):
            col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)

        def set_egress(self, mib_per_s):
            from ray_tpu.util.collective.p2p import set_modeled_egress as sme

            sme(mib_per_s)
            return True

        def drain(self, group_name, src_rank, tag):
            import numpy as np

            out = col.get_group(group_name).bcast_recv_payload(src_rank, tag, timeout=120)
            return int(np.asarray(out).size)

        def allreduce(self, group_name, tag, n, flat_ring=False):
            import numpy as np

            g = col.get_group(group_name)
            v = ((np.arange(n) % 97) + 3.0 * g.rank).astype(np.float32)
            out = g.allreduce(v) if flat_ring else g.allreduce_payload(v, tag)
            return np.asarray(out)

        def reducescatter(self, group_name, tag, k, n, flat_ring=False):
            import numpy as np

            g = col.get_group(group_name)
            v = ((np.arange(k * n).reshape(k, n) % 97) + 3.0 * g.rank).astype(
                np.float32
            )
            out = g.reducescatter(v) if flat_ring else g.reducescatter_payload(v, tag)
            return np.asarray(out)

        def coll_stats(self):
            from ray_tpu.util.collective.p2p import COLL as C

            return {k: getattr(C, k) for k in C.__slots__}

    import numpy as np

    results["relay_payload_mib"] = relay_mib
    results["relay_modeled_egress_mib_per_s"] = MODELED_MIB_S
    for K in relay_fleet:
        members = [RelayMember.remote() for _ in range(K)]
        group = f"relay{K}"
        col.init_collective_group(K + 1, 0, backend="cpu", group_name=group)
        ray_tpu.get(
            [m.init_collective.remote(K + 1, i + 1, "cpu", group) for i, m in enumerate(members)],
            timeout=120,
        )
        g = col.get_group(group)
        payload = np.arange(n_relay, dtype=np.float32)
        seq = iter(range(10_000))

        def timed_bcast(topology):
            tag = f"b{next(seq)}"
            t0 = time.perf_counter()
            info = g.bcast_send_payload(
                payload, tag, timeout=120, mailbox_fallback=False, topology=topology
            )
            dt = time.perf_counter() - t0
            assert len(info["ok_ranks"]) == K and not info["failed"], info
            # Drain member inboxes OUTSIDE the timed send-to-ack window.
            ray_tpu.get([m.drain.remote(group, 0, tag) for m in members], timeout=120)
            return dt, info

        def set_gate(mib):
            set_modeled_egress(mib)
            ray_tpu.get([m.set_egress.remote(mib) for m in members], timeout=60)

        store_before = store_objects()
        forwards_before = sum(
            s["relay_forwards"]
            for s in ray_tpu.get([m.coll_stats.remote() for m in members], timeout=60)
        )
        for topology in arms:
            raw_dt, info = timed_bcast(topology)  # warm + raw loopback row
            results[f"relay_{topology}_k{K}_raw_s"] = round(raw_dt, 4)
            if topology == "tree":
                assert info["topology"] == "tree", info
                results[f"relay_tree_k{K}_root_egress_frac"] = round(
                    info["root_egress_bytes"] / (K * info["bytes"]), 3
                )
            set_gate(MODELED_MIB_S)
            try:
                dts = sorted(timed_bcast(topology)[0] for _ in range(relay_reps))
            finally:
                set_gate(None)
            dt = dts[relay_reps // 2]
            results[f"relay_{topology}_k{K}_s"] = round(dt, 4)
            results[f"relay_{topology}_k{K}_agg_mib_per_s"] = round(K * relay_mib / dt, 1)
        if "tree" in arms and "flat" in arms:
            results[f"relay_tree_speedup_k{K}"] = round(
                results[f"relay_flat_k{K}_s"] / results[f"relay_tree_k{K}_s"], 2
            )
        forwards_after = sum(
            s["relay_forwards"]
            for s in ray_tpu.get([m.coll_stats.remote() for m in members], timeout=60)
        )
        results[f"relay_k{K}_relay_forwards"] = forwards_after - forwards_before
        results[f"relay_k{K}_store_objects_delta"] = store_objects() - store_before
        if "tree" in arms:
            # Mid-tree relays actually carried payload, and nothing touched
            # the host store — the quick-smoke contract.
            assert results[f"relay_k{K}_relay_forwards"] > 0, results
        assert results[f"relay_k{K}_store_objects_delta"] == 0, results

        # Allreduce arm (raw loopback, both transports ungated): tree
        # reduce-up/broadcast-down vs the flat GCS ring, with a BIT-EXACT
        # integer-float32 oracle — combine order must not change the sum.
        ar_group = f"ar{K}"
        ray_tpu.get(
            [m.init_collective.remote(K, i, "cpu", ar_group) for i, m in enumerate(members)],
            timeout=120,
        )
        n_ar = (1 if quick else 2) * 1024 * 1024 // 4
        expected = np.sum(
            [((np.arange(n_ar) % 97) + 3.0 * r).astype(np.float32) for r in range(K)],
            axis=0,
            dtype=np.float64,
        ).astype(np.float32)
        for label, flat_ring in (("tree", False), ("ring", True)):
            t0 = time.perf_counter()
            outs = ray_tpu.get(
                [m.allreduce.remote(ar_group, f"ar-{label}", n_ar, flat_ring) for m in members],
                timeout=240,
            )
            dt = time.perf_counter() - t0
            for out in outs:
                assert (out == expected).all(), f"allreduce {label} k{K}: oracle mismatch"
            results[f"allreduce_{label}_k{K}_s"] = round(dt, 4)
            results[f"allreduce_{label}_k{K}_agg_mib_per_s"] = round(
                K * (n_ar * 4 / 2**20) / dt, 1
            )
        results[f"allreduce_k{K}_bit_exact"] = 1

        # Reducescatter verb (ISSUE 20 satellite): tree reduce-to-root +
        # direct-mailbox shard scatter vs the flat GCS-mailbox ring, with
        # the same integer-float32 bit-exact oracle — every rank's shard
        # must equal its row of the full reduction regardless of combine
        # order or which transport carried it.
        n_rs = (256 if quick else 512) * 1024 // 4
        full_rs = np.sum(
            [
                ((np.arange(K * n_rs).reshape(K, n_rs) % 97) + 3.0 * r).astype(
                    np.float32
                )
                for r in range(K)
            ],
            axis=0,
            dtype=np.float64,
        ).astype(np.float32)
        scatter0 = sum(
            s["scatter_bytes"]
            for s in ray_tpu.get([m.coll_stats.remote() for m in members], timeout=60)
        )
        for label, flat_ring in (("tree", False), ("ring", True)):
            t0 = time.perf_counter()
            outs = ray_tpu.get(
                [
                    m.reducescatter.remote(ar_group, f"rs-{label}", K, n_rs, flat_ring)
                    for m in members
                ],
                timeout=240,
            )
            dt = time.perf_counter() - t0
            # Roster position == rank here (members hold ranks 0..K-1), so
            # rank i's shard is row i of the full reduction.
            for pos, out in enumerate(outs):
                assert (np.asarray(out) == full_rs[pos]).all(), (
                    f"reducescatter {label} k{K} rank {pos}: oracle mismatch"
                )
            results[f"reducescatter_{label}_k{K}_s"] = round(dt, 4)
            results[f"reducescatter_{label}_k{K}_agg_mib_per_s"] = round(
                K * (K * n_rs * 4 / 2**20) / dt, 1
            )
        results[f"reducescatter_k{K}_bit_exact"] = 1
        results[f"reducescatter_k{K}_scatter_bytes"] = (
            sum(
                s["scatter_bytes"]
                for s in ray_tpu.get(
                    [m.coll_stats.remote() for m in members], timeout=60
                )
            )
            - scatter0
        )
        # The tree arm actually shipped shards over direct mailboxes (the
        # ring arm rides the GCS mailbox and must not touch this counter).
        assert results[f"reducescatter_k{K}_scatter_bytes"] > 0, results

        col.destroy_collective_group(group)
        col.destroy_collective_group(ar_group)
        for m in members:
            ray_tpu.kill(m)
    set_modeled_egress(None)
    ray_tpu.shutdown()

    # ---- end-to-end Podracer row: IMPALA on CartPole, host vs device sync ----
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    iters = 2 if quick else 4
    for label, overrides in (
        ("host", {"weight_sync": "host"}),
        ("device_broadcast", {"weight_sync": "device_broadcast", "learner_mesh": True}),
    ):
        ray_tpu.init(num_cpus=6)
        cfg = (
            IMPALAConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=32)
            .training(lr=5e-4, train_batch_size=128, **overrides)
            .debugging(seed=0)
        )
        algo = cfg.build()
        try:
            # Warm compile + worker spawn outside the window. TWO steps: the
            # mesh arm pays a second jit (committed-param avals) on step 2.
            algo.step()
            algo.step()
            from ray_tpu.util.collective.p2p import COLL

            bcasts0 = COLL.bcast_sends
            t0 = time.perf_counter()
            for _ in range(iters):
                algo.step()
            dt = time.perf_counter() - t0
            results[f"podracer_{label}_iters_per_s"] = round(iters / dt, 2)
            if label == "device_broadcast":
                # Every measured iteration's weight sync must actually have
                # ridden the group-broadcast plane (driver = holder here).
                results["podracer_device_broadcasts"] = COLL.bcast_sends - bcasts0
        finally:
            algo.cleanup()
        ray_tpu.shutdown()


def resize_suite(results, quick=False):
    """--collective --resize: elastic Podracer fleet (ISSUE 17) — IMPALA on
    the device-broadcast plane driven through a scripted grow/shrink
    schedule (8→16→8 samplers; 2→4→2 under --quick). Growing gang-joins
    the new samplers into the weight group at fresh tail ranks, shrinking
    evicts the tail from the roster — no group teardown either way. Per
    phase the suite records how weight syncs actually travelled: inbox
    resolves summed over the live fleet (broadcast plane) vs host-sync
    pull fallbacks, plus iterations/s and the resize wall itself. The
    elastic contract is asserted inline: after the FIRST post-resize
    iteration the fleet-wide fallback counter is FLAT and every measured
    sync rode the plane."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    base = 2 if quick else 8
    peak = 4 if quick else 16
    iters = 2 if quick else 3
    schedule = [base, peak, base]
    results["resize_schedule"] = schedule
    ray_tpu.init(num_cpus=(6 if quick else peak + 2))
    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=base,
                  rollout_fragment_length=16 if quick else 32)
        .training(lr=5e-4, train_batch_size=64 if quick else 128,
                  weight_sync="device_broadcast")
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        assert algo._device_sync_ready, "device weight-sync group failed to form"
        algo.step()  # warm compile + worker spawn outside every window

        def fleet_totals():
            stats = [s for s in algo.workers.coll_stats() if s]
            return (
                sum(s["bcast_recvs"] for s in stats),
                sum(s["host_sync_fallbacks"] for s in stats),
            )

        for phase, n in enumerate(schedule):
            if algo.workers.num_workers != n:
                t0 = time.perf_counter()
                algo.resize_workers(n)
                results[f"resize_p{phase}_to{n}_s"] = round(time.perf_counter() - t0, 3)
            algo.step()  # the ONE iteration allowed to pull (post-resize)
            b0, f0 = fleet_totals()
            t0 = time.perf_counter()
            for _ in range(iters):
                algo.step()
            dt = time.perf_counter() - t0
            b1, f1 = fleet_totals()
            results[f"resize_p{phase}_n{n}_iters_per_s"] = round(iters / dt, 2)
            results[f"resize_p{phase}_n{n}_plane_syncs"] = b1 - b0
            results[f"resize_p{phase}_n{n}_host_fallbacks"] = f1 - f0
            # n workers x iters inbox resolves, zero pulls after the first
            # post-resize iteration — the fast-path oracle.
            assert b1 - b0 >= n * iters, results
            assert f1 - f0 == 0, results
        roster = algo.learner_group.weight_group_roster(algo._weight_group)
        results["resize_final_roster_ranks"] = roster["ranks"] if roster else None
    finally:
        algo.cleanup()
    ray_tpu.shutdown()


def recorder_overhead_suite(results, block_tasks=256, pairs=150):
    """--recorder-overhead: cost of the always-on observability plane
    (flight recorder + 1-in-64 sampled hop stamps) on the task_sync hot
    path, measured as many fine-grained paired A/B blocks.

    Noise design for a loaded 1-core box (single-block rates here swing
    +-6% while the instrumentation itself costs ~5us on a ~600us path):
    BOTH arms run inside ONE cluster against the SAME warm-leased worker,
    toggled at runtime (flight_recorder.set_enabled in driver AND worker +
    cfg.hop_sample_n in the driver, which controls the worker's stamping
    via spec.hop_ts). Blocks are COUNT-based (256 tasks ~ 150ms) and
    alternate ABBA so drift cancels within each pair; the headline
    overhead is the MEDIAN of per-pair ratios over many pairs — the only
    estimator that converged on this box (the interquartile mean rides
    along as recorder_overhead_iqmean_pct)."""
    import statistics

    import ray_tpu
    from ray_tpu._private import flight_recorder
    from ray_tpu._private.config import get_config

    ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote
    def small():
        return b"ok"

    @ray_tpu.remote
    def _toggle(on):
        # Runs on the same warm-leased worker the loop uses (num_cpus=1 and
        # an identical shape key): flips the worker-side recorder.
        from ray_tpu._private import flight_recorder as fr

        fr.set_enabled(on)
        return True

    def set_mode(on: bool):
        flight_recorder.set_enabled(on)
        get_config().hop_sample_n = 64 if on else 0
        assert ray_tpu.get(_toggle.remote(on))

    def block(n: int) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(small.remote())
        return n / (time.perf_counter() - t0)

    # Warm the lease + both code paths.
    set_mode(True)
    block(200)
    set_mode(False)
    block(200)

    ratios = []
    on_rates, off_rates = [], []
    for i in range(pairs):
        # ABBA: alternate which arm goes first so drift cancels per pair.
        order = [True, False] if i % 2 == 0 else [False, True]
        rates = {}
        for on in order:
            set_mode(on)
            rates[on] = block(block_tasks)
        on_rates.append(rates[True])
        off_rates.append(rates[False])
        ratios.append(rates[False] / rates[True])
    set_mode(True)  # leave the plane on, as in production
    ray_tpu.shutdown()
    ratios.sort()
    q = max(1, len(ratios) // 4)
    core = ratios[q : len(ratios) - q] or ratios
    results["recorder_on_task_sync_per_s"] = round(statistics.median(on_rates), 1)
    results["recorder_off_task_sync_per_s"] = round(statistics.median(off_rates), 1)
    results["recorder_overhead_pct"] = round(
        (statistics.median(ratios) - 1.0) * 100.0, 2
    )
    results["recorder_overhead_iqmean_pct"] = round(
        (sum(core) / len(core) - 1.0) * 100.0, 2
    )
    results["recorder_pair_ratios"] = [round(r, 4) for r in ratios]
    results["recorder_pairs"] = pairs
    results["recorder_block_tasks"] = block_tasks
    print(
        f"recorder overhead on task_sync: {results['recorder_overhead_pct']}% "
        f"(on={results['recorder_on_task_sync_per_s']}/s, "
        f"off={results['recorder_off_task_sync_per_s']}/s, "
        f"median of {pairs} ABBA pair ratios; "
        f"IQ-mean={results['recorder_overhead_iqmean_pct']}%)"
    )


def chaos_suite(results, quick=False):
    """--chaos: recovery-time budget table for the wire chaos plane
    (CHAOSBENCH_r{N}.json) — pull source failover under mid-frame reset,
    device-object handoff under a lost pull round trip, broadcast
    completion under a relay partition, acall heal-after-partition — plus
    the injection-DISABLED overhead check on task_sync (PR 8's paired-ABBA
    methodology: an installed-but-inert plan vs no plan; the no-plan arm
    is the production configuration, whose entire seam cost is one is-None
    check per frame, so the inert-plan arm upper-bounds it)."""
    import statistics
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu._private import chaos
    from ray_tpu._private.rpc import EventLoopThread, RpcClient, RpcServer
    from ray_tpu.cluster_utils import Cluster

    def oid_for(tag):
        return tag.encode().hex().ljust(56, "0")[:56]

    mib = 4 if quick else 16
    results["chaos_object_mib"] = mib

    # ---- acall heal-after-partition (no cluster needed) ----
    srv = RpcServer("chaosbench")

    async def _pong(req):
        return {"ok": True}

    srv.register("pong", _pong)
    addr = srv.start()
    cli = RpcClient(addr, label="chaosbench-cli")
    cli.call("pong", {}, timeout=5)
    key = f"{addr[0]}:{addr[1]}"
    partition_s = 1.0
    chaos.partition("*", key)
    healed_at = {}

    def _heal():
        chaos.heal("*", key)
        healed_at["t"] = time.perf_counter()

    timer = threading.Timer(partition_s, _heal)
    timer.start()
    t0 = time.perf_counter()
    cli.call("pong", {}, timeout=5, retries=10)
    t_done = time.perf_counter()
    timer.join()
    chaos.clear()
    cli.close()
    srv.stop()
    results["acall_partition_window_s"] = partition_s
    results["acall_heal_total_s"] = round(t_done - t0, 3)
    # Time from heal to success = the backoff schedule's probe latency;
    # bounded by rpc_retry_backoff_max_ms by construction.
    results["acall_heal_probe_latency_s"] = round(t_done - healed_at["t"], 3)

    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=1, object_store_memory=(mib * 8 + 64) * 1024 * 1024)
            for _ in range(4)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        io = EventLoopThread.get()
        data = np.random.default_rng(13).integers(
            0, 255, mib * 1024 * 1024, dtype=np.uint8
        ).tobytes()

        def seal(node, o):
            offset = io.run(node.store.create(o, len(data)))
            node.arena.write(offset, data)
            node.store.seal(o)
            io.run(node.gcs.acall(
                "add_object_location", {"object_id": o, "node_id": node.node_id}
            ))

        def read_ok(node, o):
            offset, size = io.run(node.store.get(o))
            try:
                return bytes(node.arena.read(offset, size)) == data
            finally:
                node.store.release(o)

        # ---- pull source failover under mid-frame reset ----
        o1 = oid_for("chaosbenchA")
        seal(nodes[0], o1)
        io.run(nodes[1].pull_manager.pull(o1, 120), timeout=120)  # replica 2
        t0 = time.perf_counter()
        io.run(nodes[2].pull_manager.pull(o1, 120), timeout=120)
        results["pull_unfaulted_s"] = round(time.perf_counter() - t0, 3)
        o2 = oid_for("chaosbenchB")
        seal(nodes[0], o2)
        io.run(nodes[1].pull_manager.pull(o2, 120), timeout=120)
        chaos.install({"rules": [{
            "kind": "reset", "method": ["fetch_object_chunk"],
            "peer": f"peer-{nodes[0].node_id[:8]}", "reset_at": 9, "times": 2,
        }]}, seed=13)
        t0 = time.perf_counter()
        io.run(nodes[3].pull_manager.pull(o2, 120), timeout=120)
        results["pull_failover_reset_s"] = round(time.perf_counter() - t0, 3)
        results["pull_failover_injected"] = chaos.CHAOS_STATS.resets
        chaos.clear()
        assert read_ok(nodes[2], o1) and read_ok(nodes[3], o2)

        # ---- broadcast completion under relay partition ----
        o3 = oid_for("chaosbenchC")
        seal(nodes[0], o3)
        targets = [
            {"node_id": n.node_id, "address": list(n.address)} for n in nodes[1:]
        ]
        t0 = time.perf_counter()
        resp = io.run(
            nodes[0].rpc_broadcast_object(
                {"object_id": o3, "targets": targets, "timeout": 120.0}
            ),
            timeout=120,
        )
        results["broadcast_unfaulted_s"] = round(time.perf_counter() - t0, 3)
        assert resp["ok"], resp
        for n in nodes:
            n.store.delete(o3)
            io.run(n.gcs.acall("remove_object_location",
                               {"object_id": o3, "node_id": n.node_id}))
        o4 = oid_for("chaosbenchD")
        seal(nodes[0], o4)
        # Partition the FIRST relay child (binomial split hands it the
        # subtree) for 1s mid-broadcast, healed by timer.
        victim = nodes[1]
        cluster.partition_node(victim)
        timer = threading.Timer(1.0, lambda: cluster.heal_node(victim))
        timer.start()
        t0 = time.perf_counter()
        resp = io.run(
            nodes[0].rpc_broadcast_object(
                {"object_id": o4, "targets": targets, "timeout": 120.0}
            ),
            timeout=120,
        )
        dt = time.perf_counter() - t0
        timer.join()
        cluster.heal_node(victim)
        results["broadcast_relay_partition_s"] = round(dt, 3)
        results["broadcast_relay_partition_window_s"] = 1.0
        # Completion contract: delivered everywhere, or failures NAME nodes
        # (the push plane fails fast on an unroutable relay rather than
        # waiting out the tear — the caller owns the retry policy).
        results["broadcast_relay_partition_ok"] = bool(resp.get("ok"))
        results["broadcast_relay_partition_failed_named"] = resp.get("failed", [])
        if not resp.get("ok"):
            # The documented recovery: re-broadcast after heal completes
            # (delivered targets answer "already"; the named failures get
            # their copy now).
            t0 = time.perf_counter()
            resp2 = io.run(
                nodes[0].rpc_broadcast_object(
                    {"object_id": o4, "targets": targets, "timeout": 120.0}
                ),
                timeout=120,
            )
            results["broadcast_retry_after_heal_s"] = round(time.perf_counter() - t0, 3)
            results["broadcast_retry_after_heal_ok"] = bool(resp2.get("ok"))

        # ---- device-object handoff under a lost pull round trip ----
        import jax.numpy as jnp

        @ray_tpu.remote(max_retries=2)
        def consume(arr):
            return float(np.asarray(arr).sum())

        warm = ray_tpu.put(jnp.ones(1024, jnp.float32), tensor_transport="collective")
        assert ray_tpu.get(consume.remote(warm), timeout=120) == 1024.0
        del warm
        r1 = ray_tpu.put(jnp.ones(4096, jnp.float32), tensor_transport="collective")
        t0 = time.perf_counter()
        assert ray_tpu.get(consume.remote(r1), timeout=120) == 4096.0
        results["devobj_handoff_unfaulted_s"] = round(time.perf_counter() - t0, 3)
        del r1
        r2 = ray_tpu.put(jnp.ones(4096, jnp.float32), tensor_transport="collective")
        # Drop the driver's devobj_pull REPLY once: the worker's bounded
        # per-attempt timeout retries (15s attempt cap — was a 60s stall
        # before this round's fix).
        chaos.install({"rules": [{
            "kind": "drop", "method": "devobj_pull", "side": "resp", "times": 1,
        }]}, seed=13)
        t0 = time.perf_counter()
        assert ray_tpu.get(consume.remote(r2), timeout=120) == 4096.0
        results["devobj_handoff_lost_reply_s"] = round(time.perf_counter() - t0, 3)
        chaos.clear()
        del r2
    finally:
        chaos.clear()
        cluster.shutdown()

    # ---- injection-disabled overhead on task_sync (PR 8 methodology) ----
    ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote
    def small():
        return b"ok"

    inert_plan = {"rules": [{"kind": "drop", "method": "no_such_method"}]}

    def set_mode(installed: bool):
        if installed:
            chaos.install(inert_plan, seed=1)
        else:
            chaos.clear()

    def block(n):
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(small.remote())
        return n / (time.perf_counter() - t0)

    block(200)  # warm lease + jit paths
    # 150 pairs, like OBSBENCH_r8: short runs on this box swing +-4% while
    # the long-horizon median repeats within ~0.5%.
    pairs = 8 if quick else 150
    block_tasks = 128 if quick else 256
    ratios, off_rates, on_rates = [], [], []
    for i in range(pairs):
        order = [True, False] if i % 2 == 0 else [False, True]
        rates = {}
        for installed in order:
            set_mode(installed)
            rates[installed] = block(block_tasks)
        on_rates.append(rates[True])
        off_rates.append(rates[False])
        ratios.append(rates[False] / rates[True])
    chaos.clear()
    ray_tpu.shutdown()
    results["chaos_off_task_sync_per_s"] = round(statistics.median(off_rates), 1)
    results["chaos_inert_plan_task_sync_per_s"] = round(statistics.median(on_rates), 1)
    results["chaos_inert_plan_overhead_pct"] = round(
        (statistics.median(ratios) - 1.0) * 100.0, 2
    )
    results["chaos_overhead_pairs"] = pairs
    print(
        f"chaos plane: inert-plan overhead {results['chaos_inert_plan_overhead_pct']}% "
        f"(no-plan {results['chaos_off_task_sync_per_s']}/s vs inert "
        f"{results['chaos_inert_plan_task_sync_per_s']}/s over {pairs} ABBA pairs); "
        f"disabled (no plan) is the production arm — its seam cost is one "
        f"is-None check per frame, upper-bounded by the inert-plan arm"
    )


def compute_deltas_vs_prev(results: dict, round_no: int, prev_path: str | None = None):
    """Diff numeric metrics against the previous round's artifact so a
    regression is named IN the artifact, not discovered by a later reviewer
    (VERDICT r5 #8). Keys ending in _per_s count as higher-is-better;
    regressions beyond 5% are listed explicitly."""
    if prev_path is None:
        prev_path = f"MICROBENCH_r{round_no - 1}.json"
    block: dict = {"prev_artifact": prev_path if os.path.exists(prev_path) else None}
    if block["prev_artifact"]:
        with open(prev_path) as f:
            prev = json.load(f)
        deltas = {}
        for key, cur in results.items():
            pv = prev.get(key)
            if (
                isinstance(cur, (int, float))
                and isinstance(pv, (int, float))
                and not isinstance(cur, bool)
                and pv
            ):
                deltas[key] = {"prev": pv, "cur": cur, "pct": round((cur - pv) / pv * 100.0, 1)}
        block["deltas"] = deltas
        block["regressions"] = sorted(
            key
            for key, d in deltas.items()
            if key.endswith("_per_s") and d["pct"] < -5.0
        )
    results["deltas_vs_prev"] = block


def queued_tasks_stress(results, n_tasks):
    """Queue-depth envelope (reference table: 1M+ tasks queued on one node).
    Submission throughput with the queue far beyond execution capacity, then
    a liveness check that the node still schedules."""
    import ray_tpu

    ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote
    def noop():
        return 1

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n_tasks)]
    submit_s = time.perf_counter() - t0
    results["queued_tasks"] = n_tasks
    results["queued_submit_per_s"] = round(n_tasks / submit_s, 1)
    # refs[0] has usually already finished by the end of submission — its
    # latency measures result availability, not liveness.
    t0 = time.perf_counter()
    assert ray_tpu.get(refs[0], timeout=120) == 1
    results["queued_first_result_s"] = round(time.perf_counter() - t0, 3)
    # Liveness under depth: the node must still be scheduling with the queue
    # ~full, proven by draining through the 1000th submitted task (full-queue
    # FIFO drain would take ages; a mid-queue probe shows forward progress).
    probe = min(n_tasks, 1000) - 1
    t0 = time.perf_counter()
    assert ray_tpu.get(refs[probe], timeout=600) == 1
    results["queued_probe_result_s"] = round(time.perf_counter() - t0, 3)
    ray_tpu.shutdown()


def actor_swarm_stress(results, n_actors):
    """Actor-count envelope, sized to this host (reference: 40k across a
    2000-node cluster; one core here). Measures creation + fan-out ping."""
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, n_actors), object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote(num_cpus=0.01)
    class Swarm:
        def ping(self):
            return os.getpid()

    t0 = time.perf_counter()
    actors = [Swarm.remote() for _ in range(n_actors)]
    pids = ray_tpu.get([a.ping.remote() for a in actors], timeout=1200)
    create_s = time.perf_counter() - t0
    results["actors_created"] = n_actors
    results["actor_processes"] = len(set(pids))
    results["actor_create_per_s"] = round(n_actors / create_s, 2)
    t0 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
    results["actor_fanout_ping_s"] = round(time.perf_counter() - t0, 3)
    ray_tpu.shutdown()


def broadcast_stress(results, mib, n_nodes):
    """100 MiB broadcast across simulated nodes (reference envelope: 1 GiB to
    50+ nodes; binomial-tree push plane)."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.object_transfer import broadcast_object

    cluster = Cluster()
    try:
        for _ in range(n_nodes):
            cluster.add_node(num_cpus=1, object_store_memory=(mib + 32) * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        import ray_tpu

        data = np.random.default_rng(0).integers(0, 255, mib * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(data)
        t0 = time.perf_counter()
        pushed = broadcast_object(ref, timeout=1200)
        dt = time.perf_counter() - t0
        results["broadcast_mib"] = mib
        results["broadcast_nodes"] = n_nodes
        results["broadcast_pushed"] = pushed
        results["broadcast_s"] = round(dt, 3)
        results["broadcast_aggregate_mib_per_s"] = round(mib * pushed / dt, 1)
    finally:
        cluster.shutdown()


def many_args_stress(results, n_args):
    """Reference envelope: 10,000+ object args to a single task
    (release/benchmarks/single_node/test_single_node.py test_many_args)."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, object_store_memory=512 * 1024 * 1024)

    @ray_tpu.remote
    def consume(*args):
        return len(args)

    refs = [ray_tpu.put(i) for i in range(n_args)]
    t0 = time.perf_counter()
    assert ray_tpu.get(consume.remote(*refs), timeout=600) == n_args
    results["many_args"] = n_args
    results["many_args_s"] = round(time.perf_counter() - t0, 3)
    ray_tpu.shutdown()


def many_returns_stress(results, n_returns):
    """Reference envelope: 3,000+ returns from a single task
    (test_single_node.py test_many_returns)."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, object_store_memory=512 * 1024 * 1024)

    @ray_tpu.remote
    def produce(n):
        return list(range(n))

    t0 = time.perf_counter()
    refs = produce.options(num_returns=n_returns).remote(n_returns)
    values = ray_tpu.get(refs, timeout=600)
    assert values == list(range(n_returns))
    results["many_returns"] = n_returns
    results["many_returns_s"] = round(time.perf_counter() - t0, 3)
    ray_tpu.shutdown()


def get_many_objects_stress(results, n_objects):
    """Reference envelope: ray.get on 10,000+ store objects in one call
    (test_single_node.py test_ray_get_args)."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, object_store_memory=512 * 1024 * 1024)
    refs = [ray_tpu.put(i) for i in range(n_objects)]
    t0 = time.perf_counter()
    values = ray_tpu.get(refs, timeout=600)
    dt = time.perf_counter() - t0
    assert values == list(range(n_objects))
    results["get_many_objects"] = n_objects
    results["get_many_objects_s"] = round(dt, 3)
    results["get_many_objects_per_s"] = round(n_objects / dt, 1)
    ray_tpu.shutdown()


def shuffle_stress(results, n_rows, n_blocks):
    """Dataset shuffle throughput, pull-based vs push-based (reference:
    push_based_shuffle.py + shuffle nightly suites)."""
    import ray_tpu
    from ray_tpu import data
    from ray_tpu.data.context import DataContext

    ray_tpu.init(num_cpus=4, object_store_memory=512 * 1024 * 1024)
    ctx = DataContext.get_current()
    try:
        # Warmup: spawn the worker pool so the first timed mode doesn't pay
        # cluster cold-start.
        data.range(1000, parallelism=4).random_shuffle(seed=0).count()
        for label, flag in (("pull", False), ("push", True)):
            ctx.use_push_based_shuffle = flag
            t0 = time.perf_counter()
            ds = data.range(n_rows, parallelism=n_blocks).random_shuffle(seed=0)
            assert ds.count() == n_rows
            dt = time.perf_counter() - t0
            results[f"shuffle_{label}_rows_per_s"] = round(n_rows / dt, 1)
        results["shuffle_rows"] = n_rows
        results["shuffle_blocks"] = n_blocks
    finally:
        ctx.use_push_based_shuffle = None
        ray_tpu.shutdown()


def transfer_suite(results, quick=False):
    """--transfer: the ISSUE 10 transfer-plane A/B — cut-through broadcast at
    the r5 shape, pull striping (1 vs 2 replicas), raw-vs-msgpack frame
    framing on a point-to-point push — plus the dispatch-plane regression
    guards (putget_1mib, shuffle_push) the rpc.py changes must not move."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu._private.transfer_stats import TRANSFER
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.object_transfer import broadcast_object

    io = EventLoopThread.get()

    def oid_for(tag):
        return tag.encode().hex().ljust(56, "0")[:56]

    def seal_raw(node, oid, data):
        offset = io.run(node.store.create(oid, len(data)))
        node.arena.write(offset, data)
        node.store.seal(oid)
        io.run(node.gcs.acall(
            "add_object_location", {"object_id": oid, "node_id": node.node_id}
        ))

    # --- point-to-point push: raw frames vs forced msgpack fallback ---
    mib_p2p = 16 if quick else 64
    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=1, object_store_memory=(mib_p2p + 64) * 1024 * 1024)
            for _ in range(3)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        head, n2, n3 = nodes
        payload = np.random.default_rng(0).integers(
            0, 255, mib_p2p * 1024 * 1024, dtype=np.uint8
        ).tobytes()
        # Median of 3 pushes per framing: single pushes swing with this
        # box's multi-second noise bursts (PERF_NOTES measurement traps).
        for label, raw in (("raw", True), ("msgpack", False)):
            n2.raw_frames_enabled = raw
            head.push_manager.raw_enabled = raw
            times = []
            for i in range(3):
                oid = oid_for(f"p2p-{label}-{i}")
                seal_raw(head, oid, payload)
                t0 = time.perf_counter()
                resp = io.run(
                    head.push_manager.push(oid, n2.node_id, n2.address), timeout=600
                )
                times.append(time.perf_counter() - t0)
                assert resp["ok"], resp
                for n in nodes:
                    try:
                        n.store.delete(oid)
                    except Exception:
                        pass
            results[f"push_{label}_mib_per_s"] = round(
                mib_p2p / sorted(times)[len(times) // 2], 1
            )
        n2.raw_frames_enabled = True
        head.push_manager.raw_enabled = True
        results["push_p2p_mib"] = mib_p2p
        results["push_raw_speedup_pct"] = round(
            (results["push_raw_mib_per_s"] / results["push_msgpack_mib_per_s"] - 1)
            * 100.0,
            1,
        )

        # --- pull striping: same object from 1 replica vs 2 replicas ---
        # Loopback on this one-core box has NO per-source parallelism (every
        # in-process "node" shares one IO loop and one CPU), so the striping
        # win is measured over a modeled per-source link: each source serves
        # chunks through a serialized bandwidth gate (asyncio lock + sleep =
        # a NIC at `link_mib_per_s`), which is exactly the resource striping
        # doubles in a real fleet. Unthrottled loopback numbers are recorded
        # alongside for transparency.
        import asyncio as _asyncio

        mib_pull = 8 if quick else 32
        link_mib_per_s = 64
        pdata = np.random.default_rng(1).integers(
            0, 255, mib_pull * 1024 * 1024, dtype=np.uint8
        ).tobytes()

        def throttle(node):
            orig = node.server._handlers["fetch_object_chunk"]
            gate = _asyncio.Lock()

            async def serve(req, _orig=orig, _gate=gate):
                async with _gate:  # one chunk on the "wire" at a time
                    await _asyncio.sleep(
                        req["length"] / (link_mib_per_s * 1024 * 1024)
                    )
                return await _orig(req)

            node.server._handlers["fetch_object_chunk"] = serve
            return orig

        def timed_pull(tag, replicas, throttled):
            origs = [(r, throttle(r)) for r in replicas] if throttled else []
            try:
                times = []
                for i in range(3):
                    oid = oid_for(f"{tag}-{i}")
                    for r in replicas:
                        seal_raw(r, oid, pdata)
                    t0 = time.perf_counter()
                    assert io.run(n3.pull_manager.pull(oid, 300.0), timeout=600)
                    times.append(time.perf_counter() - t0)
                    for n in nodes:
                        try:
                            n.store.delete(oid)
                        except Exception:
                            pass
                return sorted(times)[len(times) // 2]
            finally:
                for r, orig in origs:
                    r.server._handlers["fetch_object_chunk"] = orig

        dt1 = timed_pull("pl1", [head], throttled=True)
        dt2 = timed_pull("pl2", [head, n2], throttled=True)
        lb1 = timed_pull("lb1", [head], throttled=False)
        lb2 = timed_pull("lb2", [head, n2], throttled=False)
        results["pull_mib"] = mib_pull
        results["pull_link_model_mib_per_s"] = link_mib_per_s
        results["pull_1replica_mib_per_s"] = round(mib_pull / dt1, 1)
        results["pull_2replica_mib_per_s"] = round(mib_pull / dt2, 1)
        results["pull_striping_speedup_pct"] = round((dt1 / dt2 - 1) * 100.0, 1)
        results["pull_loopback_1replica_mib_per_s"] = round(mib_pull / lb1, 1)
        results["pull_loopback_2replica_mib_per_s"] = round(mib_pull / lb2, 1)
        results["transfer_chunks_raw"] = TRANSFER.chunks_raw_out
        results["transfer_chunks_msgpack"] = TRANSFER.chunks_msgpack_out
        results["transfer_relays"] = TRANSFER.relays
    finally:
        cluster.shutdown()


def serve_llm_suite(results, quick=False):
    """--serve: the ISSUE 11 continuous-batching A/B (SERVEBENCH_r{N}.json).

    A closed-loop load generator drives the serve.llm engine directly (the
    scheduler IS the claim; the HTTP/SSE envelope above it is exercised by
    tests/test_serve_llm_engine.py): N streams, each submitting a request
    with a shared 32-token system prompt + random suffix and a heavy-tailed
    (geometric — realistic output-length distribution) max_new_tokens,
    reading its token stream to completion, then immediately submitting the
    next. Two arms on the SAME model/params/slots:

    - serial:     `serial_batch=True` — the pre-engine behavior (admit only
                  into an idle engine, batch decodes in lockstep, slots idle
                  while the longest sequence drains, arrivals wait out the
                  whole batch). This is what a replica wrapping generate()
                  gives you.
    - continuous: slot-level admission mid-decode + chunked prefill
                  interleave + prefix-cache reuse.

    Metrics per arm: p50/p99 TTFT, mean time-per-output-token, aggregate
    tokens/s over the measurement window. Why continuous wins tokens/s:
    decode step latency is dominated by per-step fixed cost (weight
    streaming on TPU, dispatch on this CPU box), nearly flat in batch
    occupancy — so tokens/s tracks slot utilization, which serial batching
    caps at mean(len)/max(len) per batch."""
    import statistics
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.transformer import TransformerConfig, init_params
    from ray_tpu.serve.llm import LLMEngine, prefix_route_hint

    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=256, max_seq_len=512, dtype=jnp.float32, remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    # Oversubscribed offered load (streams > slots): the admission queue is
    # never empty, which is exactly the regime continuous batching targets —
    # a request arriving mid-decode queues behind the WHOLE draining batch
    # in the serial arm but takes the first freed slot in the continuous one.
    streams = 4 if quick else 12
    slots = 8
    duration = 3.0 if quick else 25.0
    block_size = 16
    system = list(range(7, 7 + 32))  # two full blocks shared by every stream
    results["serve_streams"] = streams
    results["serve_slots"] = slots
    results["serve_block_size"] = block_size
    results["serve_prefix_hint"] = prefix_route_hint(system, block_size)[:12]

    def run_arm(serial: bool) -> dict:
        engine = LLMEngine(
            params, cfg, num_slots=slots, block_size=block_size,
            max_model_len=192, prefill_chunk=32, serial_batch=serial,
        )
        try:
            # Warm both compiled programs outside the window.
            engine.submit(system + [1, 2, 3], max_new_tokens=4).result(300)
            stop = threading.Event()
            ttfts, tpots, tokens = [], [], [0]
            t_win = [0.0, 0.0]
            lock = threading.Lock()

            def stream(i):
                rng = np.random.default_rng(1000 + i)
                while not stop.is_set():
                    suffix = rng.integers(0, 256, int(rng.integers(8, 33))).tolist()
                    # Heavy-tailed output length (geometric, mean ~24, tail
                    # to 128 = max_model_len - longest prompt): realistic
                    # LLM completions — and exactly the shape that makes
                    # lockstep batches idle their short-sequence slots.
                    n_new = int(min(128, max(4, rng.geometric(1.0 / 24))))
                    t0 = time.perf_counter()
                    req = engine.submit(system + suffix, max_new_tokens=n_new)
                    first = None
                    for _ in req:
                        now = time.perf_counter()
                        if first is None:
                            first = now
                        if stop.is_set() and t_win[1]:
                            break  # window closed; drop the tail
                        with lock:
                            tokens[0] += 1
                    engine.cancel(req)  # no-op unless we broke early
                    if first is not None and not stop.is_set():
                        with lock:
                            ttfts.append(first - t0)
                            n_stream = req.num_generated
                            if n_stream > 1:
                                tpots.append((time.perf_counter() - first) / (n_stream - 1))

            threads = [
                threading.Thread(target=stream, args=(i,), daemon=True)
                for i in range(streams)
            ]
            t_win[0] = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(duration)
            stop.set()
            t_win[1] = time.perf_counter()
            for t in threads:
                t.join(timeout=120)
            wall = t_win[1] - t_win[0]
            st = engine.stats()
            ttfts.sort()

            def pct(xs, p):
                return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else None

            return {
                "tokens_per_s": round(tokens[0] / wall, 1),
                "requests_completed": len(ttfts),
                "ttft_p50_ms": round(1000 * pct(ttfts, 0.50), 1) if ttfts else None,
                "ttft_p99_ms": round(1000 * pct(ttfts, 0.99), 1) if ttfts else None,
                "tpot_mean_ms": round(1000 * statistics.mean(tpots), 2) if tpots else None,
                "preemptions": st["preemptions"],
                "prefix_hit_blocks": st["prefix_hit_blocks"],
                "admitted": st["admitted"],
            }
        finally:
            engine.shutdown()

    for label, serial in (("serial", True), ("continuous", False)):
        arm = run_arm(serial)
        for k, v in arm.items():
            results[f"serve_{label}_{k}"] = v
        print(f"serve[{label}]: {arm}")
    results["serve_tokens_speedup"] = round(
        results["serve_continuous_tokens_per_s"]
        / max(results["serve_serial_tokens_per_s"], 1e-9),
        2,
    )
    if results.get("serve_serial_ttft_p99_ms") and results.get("serve_continuous_ttft_p99_ms"):
        results["serve_ttft_p99_reduction_pct"] = round(
            (1 - results["serve_continuous_ttft_p99_ms"] / results["serve_serial_ttft_p99_ms"])
            * 100.0,
            1,
        )


def serve_ft_suite(results, quick=False):
    """--serve-ft: self-healing LLM serving (ISSUE 14) — FTBENCH_r{N}.json.

    End to end over a REAL serve instance (cluster + controller + proxy +
    2 LLM replicas), because the claims live in the proxy/controller, not
    the engine:

    - KILL arm: a seeded plan SIGKILLs the serving replica mid-stream (Nth
      actor-call response); the proxy migrates the request with
      resume_tokens= teacher-forced on a live replica. Reported:
      time-to-stream-resume at the CLIENT (the max inter-token gap — the
      kill->first-resumed-token stall dominates it), byte-exactness vs an
      uninterrupted oracle run, dropped streams (must be 0).
    - ROLLING arm, drain ON vs OFF: a closed loop of concurrent streams
      rides a v(n) -> v(n+1) rolling update. Drain ON (default 30s bound)
      retires old replicas only after their streams finish: zero drops AND
      zero forced migrations. Drain OFF (drain_timeout_s=0, the pre-ISSUE
      behavior) kills old replicas under live streams: the streams only
      survive because the MIGRATION path catches them — visible as forced
      migrations + a fatter p99 inter-token stall.
    """
    import threading
    import urllib.request

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.serve._private.common import PREFIX_HINT_HEADER
    from ray_tpu.serve.llm import LLMDeployment, prefix_route_hint

    model = dict(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=48, max_seq_len=64, dtype="float32", remat=False,
    )
    engine_cfg = dict(num_slots=4, block_size=4, max_model_len=64, prefill_chunk=4)
    n_tokens = 16 if quick else 32
    results["serve_ft_tokens_per_stream"] = n_tokens

    def oracle(prompt, n):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import TransformerConfig, init_params
        from ray_tpu.serve.llm import LLMEngine

        kw = dict(model)
        kw["dtype"] = jnp.dtype(kw["dtype"]).type
        cfg = TransformerConfig(**kw)
        eng = LLMEngine(init_params(jax.random.PRNGKey(0), cfg), cfg, **engine_cfg)
        try:
            return eng.submit(prompt, max_new_tokens=n).result(120)
        finally:
            eng.shutdown()

    def stream(url, body, headers=None, timeout=240):
        """Returns (tokens, done, [arrival stamps])."""
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(), headers=headers or {}
        )
        resp = urllib.request.urlopen(req, timeout=timeout)
        toks, stamps, buf = [], [], b""
        while True:
            chunk = resp.read(64)
            if not chunk:
                return toks, False, stamps
            buf += chunk
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                if not event.startswith(b"data: "):
                    continue
                payload = event[6:]
                if payload == b"[DONE]":
                    return toks, True, stamps
                toks.append(json.loads(payload)["token"])
                stamps.append(time.perf_counter())

    def deploy(version, drain_timeout_s=30.0):
        app = serve.deployment(
            num_replicas=2, version=version, drain_timeout_s=drain_timeout_s
        )(LLMDeployment).bind(model, engine_config=dict(engine_cfg))
        serve.run(app, route_prefix="/llm")

    def replica_actors():
        from ray_tpu.serve._private.common import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        table = ray_tpu.get(controller.get_routing_table.remote(-2, 0.1))["table"]
        return [r["actor_name"] for r in table.get("LLMDeployment", {}).get("replicas", [])]

    def flight_count(cluster, kind, since):
        io = EventLoopThread.get()
        resp = io.run(cluster.nodes[0].rpc_debug_dump({}), timeout=15)
        return sum(
            1
            for proc in resp.get("processes", [])
            for ev in proc.get("events", [])
            if ev.get("type") == kind and ev.get("ts", 0) >= since - 1.0
        )

    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=6, object_store_memory=96 * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        serve.start()
        deploy("v1")
        host, port = serve.http_address()
        url = f"http://{host}:{port}/llm"

        # ---- KILL arm: seeded mid-stream replica kill -> migration ----
        import zlib

        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        expect = oracle(prompt, n_tokens)
        stream(url, dict(tokens=prompt, max_new_tokens=4))  # warm both paths
        t_since = time.time()
        hint = prefix_route_hint(prompt, engine_cfg["block_size"])
        actors = replica_actors()
        victim = actors[zlib.crc32(hint.encode()) % len(actors)]
        assert cluster.install_plan_in_actor(
            victim,
            {"rules": [{"kind": "kill", "method": ["actor_call"],
                        "side": "resp", "after": 2, "times": 1}]},
            seed=13,
        )
        t0 = time.perf_counter()
        toks, done, stamps = stream(
            url, dict(tokens=prompt, max_new_tokens=n_tokens),
            headers={PREFIX_HINT_HEADER: hint},
        )
        gaps = [b - a for a, b in zip(stamps, stamps[1:])] or [0.0]
        results["kill_stream_ok"] = bool(done and toks == expect)
        results["kill_stream_wall_s"] = round(time.perf_counter() - t0, 3)
        results["kill_time_to_stream_resume_s"] = round(max(gaps), 3)
        results["kill_median_token_gap_ms"] = round(
            1000 * sorted(gaps)[len(gaps) // 2], 2
        )
        results["kill_migrations"] = flight_count(cluster, "llm_migrate", t_since)
        results["kill_chaos_kills"] = flight_count(cluster, "chaos_kill", t_since)
        print(
            f"serve-ft[kill]: ok={results['kill_stream_ok']} "
            f"resume={results['kill_time_to_stream_resume_s']}s "
            f"migrations={results['kill_migrations']}"
        )
        # Let the controller finish replacing the victim before the next arm.
        deadline = time.monotonic() + 120
        while len(replica_actors()) < 2 and time.monotonic() < deadline:
            time.sleep(0.25)

        # ---- ROLLING arm: drain ON vs OFF under a closed loop ----
        def rolling_arm(label, old_version, new_version, drain_timeout_s):
            # (Re)deploy the old version with the arm's drain config, then
            # roll under load.
            deploy(old_version, drain_timeout_s=drain_timeout_s)
            rng = np.random.default_rng(5)
            prompts = [rng.integers(0, 64, 6).tolist() for _ in range(3)]
            oracles = [oracle(p, n_tokens) for p in prompts]
            t_since = time.time()
            stop = threading.Event()
            drops, completions, corrupt = [], [0], []
            gaps_all: list = []
            lock = threading.Lock()

            def loop(i):
                while not stop.is_set():
                    try:
                        toks, done, stamps = stream(
                            url, dict(tokens=prompts[i], max_new_tokens=n_tokens)
                        )
                        if not done:
                            drops.append(i)
                            return
                        if toks != oracles[i]:
                            corrupt.append(i)
                            return
                        with lock:
                            completions[0] += 1
                            gaps_all.extend(
                                b - a for a, b in zip(stamps, stamps[1:])
                            )
                    except Exception as e:  # noqa: BLE001
                        drops.append(f"{i}:{type(e).__name__}")
                        return

            threads = [
                threading.Thread(target=loop, args=(i,), daemon=True)
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while completions[0] < 2 and not drops and time.monotonic() < deadline:
                time.sleep(0.05)
            t_roll = time.perf_counter()
            deploy(new_version, drain_timeout_s=drain_timeout_s)
            roll_wall = time.perf_counter() - t_roll
            time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join(timeout=300)
            gaps_all.sort()
            p99 = gaps_all[min(len(gaps_all) - 1, int(0.99 * len(gaps_all)))] if gaps_all else 0.0
            out = {
                "dropped_streams": len(drops) + len(corrupt),
                "completed_streams": completions[0],
                "rolling_update_wall_s": round(roll_wall, 2),
                "stall_p99_ms": round(1000 * p99, 1),
                "max_stall_ms": round(1000 * (gaps_all[-1] if gaps_all else 0.0), 1),
                "migrations": flight_count(cluster, "llm_migrate", t_since),
                "drains_recorded": flight_count(cluster, "replica_drain", t_since),
            }
            for k, v in out.items():
                results[f"rolling_{label}_{k}"] = v
            print(f"serve-ft[rolling-{label}]: {out}")

        if not quick:
            rolling_arm("drain", "v2", "v3", drain_timeout_s=30.0)
            rolling_arm("nodrain", "v4", "v5", drain_timeout_s=0.0)
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def serve_disagg_suite(results, quick=False):
    """--serve-disagg: prefill/decode disaggregation + cluster prefix tier
    (ISSUE 20) — DISAGGBENCH_r{N}.json.

    End to end over a REAL serve instance (cluster + controller + proxy),
    because the claim lives in the pool split, not the engine: under MIXED
    load — long-prefill streams (384-token prompts on a compute-bound
    model, 4 output tokens: pure prefill pressure) interleaved with
    short-decode streams (48-token prompts, 12 output tokens: the
    latency-sensitive traffic) — the
    monolithic arm makes every short stream's prefill queue FIFO behind
    whatever long prefill its replica is already chewing, while the
    disaggregated arm routes prefills to a dedicated pool (where SJF lets
    shorts jump the queue), seals the KV as a device object, and hands the
    ~300B descriptor to an uncontended decode pool over direct-mailbox p2p.

    Arms at EQUAL replica budget (4 engines each):
    - mono:   4 replicas, role "both" — continuous batching, no handoff.
    - disagg: 2 prefill + 2 decode replicas with the cluster prefix tier ON
              (2 prefill replicas so the registry actually cross-imports:
              a replica skips its own published rows).

    Per arm: p50/p99 TTFT of the SHORT streams, aggregate tokens/s across
    all streams, completed-request counts. The disagg arm also records the
    deterministic evidence: KV handoff count (decode-side imports, flight
    + engine counters agreeing), cluster-prefix import hits (>0 — seeded
    by a serial warm round-robining the shared system prefix over both
    prefill replicas), host-store object delta over the measured window
    (0: descriptors ride actor RPC, payloads ride direct mailboxes), and
    the leak oracle — every engine's free+cached block count restored to
    pool size after the load quiesces."""
    import statistics
    import threading
    import urllib.request

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import worker_context
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.serve.llm import LLMDeployment, disaggregated_llm_app

    if quick:
        # Machinery smoke: a dispatch-bound tiny model CANNOT show the TTFT
        # story on this box (prefill costs less than one HTTP hop, so the
        # handoff's fixed overhead dominates) — the quick pass only proves
        # the plumbing: handoffs flow, prefix tier hits, zero store delta,
        # zero leaked blocks. Ratio certification lives in the full sweep.
        model = dict(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
            d_ff=48, max_seq_len=160, dtype="float32", remat=False,
        )
        engine_cfg = dict(
            num_slots=4, block_size=4, max_model_len=160, prefill_chunk=8
        )
        system = list(range(5, 5 + 16))  # 4 full blocks shared by every stream
        long_prompt_len, short_new = 96, 12
        n_long, n_short = 2, 2
        duration = 5.0
    else:
        # Full sweep: a COMPUTE-bound model (a 384-token prefill costs
        # hundreds of ms of matmul on this box — far above the per-hop
        # dispatch cost), so a short stream queued FIFO behind a long
        # prefill in the monolithic arm pays real latency, which is the
        # regime disaggregation (SJF prefill pool + uncontended decode
        # pool) targets.
        model = dict(
            vocab_size=128, d_model=256, n_layers=6, n_heads=4, n_kv_heads=2,
            d_ff=1536, max_seq_len=512, dtype="float32", remat=False,
        )
        engine_cfg = dict(
            num_slots=4, block_size=16, max_model_len=448, prefill_chunk=16
        )
        system = list(range(5, 5 + 32))  # 2 full blocks shared by every stream
        long_prompt_len, short_new = 384, 12
        n_long, n_short = 4, 4
        duration = 25.0
    vocab = model["vocab_size"]
    suffix_len = len(system) // 2
    results.update(
        disagg_streams_long=n_long,
        disagg_streams_short=n_short,
        disagg_long_prompt_tokens=long_prompt_len,
        disagg_short_prompt_tokens=len(system) + suffix_len,
        disagg_short_new_tokens=short_new,
        disagg_window_s=duration,
        disagg_replicas={"mono": 4, "prefill": 2, "decode": 2},
        disagg_model={k: v for k, v in model.items() if k != "dtype"},
    )

    def stream(url, body, timeout=240):
        """Returns (tokens, done, [arrival stamps])."""
        req = urllib.request.Request(url, data=json.dumps(body).encode())
        resp = urllib.request.urlopen(req, timeout=timeout)
        toks, stamps, buf = [], [], b""
        while True:
            chunk = resp.read(64)
            if not chunk:
                return toks, False, stamps
            buf += chunk
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                if not event.startswith(b"data: "):
                    continue
                payload = event[6:]
                if payload == b"[DONE]":
                    return toks, True, stamps
                toks.append(json.loads(payload)["token"])
                stamps.append(time.perf_counter())

    def flight_count(cluster, kind, since):
        io = EventLoopThread.get()
        resp = io.run(cluster.nodes[0].rpc_debug_dump({}), timeout=15)
        return sum(
            1
            for proc in resp.get("processes", [])
            for ev in proc.get("events", [])
            if ev.get("type") == kind and ev.get("ts", 0) >= since - 1.0
        )

    def replica_stats(dep_names):
        from ray_tpu.serve._private.common import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        table = ray_tpu.get(controller.get_routing_table.remote(-2, 0.1))["table"]
        out = {}
        for dep in dep_names:
            stats = []
            for r in table.get(dep, {}).get("replicas", []):
                a = ray_tpu.get_actor(r["actor_name"])
                stats.append(
                    ray_tpu.get(
                        a.handle_request.remote("get_stats", (), {}), timeout=30
                    )
                )
            out[dep] = stats
        return out

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else None

    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=12, object_store_memory=96 * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        cw = worker_context.get_core_worker()

        def store_objects() -> int:
            return cw.raylet.call("get_state")["store"]["num_objects"]

        def run_arm(label, deploy_fn, dep_names):
            serve.start()
            deploy_fn()
            host, port = serve.http_address()
            url = f"http://{host}:{port}/llm"
            # Warm every compiled program AND (disagg) seed the cluster
            # prefix tier deterministically: 4 serial shared-prefix shorts
            # round-robin over both prefill replicas, so replica B's probe
            # finds replica A's published system-prefix row. One long warms
            # the long-prompt prefill shape.
            t_since = time.time()
            rng = np.random.default_rng(7)
            for i in range(4):
                suffix = rng.integers(0, vocab, suffix_len).tolist()
                toks, done, _ = stream(
                    url, dict(tokens=system + suffix, max_new_tokens=4)
                )
                assert done and len(toks) == 4, (label, i, toks, done)
            stream(
                url,
                dict(
                    tokens=system
                    + rng.integers(0, vocab, long_prompt_len - len(system)).tolist(),
                    max_new_tokens=2,
                ),
            )
            store_before = store_objects()
            stop = threading.Event()
            lock = threading.Lock()
            short_ttfts: list = []
            counts = {"tokens": 0, "short_done": 0, "long_done": 0, "errors": 0}

            def short_loop(i):
                srng = np.random.default_rng(100 + i)
                while not stop.is_set():
                    suffix = srng.integers(0, vocab, suffix_len).tolist()
                    t0 = time.perf_counter()
                    try:
                        toks, done, stamps = stream(
                            url, dict(tokens=system + suffix, max_new_tokens=short_new)
                        )
                    except Exception:
                        with lock:
                            counts["errors"] += 1
                        return
                    if not done:
                        continue
                    with lock:
                        counts["tokens"] += len(toks)
                        if not stop.is_set():
                            counts["short_done"] += 1
                            short_ttfts.append(stamps[0] - t0)

            def long_loop(i):
                lrng = np.random.default_rng(200 + i)
                while not stop.is_set():
                    body = lrng.integers(
                        0, vocab, long_prompt_len - len(system)
                    ).tolist()
                    try:
                        toks, done, _ = stream(
                            url, dict(tokens=system + body, max_new_tokens=4)
                        )
                    except Exception:
                        with lock:
                            counts["errors"] += 1
                        return
                    with lock:
                        counts["tokens"] += len(toks)
                        if done and not stop.is_set():
                            counts["long_done"] += 1

            threads = [
                threading.Thread(target=long_loop, args=(i,), daemon=True)
                for i in range(n_long)
            ] + [
                threading.Thread(target=short_loop, args=(i,), daemon=True)
                for i in range(n_short)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(duration)
            stop.set()
            for t in threads:
                t.join(timeout=300)
            wall = time.perf_counter() - t0
            assert counts["errors"] == 0, (label, counts)
            short_ttfts.sort()
            arm = {
                "tokens_per_s": round(counts["tokens"] / wall, 1),
                "short_completed": counts["short_done"],
                "long_completed": counts["long_done"],
                "short_ttft_p50_ms": round(1000 * pct(short_ttfts, 0.50), 1)
                if short_ttfts
                else None,
                "short_ttft_p99_ms": round(1000 * pct(short_ttfts, 0.99), 1)
                if short_ttfts
                else None,
                "short_ttft_mean_ms": round(1000 * statistics.mean(short_ttfts), 1)
                if short_ttfts
                else None,
            }
            # Handoff-path host-store evidence: descriptors ride actor RPC,
            # KV payloads ride direct mailboxes — the measured window must
            # add NOTHING to the node's shm store (bounded settle for the
            # proxy's async stream-buffer frees).
            deadline = time.monotonic() + 30
            delta = store_objects() - store_before
            while delta > 0 and time.monotonic() < deadline:
                time.sleep(0.25)
                delta = store_objects() - store_before
            arm["store_objects_delta"] = delta
            # Leak oracle: every engine's KV pool back to full (free blocks
            # + resident prefix-cache blocks == pool size) once idle.
            deadline = time.monotonic() + 30
            while True:
                stats = replica_stats(dep_names)
                leak = sum(
                    s["num_blocks"] - s["free_blocks"] - s["cached_blocks"]
                    for ss in stats.values()
                    for s in ss
                )
                if leak == 0 or time.monotonic() > deadline:
                    break
                time.sleep(0.25)
            arm["kv_leak_blocks"] = leak
            for k, v in arm.items():
                results[f"{label}_{k}"] = v
            print(f"serve-disagg[{label}]: {arm}")
            return stats, t_since

        # ---- mono arm: 4 role-"both" replicas, no pools ----
        def deploy_mono():
            app = serve.deployment(num_replicas=4, name="llm")(LLMDeployment).bind(
                model_config=model, engine_config=dict(engine_cfg)
            )
            serve.run(app, route_prefix="/llm")

        mono_stats, _ = run_arm("mono", deploy_mono, ["llm"])
        assert all(s["handoffs"] == 0 for s in mono_stats["llm"]), mono_stats
        serve.shutdown()

        # ---- disagg arm: 2 prefill + 2 decode, cluster prefix tier ON ----
        def deploy_disagg():
            serve.run(
                disaggregated_llm_app(
                    model,
                    dict(engine_cfg),
                    name="llm",
                    prefill_replicas=2,
                    decode_replicas=2,
                    cluster_prefix=True,
                )
            )

        disagg_stats, t_since = run_arm(
            "disagg", deploy_disagg, ["llm", "llm--prefill"]
        )
        dec = disagg_stats["llm"]
        pre = disagg_stats["llm--prefill"]
        results["disagg_handoffs"] = sum(s["handoffs"] for s in dec)
        results["disagg_handoff_exports"] = sum(s["handoff_exports"] for s in pre)
        results["disagg_handoff_failed"] = sum(
            s["handoff_failed"] for s in dec + pre
        )
        results["disagg_prefix_import_hits"] = sum(
            s["prefix_import_hits"] for s in pre
        )
        results["disagg_prefix_import_misses"] = sum(
            s["prefix_import_misses"] for s in pre
        )
        results["disagg_published_prefixes"] = sum(
            s["published_prefixes"] for s in pre
        )
        results["disagg_handoff_flight_events"] = flight_count(
            cluster, "llm_kv_handoff", t_since
        )
        results["disagg_prefix_import_flight_events"] = flight_count(
            cluster, "llm_prefix_import", t_since
        )
        # Pool-role hygiene: decode replicas never prefill-published, and
        # every completed stream rode a handoff (no silent mono fallback).
        assert all(s["role"] == "decode" for s in dec), dec
        assert all(s["role"] == "prefill" for s in pre), pre
        assert results["disagg_handoffs"] > 0, results
        assert results["disagg_prefix_import_hits"] > 0, results
        assert results["disagg_store_objects_delta"] == 0, results
        serve.shutdown()

        if results.get("mono_short_ttft_p99_ms") and results.get(
            "disagg_short_ttft_p99_ms"
        ):
            results["disagg_short_ttft_p99_reduction_pct"] = round(
                (
                    1
                    - results["disagg_short_ttft_p99_ms"]
                    / results["mono_short_ttft_p99_ms"]
                )
                * 100.0,
                1,
            )
        if results.get("mono_tokens_per_s"):
            results["disagg_tokens_vs_mono"] = round(
                results["disagg_tokens_per_s"] / results["mono_tokens_per_s"], 2
            )
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def putget_guard(results, duration):
    """1 MiB object-plane regression guard for the --transfer artifact: the
    rpc.py wire changes must not move the dispatch/store hot path.

    Methodology matches MICROBENCH_r5's basic_suite exactly (fresh cluster,
    ONE `duration`-second window of put then one of putget) so the numbers
    are comparable; the whole guard repeats 3× in a fresh cluster each time
    and reports the best window per metric — this box's noise is
    non-stationary multi-second bursts (PERF_NOTES measurement traps) that
    swing single windows ±30%, and repeating windows WITHIN one cluster is
    not an option: every extra put window leaves thousands of freed 1 MiB
    objects whose arena churn taxes the following putget window (cost a
    confusing hour in r10)."""
    import numpy as np

    import ray_tpu

    best_put, best_putget = 0.0, 0.0
    for _ in range(3):
        ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
        arr = np.zeros(1024 * 1024, dtype=np.uint8)
        best_put = max(best_put, timeit(lambda: ray_tpu.put(arr), duration))
        best_putget = max(
            best_putget, timeit(lambda: ray_tpu.get(ray_tpu.put(arr)), duration)
        )
        ray_tpu.shutdown()
    results["put_1mib_per_s"] = round(best_put, 1)
    results["putget_1mib_per_s"] = round(best_putget, 1)


def sim_suite(results, quick=False):
    """--sim: control-plane scale bench over simnode shells
    (SIMBENCH_r{N}.json). Four measurement families:

    1. NODE-COUNT SWEEP, before/after arms: boot + view-convergence time,
       stub-task throughput, p99 placement latency, and per-interval
       heartbeat view bytes with versioned delta sync ON vs the legacy
       full-view reply. The legacy arm's bytes/interval grow O(N) per
       raylet (O(N^2) cluster-wide); the delta arm's steady state is ~0 —
       the sub-quadratic evidence the acceptance gate asks for.
    2. NODE-DEATH directory cost: _on_node_death wall time over a seeded
       location table, per-node index vs legacy full scan.
    3. LOCALITY arms: fraction of reference-arg tasks landing on a holder
       with locality_aware_scheduling on vs off (the no-locality arm is
       the measured baseline, not a thought experiment).
    4. TASK-EVENT ingest: wire-path flood against the drop-oldest ring —
       ingest rate, ring bound honored, dropped counter.

    Plus the seeded sim-scale chaos SLO scorecard (tests/chaos_matrix.py
    run_sim_matrix). Everything runs in THIS process: shells are simnode
    shells, executors are stubs on a modeled clock (PARITY.md scale row).
    """
    import asyncio
    import statistics

    from ray_tpu._private.simnode import SimCluster, SimTraffic, _percentile

    window_s = 2.5 if quick else 4.0
    # The legacy arm's reply encode is O(N) per heartbeat: at 1000 shells
    # it saturates the loop outright (which IS the finding), so the
    # before-arm stops at 512 — the 64->512 curve establishes the growth —
    # while the delta arm runs through 1000. Heartbeat cadence relaxes
    # with N (real deployments do the same); the per-INTERVAL accounting
    # is cadence-normalized so arms stay comparable.
    if quick:
        sweep = [(64, ("delta", "legacy")), (128, ("delta", "legacy"))]
    else:
        # Legacy (full-view) arm is capped at 256 shells: at 512 the
        # O(N^2) reply traffic starves the burst loop past its 300 s
        # timeout on a single box — the collapse is already evidenced by
        # the 128->256 legacy rows (tasks/s 985 -> 115). Record the cap
        # in the artifact rather than truncating silently.
        sweep = [
            (128, ("delta", "legacy")),
            (256, ("delta", "legacy")),
            (512, ("delta",)),
            (1000, ("delta",)),
        ]
        results["sim_sweep_notes"] = (
            "legacy arm capped at 256 nodes: full-view replies at 512 "
            "shells exceed single-box capacity (task burst stalls past "
            "300 s); quadratic growth is evidenced by the 128->256 "
            "legacy rows, delta arms continue to 1000 nodes"
        )
    results["sim_sweep"] = {}

    for n_nodes, arms in sweep:
        hb_s = 0.25 if n_nodes <= 256 else 0.5
        for arm in arms:
            key = f"n{n_nodes}_{arm}"
            cfg = {
                "heartbeat_interval_s": hb_s,
                "node_death_timeout_s": 10.0,
                "heartbeat_delta_sync": arm == "delta",
            }
            t0 = time.perf_counter()
            c = SimCluster(
                n_nodes, resources_per_node={"CPU": 8},
                num_entry_nodes=16, _system_config=cfg,
            )
            c.start()
            boot_s = time.perf_counter() - t0
            c.wait_for_view(timeout=120)
            view_s = time.perf_counter() - t0

            # Heartbeat accounting over an idle window: what does merely
            # EXISTING at this scale cost the GCS reply path per interval?
            c.gcs.hb_stats = {
                "replies": 0, "rows": 0, "full_replies": 0, "view_bytes": 0,
            }
            c.gcs.hb_account = True
            time.sleep(window_s)
            c.gcs.hb_account = False
            hb = dict(c.gcs.hb_stats)
            intervals = max(1, round(window_s / hb_s))
            per_interval_bytes = hb["view_bytes"] / intervals
            per_interval_rows = hb["rows"] / intervals

            # Stub-task burst: throughput + placement tail over the real
            # submit wire.
            n_tasks = 2000 if quick else 5000
            t1 = time.perf_counter()

            async def _burst(cluster=c, total=n_tasks):
                step = 500
                for i in range(0, total, step):
                    await asyncio.gather(
                        *[
                            cluster.asubmit(cluster.make_spec(sim_ms=1.0))
                            for _ in range(step)
                        ]
                    )

            c._io.run(_burst(), timeout=300)
            assert c.wait_done(n_tasks, timeout=180), f"{key}: burst stalled"
            burst_s = time.perf_counter() - t1
            lat = c.placement_latencies()
            row = {
                "nodes": n_nodes,
                "arm": arm,
                "hb_interval_s": hb_s,
                "boot_s": round(boot_s, 2),
                "view_converge_s": round(view_s, 2),
                "hb_replies": hb["replies"],
                "hb_full_replies": hb["full_replies"],
                "hb_view_rows_per_interval": round(per_interval_rows, 1),
                "hb_view_bytes_per_interval": round(per_interval_bytes, 1),
                "hb_view_bytes_per_node_per_interval": round(
                    per_interval_bytes / n_nodes, 2
                ),
                "tasks": n_tasks,
                "tasks_per_s": round(n_tasks / burst_s, 1),
                "placement_p50_ms": round(_percentile(lat, 0.50) * 1000, 2),
                "placement_p99_ms": round(_percentile(lat, 0.99) * 1000, 2),
            }
            c.shutdown()
            results["sim_sweep"][key] = row
            print(f"  sim sweep {key}: {row}")

    # ---- node-death directory cost: per-node index vs full scan ----
    n_objects = 5000 if quick else 20000
    death = {}
    for arm in ("index", "scan"):
        cfg = {
            "heartbeat_interval_s": 0.5,
            "node_death_timeout_s": 60.0,
            "gcs_location_index": arm == "index",
        }
        c = SimCluster(
            64, resources_per_node={"CPU": 8}, _system_config=cfg,
        )
        c.start()
        c.wait_for_view(timeout=60)
        victim = c.nodes[-1]

        async def _seed(cluster=c, victim_node=victim, total=n_objects):
            gcs = cluster.nodes[0].gcs
            for i in range(total):
                node = (
                    victim_node
                    if i % 8 == 0
                    else cluster.nodes[i % (len(cluster.nodes) - 1)]
                )
                await gcs.acall(
                    "add_object_location",
                    {"object_id": f"{i:056x}", "node_id": node.node_id},
                )

        c._io.run(_seed(), timeout=300)
        t0 = time.perf_counter()
        c._io.run(c.gcs._on_node_death(victim.node_id), timeout=60)
        death[arm] = {
            "on_node_death_ms": round((time.perf_counter() - t0) * 1000, 2),
            "location_rows": n_objects,
            "victim_rows": n_objects // 8,
        }
        c.shutdown()
    results["sim_node_death"] = death
    print(f"  sim node death: {death}")

    # ---- locality arms ----
    loc = {}
    n_ref_tasks = 120 if quick else 400
    for arm in ("locality", "no_locality"):
        cfg = {
            "heartbeat_interval_s": 0.2,
            "node_death_timeout_s": 60.0,
            "locality_aware_scheduling": arm == "locality",
        }
        c = SimCluster(
            128 if not quick else 64,
            resources_per_node={"CPU": 8},
            num_entry_nodes=8,
            _system_config=cfg,
        )
        c.start()
        c.wait_for_view(timeout=60)
        holders = c.nodes[32:48]
        oids = []
        for i, h in enumerate(holders):
            oid = f"b{i:055x}"
            c.seed_object(h, oid)
            oids.append((oid, h.node_id))
        time.sleep(0.5)  # let holder rows settle into entry views

        async def _ref_burst(cluster=c, pairs=oids, total=n_ref_tasks):
            futs = []
            for i in range(total):
                oid, _holder = pairs[i % len(pairs)]
                spec = cluster.make_spec(
                    args=[("r", oid, None)], sim_ms=2.0
                )
                fut = cluster.register_waiter(spec.task_id)
                await cluster.asubmit(spec)
                futs.append((spec.task_id, fut, pairs[i % len(pairs)][1]))
            hits = 0
            for tid, fut, holder_nid in futs:
                landed = await asyncio.wait_for(fut, 30)
                if landed == holder_nid:
                    hits += 1
            return hits

        hits = c._io.run(_ref_burst(), timeout=180)
        lat = c.placement_latencies()
        loc[arm] = {
            "ref_tasks": n_ref_tasks,
            "holder_hits": hits,
            "holder_hit_frac": round(hits / n_ref_tasks, 3),
            "locality_hit_events": sum(n.locality_hits for n in c.nodes),
            "placement_p99_ms": round(_percentile(lat, 0.99) * 1000, 2),
        }
        c.shutdown()
    results["sim_locality"] = loc
    print(f"  sim locality: {loc}")

    # ---- task-event ingest flood vs the drop-oldest ring ----
    from ray_tpu._private.rpc import RpcClient

    cfg = {
        "heartbeat_interval_s": 0.5,
        "task_events_buffer_size": 2048,
    }
    c = SimCluster(8, _system_config=cfg)
    c.start()
    cli = RpcClient(c.gcs.address, label="simbench-events")
    n_events = 20000 if quick else 100000
    batch = 1000
    t0 = time.perf_counter()

    async def _flood(total=n_events, step=batch, client=cli):
        for i in range(0, total, step):
            evs = [
                {"task_id": f"e{j:014d}", "state": "FINISHED", "ts": 0.0}
                for j in range(i, i + step)
            ]
            await client.acall("record_task_events", {"events": evs})

    c._io.run(_flood(), timeout=300)
    flood_s = time.perf_counter() - t0
    results["sim_task_events"] = {
        "events_sent": n_events,
        "ingest_events_per_s": round(n_events / flood_s, 1),
        "ring_size_after": len(c.gcs.task_events),
        "ring_maxlen": c.gcs.task_events.maxlen,
        "events_dropped_total": c.gcs.events_dropped_total,
    }
    assert len(c.gcs.task_events) <= c.gcs.task_events.maxlen
    assert c.gcs.events_dropped_total == n_events - c.gcs.task_events.maxlen
    cli.close()
    c.shutdown()
    print(f"  sim task events: {results['sim_task_events']}")

    # ---- sim-scale chaos SLO scorecard ----
    import sys as _sys

    tests_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests")
    if tests_dir not in _sys.path:
        _sys.path.insert(0, tests_dir)
    from chaos_matrix import run_sim_matrix

    cells = run_sim_matrix(num_nodes=96, seed=7, quick=quick)
    results["sim_slo_scorecard"] = [r.summary() for r in cells]
    results["sim_slo_ok"] = all(r.ok for r in cells)
    print(f"  sim SLO scorecard ok={results['sim_slo_ok']}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=int(os.environ.get("GRAFT_ROUND", "2")))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tiny-count CPU-only sanity pass (<30s): basic suite only, "
        "nonzero exit on any error — invoked from tier-1 so dispatch-path "
        "breakage fails pytest instead of the next bench round",
    )
    ap.add_argument(
        "--hop-budget",
        action="store_true",
        help="measure and print the per-hop dispatch latency budget "
        "(warm lease vs direct actor vs classic raylet path)",
    )
    ap.add_argument(
        "--recorder-overhead",
        action="store_true",
        help="measure the always-on flight-recorder + sampled-hop-stamp cost "
        "on task_sync (paired ABBA windows, one cluster; OBSBENCH_r{N}.json)",
    )
    ap.add_argument(
        "--device-objects",
        action="store_true",
        help="device-ref handoff vs host-shm put/get at 1 MiB / 32 MiB "
        "(same-process zero-copy + actor→actor collective handoff); records "
        "DEVBENCH_r{N}.json with the zero-shm-copy evidence",
    )
    ap.add_argument(
        "--dag",
        action="store_true",
        help="classic dag.execute() vs compiled execution on a 4-stage "
        "actor pipeline; records DAGBENCH_r{N}.json with the zero-RPC/"
        "zero-ref evidence and per-stage hop stamps",
    )
    ap.add_argument(
        "--pipeline",
        action="store_true",
        help="MPMD pipeline over compiled graphs (ISSUE 12): 4-stage "
        "descriptor-channel pipeline vs classic-dispatch actor pipeline "
        "(device-object and host arms) and single-controller "
        "pipeline_apply, with bubble fraction at M in {4,16} and the "
        "zero-RPC / zero-host-copy counters; records PIPEBENCH_r{N}.json",
    )
    ap.add_argument(
        "--serve",
        action="store_true",
        help="continuous-batching LLM serving A/B (ISSUE 11): closed-loop "
        "load generator at N concurrent streams, continuous-batching engine "
        "vs serial-batch baseline — p50/p99 TTFT, time-per-output-token, "
        "aggregate tokens/s; records SERVEBENCH_r{N}.json",
    )
    ap.add_argument(
        "--serve-ft",
        dest="serve_ft",
        action="store_true",
        help="self-healing serving (ISSUE 14): time-to-stream-resume after "
        "a seeded mid-stream replica kill (migration + teacher-forced "
        "resume), and rolling-update dropped-stream counts with drain ON "
        "vs OFF; records FTBENCH_r{N}.json",
    )
    ap.add_argument(
        "--serve-disagg",
        dest="serve_disagg",
        action="store_true",
        help="prefill/decode disaggregation + cluster KV prefix tier "
        "(ISSUE 20): mixed long-prefill/short-decode closed-loop load, "
        "monolithic 4-replica arm vs 2-prefill+2-decode pools — short-"
        "stream p99 TTFT, aggregate tokens/s, KV handoff + cluster-prefix-"
        "import counters, zero-host-store handoff evidence; records "
        "DISAGGBENCH_r{N}.json",
    )
    ap.add_argument(
        "--chaos",
        action="store_true",
        help="chaos-plane recovery budgets (ISSUE 13): pull failover under "
        "mid-frame reset, devobj handoff under a lost pull reply, broadcast "
        "under relay partition, acall heal-after-partition, plus the "
        "injection-disabled overhead check on task_sync; records "
        "CHAOSBENCH_r{N}.json",
    )
    ap.add_argument(
        "--sim",
        action="store_true",
        help="control-plane scale bench (ISSUE 19): node-count sweep over "
        "simnode raylet shells with heartbeat delta-sync before/after arms "
        "(per-interval view bytes), node-death directory cost index vs "
        "scan, locality vs no-locality placement arms, task-event ingest "
        "flood, and the seeded sim-scale chaos SLO scorecard; records "
        "SIMBENCH_r{N}.json",
    )
    ap.add_argument(
        "--collective",
        action="store_true",
        help="group-broadcast weight-sync A/B (ISSUE 15): device-object "
        "broadcast vs K-serial-unicast at fleet sizes K, latency + "
        "aggregate MiB/s, zero-host-store evidence, and an end-to-end "
        "Podracer IMPALA iterations/s row; plus (ISSUE 16) relay-tree vs "
        "flat broadcast under a modeled egress link and the tree-allreduce "
        "bit-exact oracle sweep; records COLLBENCH_r{N}.json",
    )
    ap.add_argument(
        "--tree",
        action="store_true",
        help="with --collective: run only the relay-TREE broadcast arm of "
        "the ISSUE 16 A/B (default: both arms)",
    )
    ap.add_argument(
        "--flat",
        action="store_true",
        help="with --collective: run only the FLAT fan-out broadcast arm "
        "of the ISSUE 16 A/B (default: both arms)",
    )
    ap.add_argument(
        "--resize",
        action="store_true",
        help="with --collective: elastic-fleet arm (ISSUE 17) — IMPALA on "
        "the device-broadcast plane through a scripted 8→16→8 sampler "
        "resize (2→4→2 with --quick), recording broadcast-plane syncs vs "
        "host-sync fallbacks per phase; records RESIZEBENCH_r{N}.json",
    )
    ap.add_argument(
        "--transfer",
        action="store_true",
        help="transfer-plane A/B (ISSUE 10): cut-through broadcast at the "
        "r5 shape, pull striping 1-vs-2 replicas over a modeled per-source "
        "link, raw-vs-msgpack chunk framing, plus putget/shuffle dispatch "
        "regression guards; records TRANSFER_r{N}.json",
    )
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.smoke:
        results = {"host_cpus": os.cpu_count(), "mode": "smoke"}
        t0 = time.perf_counter()
        basic_suite(results, duration=0.3)
        results["smoke_wall_s"] = round(time.perf_counter() - t0, 1)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(results, f, indent=1)
        print(json.dumps(results))
        required = [
            "task_sync_per_s",
            "task_async100_per_s",
            "actor_call_sync_per_s",
            "actor_call_async100_per_s",
            "put_1mib_per_s",
            "putget_1mib_per_s",
        ]
        bad = [k for k in required if not results.get(k)]
        if bad:
            print(f"SMOKE FAILED: missing/zero metrics {bad}", file=sys.stderr)
            sys.exit(1)
        return

    if args.recorder_overhead:
        results = {"host_cpus": os.cpu_count(), "mode": "recorder_overhead"}
        t0 = time.perf_counter()
        # 150 pairs (~60s) is where the median converges on this box: the
        # noise is non-stationary (multi-second bursts), so short runs can
        # land anywhere in +-4% while long-horizon medians repeat within
        # ~0.4%.
        recorder_overhead_suite(
            results,
            block_tasks=128 if args.quick else 256,
            pairs=8 if args.quick else 150,
        )
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"OBSBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.hop_budget:
        results = {"host_cpus": os.cpu_count(), "mode": "hop_budget"}
        hop_budget_suite(results, duration=1.0 if args.quick else 3.0)
        compute_deltas_vs_prev(results, args.round)
        out = args.out or f"HOPBUDGET_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        return

    if args.device_objects:
        results = {"host_cpus": os.cpu_count(), "mode": "device_objects"}
        t0 = time.perf_counter()
        device_objects_suite(results, duration=0.4 if args.quick else 3.0)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        compute_deltas_vs_prev(
            results, args.round, prev_path=f"DEVBENCH_r{args.round - 1}.json"
        )
        out = args.out or f"DEVBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.dag:
        results = {"host_cpus": os.cpu_count(), "mode": "dag"}
        t0 = time.perf_counter()
        dag_suite(results, duration=0.5 if args.quick else 3.0)
        results["dag_wall_s"] = round(time.perf_counter() - t0, 1)
        compute_deltas_vs_prev(
            results, args.round, prev_path=f"DAGBENCH_r{args.round - 1}.json"
        )
        out = args.out or f"DAGBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps({k: v for k, v in results.items() if k != "dag_hop_budget"}))
        return

    if args.pipeline:
        results = {"host_cpus": os.cpu_count(), "mode": "pipeline"}
        t0 = time.perf_counter()
        pipeline_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        compute_deltas_vs_prev(
            results, args.round, prev_path=f"PIPEBENCH_r{args.round - 1}.json"
        )
        out = args.out or f"PIPEBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.serve:
        results = {"host_cpus": os.cpu_count(), "mode": "serve_llm"}
        t0 = time.perf_counter()
        serve_llm_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        compute_deltas_vs_prev(
            results, args.round, prev_path=f"SERVEBENCH_r{args.round - 1}.json"
        )
        out = args.out or f"SERVEBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.serve_ft:
        results = {"host_cpus": os.cpu_count(), "mode": "serve_ft"}
        t0 = time.perf_counter()
        serve_ft_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"FTBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.serve_disagg:
        results = {"host_cpus": os.cpu_count(), "mode": "serve_disagg"}
        t0 = time.perf_counter()
        serve_disagg_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"DISAGGBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.sim:
        results = {"host_cpus": os.cpu_count(), "mode": "sim"}
        t0 = time.perf_counter()
        sim_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"SIMBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.chaos:
        results = {"host_cpus": os.cpu_count(), "mode": "chaos"}
        t0 = time.perf_counter()
        chaos_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"CHAOSBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.collective and args.resize:
        results = {"host_cpus": os.cpu_count(), "mode": "resize"}
        t0 = time.perf_counter()
        resize_suite(results, quick=args.quick)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"RESIZEBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.collective:
        results = {"host_cpus": os.cpu_count(), "mode": "collective"}
        arms = tuple(
            t for t, on in (("tree", args.tree), ("flat", args.flat)) if on
        ) or ("tree", "flat")
        t0 = time.perf_counter()
        collective_suite(results, quick=args.quick, arms=arms)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        out = args.out or f"COLLBENCH_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    if args.transfer:
        results = {"host_cpus": os.cpu_count(), "mode": "transfer"}
        t0 = time.perf_counter()
        mib = 16 if args.quick else 100
        n_nodes = 4 if args.quick else 32
        # Guards run FIRST: they certify the untouched dispatch plane, so
        # they must not measure the worker-reaping/arena-cleanup tail of a
        # freshly-shut-down 32-node broadcast cluster.
        def shuffle_guard():
            # Best of 2 full shuffle passes (fresh cluster each — see the
            # putget_guard docstring for why windows never share a cluster).
            best: dict = {}
            for _ in range(1 if args.quick else 2):
                tmp: dict = {}
                shuffle_stress(
                    tmp, 50_000 if args.quick else 500_000, 8 if args.quick else 32
                )
                for k, v in tmp.items():
                    if k.endswith("_rows_per_s"):
                        best[k] = max(best.get(k, 0), v)
                    else:
                        best[k] = v
            results.update(best)

        for name, fn in [
            ("putget", lambda: putget_guard(results, 1.0 if args.quick else 3.0)),
            ("shuffle", shuffle_guard),
            ("transfer", lambda: transfer_suite(results, args.quick)),
            ("broadcast", lambda: broadcast_stress(results, mib, n_nodes)),
        ]:
            tt = time.perf_counter()
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                results[f"{name}_error"] = f"{type(e).__name__}: {e}"
            results[f"{name}_wall_s"] = round(time.perf_counter() - tt, 1)
        results["wall_s"] = round(time.perf_counter() - t0, 1)
        # Diff against r5: the last artifact carrying broadcast/shuffle/
        # putget numbers for this box (r6-r9 were hop/DAG/obs/devobj rounds).
        compute_deltas_vs_prev(results, args.round, prev_path="MICROBENCH_r5.json")
        out = args.out or f"TRANSFER_r{args.round}.json"
        with open(out, "w") as f:
            json.dump(results, f, indent=1)
        print(json.dumps(results))
        return

    # Reference envelope shapes (release/benchmarks/README.md:21-31), scaled
    # to this host in --quick mode: 1M queued / 10k args / 3k returns /
    # 10k-object get / 32 simulated nodes.
    duration = 1.0 if args.quick else 3.0
    n_tasks = 10_000 if args.quick else 1_000_000
    n_actors = 8 if args.quick else 64
    mib = 16 if args.quick else 100
    n_nodes = 4 if args.quick else 32
    n_args = 1_000 if args.quick else 10_000
    n_returns = 300 if args.quick else 3_000
    n_get = 1_000 if args.quick else 10_000

    results: dict = {"host_cpus": os.cpu_count()}
    for name, fn in [
        ("basic", lambda: basic_suite(results, duration)),
        ("hop_budget", lambda: hop_budget_suite(results, min(duration, 2.0))),
        ("queued", lambda: queued_tasks_stress(results, n_tasks)),
        ("actors", lambda: actor_swarm_stress(results, n_actors)),
        ("many_args", lambda: many_args_stress(results, n_args)),
        ("many_returns", lambda: many_returns_stress(results, n_returns)),
        ("get_many", lambda: get_many_objects_stress(results, n_get)),
        ("shuffle", lambda: shuffle_stress(
            results, 50_000 if args.quick else 500_000, 8 if args.quick else 32)),
        ("broadcast", lambda: broadcast_stress(results, mib, n_nodes)),
    ]:
        t0 = time.perf_counter()
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            results[f"{name}_error"] = f"{type(e).__name__}: {e}"
        results[f"{name}_wall_s"] = round(time.perf_counter() - t0, 1)

    compute_deltas_vs_prev(results, args.round)
    out = args.out or f"MICROBENCH_r{args.round}.json"
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
