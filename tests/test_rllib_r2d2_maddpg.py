"""R2D2 + MADDPG + ExternalEnv (VERDICT r3 item 6).

Learning-gated like the reference's tuned-example regression tests:
- R2D2 reaches reward >=100 on CartPole (recurrent replay + burn-in +
  h-rescaling; reference rllib/algorithms/r2d2/).
- MADDPG solves a cooperative 2-agent spread task that needs the
  centralized critic (reference rllib/algorithms/maddpg/).
- ExternalEnv drives a DQN purely from an inverted-control loop
  (reference rllib/env/external_env.py:23).
"""

import numpy as np
import pytest

import gymnasium as gym

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv


class Spread1D(MultiAgentEnv):
    """Two agents on a line must cover goals at -0.5/+0.5 without
    colliding; the shared min-assignment reward makes it cooperative, so
    independent learners plateau but a centralized critic does not."""

    possible_agents = ["agent_0", "agent_1"]

    def __init__(self, config=None):
        self._obs_space = gym.spaces.Box(-2, 2, (4,), np.float32)
        self._act_space = gym.spaces.Box(-1, 1, (1,), np.float32)
        self.goals = np.array([-0.5, 0.5], np.float32)
        self.t = 0
        self._rng = np.random.default_rng(0)

    @property
    def observation_space(self):
        return self._obs_space

    @property
    def action_space(self):
        return self._act_space

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.pos = self._rng.uniform(-1, 1, 2).astype(np.float32)
        self.t = 0
        return self._obs(), {}

    def _obs(self):
        return {
            "agent_0": np.array([self.pos[0], self.pos[1], *self.goals], np.float32),
            "agent_1": np.array([self.pos[1], self.pos[0], *self.goals], np.float32),
        }

    def step(self, actions):
        self.pos[0] = np.clip(self.pos[0] + 0.1 * float(actions["agent_0"][0]), -2, 2)
        self.pos[1] = np.clip(self.pos[1] + 0.1 * float(actions["agent_1"][0]), -2, 2)
        self.t += 1
        d1 = abs(self.pos[0] - self.goals[0]) + abs(self.pos[1] - self.goals[1])
        d2 = abs(self.pos[0] - self.goals[1]) + abs(self.pos[1] - self.goals[0])
        r = -min(d1, d2)
        if abs(self.pos[0] - self.pos[1]) < 0.1:
            r -= 1.0
        done = self.t >= 25
        return (
            self._obs(),
            {"agent_0": r / 2, "agent_1": r / 2},
            {"__all__": done},
            {"__all__": False},
            {},
        )


def test_r2d2_learns_cartpole():
    from ray_tpu.rllib.algorithms.r2d2 import R2D2Config

    cfg = (
        R2D2Config()
        .environment("CartPole-v1")
        .rollouts(num_envs_per_worker=4)
        .training(
            lr=1e-3,
            rollout_steps_per_iter=1000,
            learning_starts=400,
            train_intensity=16,
            epsilon_timesteps=6000,
            target_network_update_freq=100,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(30):
            r = algo.step()
            best = max(best, r.get("episode_reward_mean") or 0.0)
            if best >= 100:
                break
        assert best >= 100, f"R2D2 failed to learn CartPole (best={best})"
        # Recurrent action API round-trips hidden state.
        a, h = algo.compute_single_action(
            [0.0, 0.1, 0.0, -0.1], state=np.zeros((1, cfg.hidden_size), np.float32)
        )
        assert a in (0, 1) and h.shape == (1, cfg.hidden_size)
    finally:
        algo.cleanup()


def test_r2d2_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib.algorithms.r2d2 import R2D2Config

    cfg = (
        R2D2Config()
        .environment("CartPole-v1")
        .training(rollout_steps_per_iter=200, learning_starts=100, train_intensity=20)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    algo.step()
    ckpt = algo.save_checkpoint()
    ts = algo._timesteps_total
    algo2 = cfg.build()
    algo2.setup(cfg.to_dict())
    algo2.load_checkpoint(ckpt)
    assert algo2._timesteps_total == ts
    import jax

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        algo.params, algo2.params,
    )
    algo.cleanup()
    algo2.cleanup()


def test_maddpg_learns_cooperative_spread():
    from ray_tpu.rllib.algorithms.maddpg import MADDPGConfig

    cfg = (
        MADDPGConfig()
        .environment(Spread1D)
        .training(
            rollout_steps_per_iter=500,
            learning_starts=500,
            train_batch_size=128,
            exploration_noise=0.3,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = -1e9
    try:
        for _ in range(24):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best > -6:
                break
        assert best > -8, f"MADDPG failed to learn (best={best})"
        # Decentralized execution API.
        acts = algo.compute_actions(algo.env._obs())
        assert set(acts) == {"agent_0", "agent_1"}
    finally:
        algo.cleanup()


def test_external_env_drives_dqn():
    """Inverted control: a user thread owns the CartPole loop and queries
    the algorithm; episodes flow into DQN replay and the policy improves."""
    from ray_tpu.rllib.algorithms.dqn import DQNConfig
    from ray_tpu.rllib.env.external_env import ExternalEnv, ExternalEnvRunner

    class CartPoleExternal(ExternalEnv):
        def __init__(self):
            env = gym.make("CartPole-v1")
            super().__init__(env.action_space, env.observation_space)
            self._env = env
            self._stop = False

        def run(self):
            while not self._stop:
                eid = self.start_episode()
                obs, _ = self._env.reset()
                done = False
                while not done:
                    action = self.get_action(eid, obs)
                    obs, reward, term, trunc, _ = self._env.step(int(action))
                    self.log_returns(eid, reward)
                    done = term or trunc
                self.end_episode(eid, obs)

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")  # spaces probe only; rollouts come from the external env
        .training(
            lr=1e-3,
            learning_starts=500,
            epsilon_timesteps=4000,
            target_network_update_freq=100,
            rollout_steps_per_iter=0,  # no internal rollouts
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    ext = CartPoleExternal()
    runner = ExternalEnvRunner(ext, algo)
    best = 0.0
    try:
        # 60 rounds (early-exit at reward 100): under full-suite load on a
        # 1-core box the collector thread gets starved and 40 rounds was
        # marginal — passed standalone, flaked in-suite.
        for _ in range(60):
            runner.collect(min_steps=500, timeout=60)
            for _ in range(60):
                algo._train_once()
            window = algo._episode_reward_window[-20:]
            if window:
                best = max(best, float(np.mean(window)))
            if best >= 100:
                break
        assert best >= 100, f"ExternalEnv-driven DQN failed to learn (best={best})"
    finally:
        ext._stop = True
        algo.cleanup()
