"""Lost-task sweep: raylet-path specs orphaned by node death are recovered.

Server-side spillback forwards a spec raylet-to-raylet and forgets it; a
node that dies holding the spec leaves NOBODY responsible — the owner
would wait on its returns forever (this exact shape hung a chaos run:
queued shuffle tasks died with their node and dataset.sum() never
returned). The owner-side sweep (core_worker._sweep_lost_tasks) locates
aged pending raylet-path tasks across alive raylets and resubmits ones
held by nowhere. This test simulates the loss deterministically by
stealing the queued spec out of the raylet's queue.
"""

import os
import time

import pytest

import ray_tpu


@pytest.fixture
def fast_sweep_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOST_TASK_SWEEP_INTERVAL_S", "0.5")
    monkeypatch.setenv("RAY_TPU_LOST_TASK_AGE_S", "1.0")
    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_lost_raylet_path_task_is_resubmitted(fast_sweep_cluster, tmp_path):
    gate = str(tmp_path / "gate")

    @ray_tpu.remote
    def blocker(path):
        import os
        import time

        while not os.path.exists(path):
            time.sleep(0.05)
        return "unblocked"

    @ray_tpu.remote
    def victim():
        return "recovered"

    # Occupy the single CPU (lease path) so the SPREAD task queues at the
    # raylet instead of dispatching.
    b = blocker.remote(gate)
    time.sleep(1.5)  # let the blocker actually start

    v = victim.options(scheduling_strategy="SPREAD").remote()

    # Steal the queued spec — the in-process stand-in for "the node holding
    # the spillback died": no raylet holds it, no failure is ever reported.
    raylet = ray_tpu._global_node.raylet
    stolen = None
    deadline = time.time() + 10
    while stolen is None and time.time() < deadline:
        for spec in list(raylet.task_queue) + list(raylet._infeasible):
            if spec.name == "victim":
                try:
                    raylet.task_queue.remove(spec)
                except ValueError:
                    try:
                        raylet._infeasible.remove(spec)
                    except ValueError:
                        continue
                stolen = spec
                break
        time.sleep(0.05)
    assert stolen is not None, "victim spec never reached the raylet queue"

    # Free the CPU; without the sweep the stolen task would hang forever.
    open(gate, "w").close()
    assert ray_tpu.get(b, timeout=30) == "unblocked"
    assert ray_tpu.get(v, timeout=30) == "recovered"


def test_sweep_does_not_touch_live_tasks(fast_sweep_cluster):
    """A legitimately slow, queued-or-running raylet-path task must NOT be
    resubmitted (locate_tasks finds it) — duplicate execution of live
    tasks would break side-effecting workloads."""
    marker = {"n": 0}

    @ray_tpu.remote
    def slow(path):
        import os
        import time

        time.sleep(4.0)  # longer than age + 2 sweep intervals
        # Count executions through the filesystem (task may run in any worker).
        with open(path, "a") as f:
            f.write("x")
        return os.getpid()

    import tempfile

    path = os.path.join(tempfile.mkdtemp(), "count")
    ref = slow.options(scheduling_strategy="SPREAD").remote(path)
    ray_tpu.get(ref, timeout=60)
    time.sleep(2.0)  # give a stray resubmission time to run if one happened
    with open(path) as f:
        executions = len(f.read())
    assert executions == 1, f"slow task executed {executions} times"
    assert marker["n"] == 0
