"""Process-mode chaos: SIGKILL real raylet process trees under load.

Reference: python/ray/tests/test_chaos.py:193 + test_utils.py:1360
(NodeKillerActor): the control plane (GCS) and every node (raylet) run as
REAL OS processes (their standalone main()s), a killer loop SIGKILLs random
worker-node process trees while a workload runs, and completion is asserted
via task retries + lineage reconstruction and trainer gang restart — the
in-process chaos tests (test_failures.py) cannot exercise process death.

The driver's own node is a zero-CPU "head" so every task/actor lands on a
killable victim node.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from conftest import skip_without_multiprocess_collectives
from ray_tpu._private import worker_context
from ray_tpu._private.config import init_config
from ray_tpu._private.core_worker import DRIVER, CoreWorker

_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "RAY_TPU_JAX_CONFIG_PLATFORMS": "cpu",
    "RAY_TPU_NUM_TPUS": "0",
}
_ENV.pop("PALLAS_AXON_POOL_IPS", None)


def _wait_file(path, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(0.1)
    raise TimeoutError(f"{path} never appeared")


def _start_gcs(tmp, name="gcs"):
    addr_file = os.path.join(tmp, f"{name}.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs", "--address-file", addr_file],
        env=_ENV,
        stdout=open(os.path.join(tmp, f"{name}.log"), "ab"),
        stderr=subprocess.STDOUT,
    )
    info = _wait_file(addr_file)
    return proc, tuple(info["address"])


def _start_raylet(tmp, gcs_addr, cpus, tag):
    addr_file = os.path.join(tmp, f"raylet-{tag}-{time.monotonic_ns()}.json")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--gcs-address", json.dumps(list(gcs_addr)),
            "--session-dir", os.path.join(tmp, "session"),
            "--resources", json.dumps({"CPU": cpus}),
            "--address-file", addr_file,
        ],
        env=_ENV,
        stdout=open(os.path.join(tmp, f"raylet-{tag}.log"), "ab"),
        stderr=subprocess.STDOUT,
    )
    info = _wait_file(addr_file)
    return proc, info


def _kill_tree(proc):
    """SIGKILL a raylet and every descendant (zygote, workers) — the
    reference's NodeKillerActor kill shape."""
    import psutil

    try:
        parent = psutil.Process(proc.pid)
        children = parent.children(recursive=True)
    except psutil.NoSuchProcess:
        children = []
    for p in children:
        try:
            p.send_signal(signal.SIGKILL)
        except Exception:
            pass
    try:
        proc.send_signal(signal.SIGKILL)
    except Exception:
        pass
    proc.wait(timeout=10)


@pytest.fixture
def process_cluster(tmp_path):
    """GCS + zero-CPU head + 3 victim raylets, all real OS processes."""
    init_config(None)
    tmp = str(tmp_path)
    os.makedirs(os.path.join(tmp, "session", "logs"), exist_ok=True)
    gcs_proc, gcs_addr = _start_gcs(tmp)
    head_proc, head = _start_raylet(tmp, gcs_addr, cpus=0, tag="head")
    victims = [_start_raylet(tmp, gcs_addr, cpus=2, tag=f"v{i}") for i in range(3)]
    cw = CoreWorker(
        mode=DRIVER,
        gcs_address=gcs_addr,
        raylet_address=tuple(head["address"]),
        arena_name=head["arena"],
        node_id=head["node_id"],
        session_dir=os.path.join(tmp, "session"),
    )
    worker_context.set_core_worker(cw)
    state = {"gcs_addr": gcs_addr, "tmp": tmp, "victims": [v[0] for v in victims]}
    try:
        yield state
    finally:
        worker_context.set_core_worker(None)
        try:
            cw.shutdown()
        except Exception:
            pass
        for proc in state["victims"] + [head_proc, gcs_proc]:
            try:
                _kill_tree(proc)
            except Exception:
                pass


class _NodeKiller(threading.Thread):
    """Kill a random victim's process tree every `interval`, then start a
    replacement node so capacity recovers (the autoscaler's role in the
    reference's chaos suite)."""

    def __init__(self, state, interval=6.0, kills=2):
        super().__init__(daemon=True)
        self.state = state
        self.interval = interval
        self.kills = kills
        self.killed = 0

    def run(self):
        import random

        for _ in range(self.kills):
            time.sleep(self.interval)
            victims = self.state["victims"]
            if not victims:
                return
            proc = victims.pop(random.randrange(len(victims)))
            _kill_tree(proc)
            self.killed += 1
            replacement, _ = _start_raylet(
                self.state["tmp"], self.state["gcs_addr"], cpus=2,
                tag=f"r{self.killed}",
            )
            victims.append(replacement)


def test_tasks_and_shuffle_survive_node_kills(process_cluster):
    """A task wave + a dataset shuffle complete while raylet process trees
    are SIGKILLed: retries resubmit, lineage rebuilds lost objects."""
    from ray_tpu import data

    @ray_tpu.remote(max_retries=8)
    def chunk(i):
        time.sleep(0.3)
        return i

    killer = _NodeKiller(process_cluster, interval=5.0, kills=2)
    killer.start()
    refs = [chunk.remote(i) for i in range(60)]
    ds = data.range(400, parallelism=8).random_shuffle(seed=0)
    total = ds.sum("id")
    assert total == sum(range(400))
    assert sorted(ray_tpu.get(refs, timeout=420)) == list(range(60))
    killer.join(timeout=60)
    assert killer.killed == 2, "node killer did not complete its kills"
    # The cluster still works after the chaos.
    assert ray_tpu.get(chunk.remote(123), timeout=120) == 123


@skip_without_multiprocess_collectives
def test_checkpointed_trainer_survives_node_kill(process_cluster):
    """A 2-worker JaxTrainer run rides out a node SIGKILL via whole-gang
    restart (reference: Train fault tolerance under chaos)."""
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    def loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        w = jnp.zeros((4,))

        def loss_fn(w):
            return jnp.sum((w - 3.0) ** 2)

        for step_i in range(16):
            g = jax.grad(loss_fn)(w)
            g = jnp.asarray(col.allreduce(g, group_name="train")) / session.get_world_size()
            w = w - 0.1 * g
            time.sleep(0.4)  # stretch the run across the kill window
            session.report(
                {"step": step_i, "loss": float(loss_fn(w))},
                checkpoint=Checkpoint.from_dict({"step": step_i}),
            )

    killer = _NodeKiller(process_cluster, interval=8.0, kills=1)
    killer.start()
    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=os.path.join(process_cluster["tmp"], "train"),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
            failure_config=FailureConfig(max_failures=4),
        ),
    )
    result = trainer.fit()
    killer.join(timeout=60)
    assert result.error is None, f"trainer failed under chaos: {result.error}"
    assert result.metrics["step"] == 15
    assert result.metrics["loss"] < 1.0
    assert killer.killed == 1
