"""Device-array object plane (SURVEY §2.3 object-plane row; VERDICT r1 #3).

ray.put/get of a jax.Array must preserve the type AND the sharding layout:
put does one device->host DMA per unique shard, get reassembles with
jax.make_array_from_single_device_arrays — never a host gather of the global
array. The test process runs on the 8-virtual-device CPU mesh from conftest.
"""

import numpy as np
import pytest

import ray_tpu


def _mesh(shape, names):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def test_sharded_array_roundtrip_preserves_sharding(ray_start_regular):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh((4, 2), ("dp", "tp"))
    x = jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8)
    sharding = NamedSharding(mesh, P("dp", "tp"))
    x = jax.device_put(x, sharding)

    out = ray_tpu.get(ray_tpu.put(x))
    assert isinstance(out, jax.Array)
    assert out.sharding == x.sharding
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # Same per-device placement, shard for shard.
    got = {s.device.id: np.asarray(s.data) for s in out.addressable_shards}
    for s in x.addressable_shards:
        np.testing.assert_array_equal(got[s.device.id], np.asarray(s.data))


def test_replicated_array_dedupes_shards(ray_start_regular):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu._private.serialization import serialize

    mesh = _mesh((8,), ("dp",))
    x = jax.device_put(jnp.ones((256, 256), jnp.float32), NamedSharding(mesh, P()))
    ser = serialize(x)
    # Fully replicated: ~1x the array, not 8x.
    assert ser.total_size < 2 * x.nbytes
    out = ray_tpu.get(ray_tpu.put(x))
    assert isinstance(out, jax.Array)
    assert out.sharding == x.sharding


def test_single_device_array_keeps_type_and_device(ray_start_regular):
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[3]
    x = jax.device_put(jnp.arange(16.0), dev)
    out = ray_tpu.get(ray_tpu.put(x))
    assert isinstance(out, jax.Array)
    assert out.devices() == {dev}
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_sharded_array_through_task(ray_start_regular):
    """A worker process (same virtual topology) returns a sharded array; the
    driver's get sees the same layout."""
    import jax

    @ray_tpu.remote
    def make():
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))
        return jax.device_put(
            jnp.arange(16.0, dtype=jnp.float32).reshape(4, 4), NamedSharding(mesh, P("a", "b"))
        )

    out = ray_tpu.get(make.remote(), timeout=120)
    assert isinstance(out, jax.Array)
    assert set(out.sharding.mesh.axis_names) == {"a", "b"}
    np.testing.assert_array_equal(np.asarray(out), np.arange(16.0).reshape(4, 4))


def test_multihost_array_put_raises():
    """A non-fully-addressable array can't ride the object store; the error
    must say so (not a silent gather)."""

    class _FakeShard:
        pass

    from ray_tpu._private import serialization

    class _FakeArr:
        is_fully_addressable = False
        addressable_shards = [_FakeShard()]
        sharding = object()

    with pytest.raises(TypeError, match="multi-host"):
        serialization._reduce_jax_array(_FakeArr())


def test_pytree_of_sharded_arrays(ray_start_regular):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh((8,), ("dp",))
    tree = {
        "w": jax.device_put(jnp.ones((16, 4)), NamedSharding(mesh, P("dp"))),
        "b": jax.device_put(jnp.zeros((4,)), NamedSharding(mesh, P())),
        "step": 7,
    }
    out = ray_tpu.get(ray_tpu.put(tree))
    assert out["step"] == 7
    assert isinstance(out["w"], jax.Array) and out["w"].sharding == tree["w"].sharding
    assert isinstance(out["b"], jax.Array) and out["b"].sharding == tree["b"].sharding
