"""DreamerV3 — model-based RL (reference: rllib/algorithms/dreamerv3/).

World model (RSSM, categorical latents) + actor-critic trained purely in
imagination. The learning test uses a 1-D target-reaching task: a correct
world model makes it solvable in a handful of iterations, while a broken
reward/dynamics head leaves the actor at random-policy level.
"""

import numpy as np
import pytest

import gymnasium as gym


class Reach1D(gym.Env):
    """Move to the target: obs [pos, target], action in [-1, 1],
    pos += 0.2 * a, reward -|pos - target|, 20-step episodes.
    Random policy averages about -18 per episode; a good policy -5."""

    observation_space = gym.spaces.Box(-2, 2, (2,), np.float32)
    action_space = gym.spaces.Box(-1, 1, (1,), np.float32)

    def __init__(self, config=None):
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.pos = float(self._rng.uniform(-1, 1))
        self.target = float(self._rng.uniform(-1, 1))
        self.t = 0
        return np.array([self.pos, self.target], np.float32), {}

    def step(self, a):
        self.pos = float(np.clip(self.pos + 0.2 * float(np.asarray(a).ravel()[0]), -2, 2))
        self.t += 1
        r = -abs(self.pos - self.target)
        return np.array([self.pos, self.target], np.float32), r, False, self.t >= 20, {}


def test_dreamerv3_learns_reach1d():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment(Reach1D)
        .training(
            learning_starts=300, rollout_steps_per_iter=400, train_intensity=10,
            batch_size=8, batch_length=12, deter_size=64, model_hiddens=(64,),
            latent_groups=4, latent_classes=8, imagine_horizon=10,
            entropy_coeff=1e-3,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    best = -1e9
    try:
        for _ in range(25):
            r = algo.step()
            m = r.get("episode_reward_mean")
            if m is not None and np.isfinite(m):
                best = max(best, m)
            if best > -8:
                break
        # Random policy sits near -18; the world-model-driven actor must
        # clearly beat it.
        assert best > -8, f"DreamerV3 failed to learn Reach1D (best={best})"
        assert np.isfinite(r["model_loss"])
    finally:
        algo.cleanup()


def test_dreamerv3_pendulum_smoke_and_checkpoint():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment("Pendulum-v1")
        .training(
            learning_starts=200, rollout_steps_per_iter=250, train_intensity=25,
            batch_size=4, batch_length=12, deter_size=64, model_hiddens=(64,),
            latent_groups=4, latent_classes=8, imagine_horizon=8,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        for _ in range(2):
            r = algo.step()
        for key in ("model_loss", "recon_loss", "reward_loss", "actor_loss", "critic_loss"):
            assert np.isfinite(r[key]), key
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert a.shape == (1,) and -2.0 <= float(a[0]) <= 2.0
        ckpt = algo.save_checkpoint()
        w0 = np.asarray(algo.params["reward"][0]["w"])
        algo.load_checkpoint(ckpt)
        np.testing.assert_allclose(np.asarray(algo.params["reward"][0]["w"]), w0)
    finally:
        algo.cleanup()


def test_dreamerv3_discrete_smoke():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment("CartPole-v1")
        .training(
            learning_starts=200, rollout_steps_per_iter=250, train_intensity=25,
            batch_size=4, batch_length=12, deter_size=64, model_hiddens=(64,),
            latent_groups=4, latent_classes=8, imagine_horizon=8,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        for _ in range(2):
            r = algo.step()
        assert np.isfinite(r["model_loss"]) and np.isfinite(r["actor_loss"])
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_dreamerv3_evaluation():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment(Reach1D)
        .training(
            learning_starts=100, rollout_steps_per_iter=150, train_intensity=50,
            batch_size=4, batch_length=12, deter_size=64, model_hiddens=(64,),
            latent_groups=4, latent_classes=8, imagine_horizon=8,
        )
        .evaluation(evaluation_interval=1, evaluation_duration=2)
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        r = algo.train()
        ev = r["evaluation"]
        assert ev["episodes_this_iter"] == 2
        assert np.isfinite(ev["episode_reward_mean"])
        # Eval must not corrupt the training rollout's live RSSM carry.
        r2 = algo.train()
        assert np.isfinite(r2["model_loss"])
    finally:
        algo.cleanup()
