"""AWS / GCE / Azure node providers against in-process mock cloud APIs.

Same strategy as test_tpu_pod_provider.py (mock the REST surface, drive the
full NodeProvider lifecycle): create N, list, tags, terminate, is_running.
The AWS mock also checks the SigV4 Authorization header is present and
well-formed, so the self-contained signer is exercised on every call.
"""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from ray_tpu.autoscaler.cloud_providers import (
    AWSNodeProvider,
    AzureNodeProvider,
    GCENodeProvider,
    _sigv4_headers,
)


def _serve(handler_cls):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}"


def _lifecycle(provider, expect_user_data=True):
    """Shared create/list/tags/terminate exercise for any provider."""
    ids = provider.create_node(
        {"node_config": {}}, {"node_type": "worker", "extra": "1"}, 2
    )
    assert len(ids) == 2 and len(set(ids)) == 2
    alive = provider.non_terminated_nodes()
    assert sorted(alive) == sorted(ids)
    tags = provider.node_tags(ids[0])
    assert tags["ray-cluster-name"] == "c1"
    assert tags["node_type"] == "worker"
    assert tags.get("provider_node_id")
    assert provider.is_running(ids[0])
    provider.terminate_node(ids[0])
    assert provider.non_terminated_nodes() == [ids[1]]
    assert not provider.is_running(ids[0])
    provider.terminate_node(ids[1])
    assert provider.non_terminated_nodes() == []


# ---------------------------------------------------------------------------
# AWS
# ---------------------------------------------------------------------------


class _MockEC2:
    def __init__(self):
        self.instances: dict = {}  # id -> {state, tags, user_data}
        self.auth_headers: list = []
        self._n = 0

    def handler(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                form = parse_qs(self.rfile.read(length).decode())
                api.auth_headers.append(self.headers.get("Authorization", ""))
                action = form["Action"][0]
                if action == "RunInstances":
                    api._n += 1
                    iid = f"i-{api._n:08x}"
                    tags = {}
                    i = 1
                    while f"TagSpecification.1.Tag.{i}.Key" in form:
                        tags[form[f"TagSpecification.1.Tag.{i}.Key"][0]] = form[
                            f"TagSpecification.1.Tag.{i}.Value"
                        ][0]
                        i += 1
                    api.instances[iid] = {
                        "state": "pending",
                        "tags": tags,
                        "user_data": form.get("UserData", [""])[0],
                        "itype": form["InstanceType"][0],
                    }
                    body = (
                        '<RunInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">'
                        f"<instancesSet><item><instanceId>{iid}</instanceId>"
                        "<instanceState><name>pending</name></instanceState>"
                        "</item></instancesSet></RunInstancesResponse>"
                    )
                elif action == "DescribeInstances":
                    # One poll flips pending -> running (create_node wait loop).
                    items = []
                    for iid, inst in api.instances.items():
                        if inst["state"] == "pending":
                            inst["state"] = "running"
                        tag_xml = "".join(
                            f"<item><key>{k}</key><value>{v}</value></item>"
                            for k, v in inst["tags"].items()
                        )
                        items.append(
                            f"<item><instanceId>{iid}</instanceId>"
                            f"<instanceState><name>{inst['state']}</name></instanceState>"
                            f"<tagSet>{tag_xml}</tagSet></item>"
                        )
                    body = (
                        '<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">'
                        "<reservationSet><item><instancesSet>"
                        + "".join(items)
                        + "</instancesSet></item></reservationSet>"
                        "</DescribeInstancesResponse>"
                    )
                elif action == "TerminateInstances":
                    iid = form["InstanceId.1"][0]
                    if iid in api.instances:
                        api.instances[iid]["state"] = "terminated"
                    body = "<TerminateInstancesResponse/>"
                else:
                    self.send_response(400)
                    self.end_headers()
                    return
                payload = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/xml")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        return Handler


def test_aws_provider_lifecycle():
    api = _MockEC2()
    srv, endpoint = _serve(api.handler())
    try:
        provider = AWSNodeProvider(
            {
                "api_endpoint": endpoint,
                "region": "us-test-1",
                "access_key": "AKIATEST",
                "secret_key": "secret",
                "gcs_address": "10.0.0.1:6379",
                "wait_for_ready": True,
                "poll_interval_s": 0.01,
            },
            "c1",
        )
        _lifecycle(provider)
        # Every call carried a SigV4 authorization header.
        assert api.auth_headers and all(
            h.startswith("AWS4-HMAC-SHA256 Credential=AKIATEST/") and "Signature=" in h
            for h in api.auth_headers
        )
        # Bootstrap user data decodes to a ray_tpu start script.
        inst = next(iter(api.instances.values()))
        script = base64.b64decode(inst["user_data"]).decode()
        assert "--address 10.0.0.1:6379" in script and "provider_node_id" in script
        # Autoscaler contract: the ids create_node returns ARE the
        # provider_node_id tag values the booted raylets register with
        # (NOT raw EC2 instance ids) — reconciliation matches on them.
        ids = provider.create_node({}, {"node_type": "worker"}, 1)
        assert provider.node_tags(ids[0])["provider_node_id"] == ids[0]
        assert not ids[0].startswith("i-")
    finally:
        srv.shutdown()


def test_sigv4_deterministic_and_secret_sensitive():
    import time

    now = time.gmtime(1753000000)
    a = _sigv4_headers("POST", "http://x/", b"Action=Foo", "r", "ec2", "AK", "sk", now=now)
    b = _sigv4_headers("POST", "http://x/", b"Action=Foo", "r", "ec2", "AK", "sk", now=now)
    c = _sigv4_headers("POST", "http://x/", b"Action=Foo", "r", "ec2", "AK", "sk2", now=now)
    assert a["authorization"] == b["authorization"]
    assert a["authorization"] != c["authorization"]
    assert "SignedHeaders=content-type;host;x-amz-date" in a["authorization"]


# ---------------------------------------------------------------------------
# GCE
# ---------------------------------------------------------------------------


class _MockGCE:
    def __init__(self):
        self.instances: dict = {}
        self.ops: dict = {}
        self._n = 0

    def handler(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length))
                name = body["name"]
                api.instances[name] = {
                    "name": name,
                    "status": "PROVISIONING",
                    "labels": body.get("labels", {}),
                    "metadata": body.get("metadata", {}),
                }
                api._n += 1
                op_name = f"op-{api._n}"
                api.ops[op_name] = {"name": op_name, "status": "PENDING", "node": name}
                self._send(200, api.ops[op_name])

            def do_GET(self):
                parsed = urlparse(self.path)
                parts = parsed.path.strip("/").split("/")
                if "operations" in parts:
                    op = api.ops.get(parts[-1])
                    if op is None:
                        return self._send(404, {})
                    op["status"] = "DONE"
                    api.instances[op["node"]]["status"] = "RUNNING"
                    return self._send(200, op)
                if parts[-1] == "instances":
                    return self._send(200, {"items": list(api.instances.values())})
                inst = api.instances.get(parts[-1])
                return self._send(200, inst) if inst else self._send(404, {})

            def do_DELETE(self):
                name = urlparse(self.path).path.strip("/").split("/")[-1]
                api.instances.pop(name, None)
                self._send(200, {"name": "op-del", "status": "DONE"})

        return Handler


def test_gce_provider_lifecycle():
    api = _MockGCE()
    srv, endpoint = _serve(api.handler())
    try:
        provider = GCENodeProvider(
            {
                "api_endpoint": endpoint,
                "project_id": "p1",
                "zone": "us-test1-a",
                "access_token": "tok",
                "gcs_address": "10.0.0.1:6379",
                "wait_for_ready": True,
                "poll_interval_s": 0.01,
            },
            "c1",
        )
        _lifecycle(provider)
        # Startup script + original node_type rode the instance metadata.
        created = provider.create_node({}, {"node_type": "Worker_A"}, 1)
        meta = {i["key"]: i["value"] for i in api.instances[created[0]]["metadata"]["items"]}
        assert "--address 10.0.0.1:6379" in meta["startup-script"]
        assert meta["ray-node-type"] == "Worker_A"
        # Labels are sanitized but node_tags round-trips the original type.
        assert provider.node_tags(created[0])["node_type"] == "Worker_A"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Azure
# ---------------------------------------------------------------------------


class _MockAzure:
    def __init__(self):
        self.vms: dict = {}

    def handler(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length))
                name = urlparse(self.path).path.strip("/").split("/")[-1]
                body["name"] = name
                body.setdefault("properties", {})["provisioningState"] = "Succeeded"
                api.vms[name] = body
                self._send(201, body)

            def do_GET(self):
                parts = urlparse(self.path).path.strip("/").split("/")
                if parts[-1] == "virtualMachines":
                    return self._send(200, {"value": list(api.vms.values())})
                vm = api.vms.get(parts[-1])
                return self._send(200, vm) if vm else self._send(404, {})

            def do_DELETE(self):
                name = urlparse(self.path).path.strip("/").split("/")[-1]
                api.vms.pop(name, None)
                self._send(200, {})

        return Handler


def test_azure_provider_lifecycle():
    api = _MockAzure()
    srv, endpoint = _serve(api.handler())
    try:
        provider = AzureNodeProvider(
            {
                "api_endpoint": endpoint,
                "subscription_id": "sub1",
                "resource_group": "rg1",
                "location": "testus",
                "access_token": "tok",
                "gcs_address": "10.0.0.1:6379",
                "wait_for_ready": True,
                "poll_interval_s": 0.01,
            },
            "c1",
        )
        _lifecycle(provider)
        # Bootstrap rode osProfile.customData, base64 per ARM convention.
        created = provider.create_node({}, {"node_type": "worker"}, 1)
        custom = api.vms[created[0]]["properties"]["osProfile"]["customData"]
        assert "--address 10.0.0.1:6379" in base64.b64decode(custom).decode()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_make_provider_registry():
    from ray_tpu.autoscaler.autoscaler import _make_provider

    api = _MockGCE()
    srv, endpoint = _serve(api.handler())
    try:
        p = _make_provider(
            {
                "cluster_name": "c1",
                "provider": {
                    "type": "gcp",
                    "api_endpoint": endpoint,
                    "project_id": "p",
                    "zone": "z",
                    "access_token": "t",
                },
            }
        )
        assert isinstance(p, GCENodeProvider)
    finally:
        srv.shutdown()
    with pytest.raises(RuntimeError, match="credentials"):
        _make_provider(
            {"provider": {"type": "aws", "region": "us-east-1"}}
        )
