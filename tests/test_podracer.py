"""Podracer learner/sampler weight sync (ISSUE 15) + elastic fleets (ISSUE 17).

The RLlib seam: ``weight_sync="device_broadcast"`` packs the learner's
params into ONE flat device-resident vector, forms a learner↔sampler
collective group at setup, and every sync is one
``device_object.broadcast`` instead of K per-worker pytree ships —
runnable from IMPALA and APPO unchanged. ``learner_mesh=True`` runs the
jitted update on a pjit mesh over every local device (trivial on this
1-device box; the multi-chip layout is a deployment detail).

One module-scoped cluster (spin-up dominates tier-1 wall otherwise).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def pod_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# pack/unpack (clusterless)
# ---------------------------------------------------------------------------


def test_pack_unpack_roundtrip():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core.learner import pack_weights, unpack_weights

    params = {
        "dense": {"w": jnp.arange(6.0, dtype=jnp.float32).reshape(2, 3),
                  "b": jnp.full((3,), -1.5, jnp.float32)},
        "head": jnp.ones((4,), jnp.float32),
    }
    flat = pack_weights(params)
    assert flat.shape == (13,) and flat.dtype == jnp.float32
    rebuilt = unpack_weights(np.asarray(flat), params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, rebuilt,
    )


def test_unpack_size_mismatch_raises():
    import jax.numpy as jnp

    from ray_tpu.rllib.core.learner import unpack_weights

    with pytest.raises(ValueError, match="disagree on the module spec"):
        unpack_weights(jnp.zeros((5,), jnp.float32), {"w": jnp.zeros((3, 3))})


# ---------------------------------------------------------------------------
# IMPALA / APPO on the device-broadcast topology
# ---------------------------------------------------------------------------


def _impala_config(**training_overrides):
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    return (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=16)
        .training(lr=5e-4, train_batch_size=64, **training_overrides)
        .debugging(seed=0)
    )


def test_impala_device_broadcast_topology(pod_cluster):
    """IMPALA runs the Podracer topology end to end: the weight group forms
    at setup, every broadcast-interval sync rides the group-broadcast plane
    (COLL counters prove it), and training metrics stay finite."""
    from ray_tpu.util.collective.p2p import COLL

    cfg = _impala_config(weight_sync="device_broadcast", learner_mesh=True)
    algo = cfg.build()
    try:
        assert algo._device_sync_ready
        before = COLL.bcast_sends
        m1 = algo.step()
        m2 = algo.step()
        # setup already synced once; each step syncs again (driver = holder,
        # so the fan-outs are counted in THIS process).
        assert COLL.bcast_sends - before >= 2
        assert np.isfinite(m1["total_loss"]) and np.isfinite(m2["total_loss"])
        # The learner's params actually reached the samplers: a fresh sync
        # must be a no-op for behavior (greedy actions computable).
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_impala_device_broadcast_survives_dead_sampler(pod_cluster):
    """Kill one sampler between iterations: the sync loop respawns it, the
    replacement RE-REGISTERS into the weight group at its old rank (roster
    epoch bump), and the next broadcast covers it over the group plane —
    the degradation is one pull at most, not permanent. The replacement's
    own counters prove it: bcast_recvs climbs across the post-respawn
    steps while host_sync_fallbacks stays ≤ 1 (only the sync that raced
    the respawn may have pulled) and then stays FLAT."""
    cfg = _impala_config(weight_sync="device_broadcast")
    algo = cfg.build()
    try:
        algo.step()
        ray_tpu.kill(algo.workers._workers[0])
        algo.sync_worker_weights()  # must respawn + re-register + deliver
        assert algo.workers.num_workers == 2
        m = algo.step()  # first post-respawn iteration: back on fast path
        assert np.isfinite(m["total_loss"])
        base = algo.workers.coll_stats()[0]  # the replacement
        assert base is not None and base["host_sync_fallbacks"] <= 1, base
        algo.step()
        after = algo.workers.coll_stats()[0]
        assert after["bcast_recvs"] > base["bcast_recvs"], (base, after)
        assert after["host_sync_fallbacks"] == base["host_sync_fallbacks"], (base, after)
    finally:
        algo.cleanup()


def test_impala_resize_oracle_weight_sync_stays_on_fast_path(pod_cluster):
    """The resize oracle: grow 2→4 and shrink 4→2 mid-IMPALA. Growing
    joins the new samplers into the weight group at fresh tail ranks,
    shrinking evicts the tail from the roster — no group teardown either
    way — and after the first post-resize iteration every live sampler
    resolves weight syncs from its broadcast inbox with the host-sync
    fallback counter FLAT."""
    cfg = _impala_config(weight_sync="device_broadcast")
    algo = cfg.build()
    try:
        assert algo._device_sync_ready
        algo.step()
        assert algo.resize_workers(4) == 4
        roster = algo.learner_group.weight_group_roster(algo._weight_group)
        assert roster["ranks"] == [0, 1, 2, 3, 4], roster
        m = algo.step()  # first post-grow iteration
        assert np.isfinite(m["total_loss"])
        base = algo.workers.coll_stats()
        assert all(s is not None for s in base), base
        algo.step()
        after = algo.workers.coll_stats()
        for b, a in zip(base, after):
            assert a["bcast_recvs"] > b["bcast_recvs"], (base, after)
            # ZERO fallbacks after the first post-resize iteration.
            assert a["host_sync_fallbacks"] == b["host_sync_fallbacks"], (base, after)
        assert algo.resize_workers(2) == 2
        roster = algo.learner_group.weight_group_roster(algo._weight_group)
        assert roster["ranks"] == [0, 1, 2], roster  # tail ranks evicted
        m = algo.step()  # first post-shrink iteration
        assert np.isfinite(m["total_loss"])
        base = algo.workers.coll_stats()
        algo.step()
        after = algo.workers.coll_stats()
        for b, a in zip(base, after):
            assert a["host_sync_fallbacks"] == b["host_sync_fallbacks"], (base, after)
    finally:
        algo.cleanup()


def test_appo_device_broadcast_runs(pod_cluster):
    from ray_tpu.rllib.algorithms.appo import APPOConfig

    cfg = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=16)
        .training(lr=5e-4, train_batch_size=64, weight_sync="device_broadcast")
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        assert algo._device_sync_ready
        m = algo.step()
        assert np.isfinite(m["total_loss"])
    finally:
        algo.cleanup()


def test_impala_device_allreduce_grad_sync(pod_cluster):
    """IMPALA with two remote learners and ``grad_sync="device_allreduce"``
    runs end to end: every measured gradient sync rides the tree allreduce
    plane — the packed grad vector reduces up the binomial tree and
    broadcasts back down — instead of the per-leaf GCS ring. The
    ``grad_allreduce_tree`` metric (tree reduce_sends observed inside the
    learner during its update) proves the transport on every step."""
    cfg = _impala_config(grad_sync="device_allreduce").resources(num_learners=2)
    algo = cfg.build()
    try:
        m1 = algo.step()
        m2 = algo.step()
        for m in (m1, m2):
            assert np.isfinite(m["total_loss"]), m
            # Mean over the 2 learners; each did >= 1 tree reduce per step.
            assert m.get("grad_allreduce_tree", 0.0) >= 1.0, m
    finally:
        algo.cleanup()


def test_host_weight_sync_unchanged(pod_cluster):
    """The default path stays the default: no group forms, no broadcast."""
    cfg = _impala_config()
    algo = cfg.build()
    try:
        assert not getattr(algo, "_device_sync_ready", False)
        m = algo.step()
        assert np.isfinite(m["total_loss"])
    finally:
        algo.cleanup()
