"""TorchTrainer / HuggingFaceTrainer tests.

Reference analog: python/ray/train/tests/test_torch_trainer.py and
test_huggingface_trainer.py — gloo process-group formation across worker
actors, DDP gradient sync, HF Trainer bridged into session.report. Models are
built from configs (no hub downloads — zero-egress environment).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_torch_trainer_ddp_two_workers(ray_cluster):
    from ray_tpu.air import session
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch import prepare_model

        assert dist.is_initialized() and dist.get_world_size() == 2
        torch.manual_seed(0)
        model = prepare_model(torch.nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        g = torch.Generator().manual_seed(session.get_world_rank())
        X = torch.randn(64, 4, generator=g)
        y = X @ torch.tensor([[1.0], [2.0], [-1.0], [0.5]]) + 0.1
        loss = None
        for _ in range(20):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X), y)
            loss.backward()  # DDP allreduces grads here
            opt.step()
        # After identical synced updates, every rank holds the same weights.
        w = [p.detach().clone() for p in model.parameters()]
        flat = torch.cat([t.reshape(-1) for t in w])
        gathered = [torch.zeros_like(flat) for _ in range(2)]
        dist.all_gather(gathered, flat)
        assert torch.allclose(gathered[0], gathered[1], atol=1e-6)
        session.report({"loss": float(loss)})

    trainer = TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["loss"] < 1.0


def test_torch_trainer_single_worker_no_pg(ray_cluster):
    from ray_tpu.air import session
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch import prepare_data_loader, prepare_model

        assert not dist.is_initialized()
        model = prepare_model(torch.nn.Linear(2, 1))  # passthrough
        dl = prepare_data_loader(
            torch.utils.data.DataLoader(
                torch.utils.data.TensorDataset(torch.randn(8, 2), torch.randn(8, 1)),
                batch_size=4,
            )
        )
        n = sum(1 for _ in dl)
        session.report({"batches": n, "is_ddp": isinstance(model, torch.nn.Linear)})

    trainer = TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.metrics["batches"] == 2
    assert result.metrics["is_ddp"]


def test_huggingface_trainer_tiny_bert(ray_cluster, tmp_path):
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.data import from_items
    from ray_tpu.train.huggingface import HuggingFaceTrainer

    rng = np.random.default_rng(0)
    rows = [
        {
            "input_ids": rng.integers(0, 64, 8).tolist(),
            "attention_mask": [1] * 8,
            "labels": int(rng.integers(0, 2)),
        }
        for _ in range(16)
    ]
    out_dir = str(tmp_path / "hf_out")

    def trainer_init(train_ds, eval_ds, **config):
        import torch
        import transformers

        cfg = transformers.BertConfig(
            vocab_size=64, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32, max_position_embeddings=16,
            num_labels=2,
        )
        model = transformers.BertForSequenceClassification(cfg)

        def collate(batch):
            return {
                "input_ids": torch.tensor([r["input_ids"] for r in batch]),
                "attention_mask": torch.tensor([r["attention_mask"] for r in batch]),
                "labels": torch.tensor([r["labels"] for r in batch]),
            }

        args = transformers.TrainingArguments(
            output_dir=config["output_dir"],
            max_steps=3,
            per_device_train_batch_size=4,
            logging_steps=1,
            report_to=[],
            save_strategy="no",
            use_cpu=True,
        )
        return transformers.Trainer(
            model=model, args=args, train_dataset=train_ds, data_collator=collate
        )

    trainer = HuggingFaceTrainer(
        trainer_init,
        trainer_init_config={"output_dir": out_dir},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(),
        datasets={"train": from_items(rows)},
    )
    result = trainer.fit()
    assert "train_loss" in result.metrics or "loss" in result.metrics
    assert result.checkpoint is not None
    state = result.checkpoint.to_dict()
    assert "model_state" in state
