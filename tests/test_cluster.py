"""Multi-node (multi-raylet single-host) tests — the reference's
cluster_utils.Cluster pattern (python/ray/tests/conftest.py:396)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


def test_two_node_scheduling(ray_start_cluster):
    cluster = ray_start_cluster
    n1 = cluster.add_node(num_cpus=1, resources={"a": 1})
    n2 = cluster.add_node(num_cpus=1, resources={"b": 1})
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def whoami():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    on_a = whoami.options(resources={"a": 1}).remote()
    on_b = whoami.options(resources={"b": 1}).remote()
    node_a, node_b = ray_tpu.get([on_a, on_b], timeout=120)
    assert node_a == n1.node_id
    assert node_b == n2.node_id


def test_cross_node_object_transfer(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"a": 1})
    cluster.add_node(num_cpus=1, resources={"b": 1})
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"a": 1})
    def produce():
        return np.full((512, 512), 7.0, dtype=np.float32)  # 1MB -> plasma

    @ray_tpu.remote(resources={"b": 1})
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    out = ray_tpu.get(consume.remote(ref), timeout=120)
    assert out == 7.0 * 512 * 512


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    n2 = cluster.add_node(num_cpus=1)
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote
    def whoami():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    strategy = NodeAffinitySchedulingStrategy(node_id=n2.node_id)
    ref = whoami.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref, timeout=120) == n2.node_id


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    cluster.wait_for_nodes()

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)
    nodes = {pg.bundle_node(0), pg.bundle_node(1)}
    assert len(nodes) == 2

    @ray_tpu.remote
    def whoami():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    r0 = whoami.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    r1 = whoami.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 1)
    ).remote()
    got = set(ray_tpu.get([r0, r1], timeout=120))
    assert got == nodes
    remove_placement_group(pg)


def test_placement_group_strict_pack_tpu_slice(ray_start_cluster):
    """STRICT_PACK = one ICI domain: all TPU bundles land on one node."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, num_tpus=4, labels={"tpu_slice": "v5e-4"})
    cluster.add_node(num_cpus=1, num_tpus=4, labels={"tpu_slice": "v5e-4"})
    cluster.connect()
    cluster.wait_for_nodes()

    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=30)
    assert pg.bundle_node(0) == pg.bundle_node(1)
    remove_placement_group(pg)


def test_infeasible_pg_pending(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()
    pg = placement_group([{"CPU": 64}], strategy="PACK")
    from ray_tpu.exceptions import PlacementGroupUnavailableError

    with pytest.raises(PlacementGroupUnavailableError):
        pg.ready(timeout=1.0)


def test_infeasible_tasks_dont_block_runnable_ones(ray_start_cluster):
    """Tasks whose resources don't exist yet park in the infeasible queue
    (reference keeps one too) — a block of them ahead of runnable CPU tasks
    must not delay the runnable ones."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"phantom_accel": 1})
    def needs_phantom():
        return "never"

    @ray_tpu.remote
    def runnable(x):
        return x * 2

    blocked = [needs_phantom.remote() for _ in range(50)]
    # Starvation shows up as this get timing out (the queue scan would only
    # revisit the runnable tasks on slow heartbeat-paced rotation).
    out = ray_tpu.get([runnable.remote(i) for i in range(8)], timeout=30)
    assert out == [i * 2 for i in range(8)]
    # The infeasible tasks are still pending (not failed, not run).
    ready, _ = ray_tpu.wait(blocked, num_returns=1, timeout=0.5)
    assert not ready
