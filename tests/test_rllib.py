"""ray_tpu.rllib tests.

Modeled on the reference's rllib test strategy (per-algorithm learning tests
against CartPole with a reward stop criterion — rllib/tuned_examples/ppo/
cartpole-ppo.yaml reward 150; unit tests for SampleBatch/GAE/buffers)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.policy.sample_batch import (
    ADVANTAGES,
    DONES,
    REWARDS,
    VALUE_TARGETS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_sample_batch_basics():
    b = SampleBatch({"a": np.arange(10), "b": np.arange(10) * 2.0})
    assert b.count == 10
    cat = SampleBatch.concat_samples([b, b])
    assert cat.count == 20
    sh = b.shuffle(seed=0)
    assert sorted(sh["a"]) == list(range(10))
    mbs = list(cat.minibatches(8, seed=1))
    assert all(mb.count == 8 for mb in mbs)


def test_gae_matches_reference_impl():
    rng = np.random.default_rng(0)
    n = 50
    batch = SampleBatch({
        REWARDS: rng.normal(size=n).astype(np.float32),
        DONES: (rng.random(n) < 0.1).astype(np.float32),
        VF_PREDS: rng.normal(size=n).astype(np.float32),
    })
    last_v = 0.3
    gamma, lam = 0.95, 0.9
    out = compute_gae(SampleBatch(dict(batch)), last_v, gamma, lam)
    # brute-force forward recomputation
    rewards, dones, values = batch[REWARDS], batch[DONES], batch[VF_PREDS]
    vals_ext = np.append(values, last_v)
    adv = np.zeros(n)
    for t in range(n):
        acc, coef = 0.0, 1.0
        for k in range(t, n):
            nonterm = 1.0 - dones[k]
            delta = rewards[k] + gamma * vals_ext[k + 1] * nonterm - values[k]
            acc += coef * delta
            if dones[k]:
                break
            coef *= gamma * lam
        adv[t] = acc
    np.testing.assert_allclose(out[ADVANTAGES], adv, atol=1e-4)
    np.testing.assert_allclose(out[VALUE_TARGETS], adv + values, atol=1e-4)


def test_replay_buffer_ring():
    buf = ReplayBuffer(capacity=100, seed=0)
    for i in range(5):
        buf.add(SampleBatch({"x": np.full(30, i)}))
    assert len(buf) == 100
    s = buf.sample(64)
    assert s.count == 64
    assert set(np.unique(s["x"])).issubset({1, 2, 3, 4})  # 0s evicted


def test_prioritized_replay_updates():
    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    buf.add(SampleBatch({"x": np.arange(64, dtype=np.float32)}))
    s = buf.sample(16)
    assert "weights" in s
    buf.update_priorities(np.ones(16) * 5.0)
    s2 = buf.sample(32)
    assert s2.count == 32


def test_vector_env_autoreset():
    from ray_tpu.rllib.env.vector_env import VectorEnv

    env = VectorEnv("CartPole-v1", 3, seed=0)
    total_done = 0
    for _ in range(300):
        _, _, dones, _ = env.step(np.zeros(3, dtype=np.int64))
        total_done += dones.sum()
    assert total_done > 0
    rewards, lens = env.pop_episode_stats()
    assert len(rewards) == total_done
    assert all(l > 0 for l in lens)
    env.close()


def test_ppo_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4)
        .training(lr=3e-4, train_batch_size=2048, sgd_minibatch_size=256, num_sgd_iter=8, entropy_coeff=0.01)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(20):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 120:
                break
        assert best >= 120, f"PPO failed to learn CartPole (best={best})"
        a = algo.compute_single_action(np.zeros(4, np.float32))
        assert a in (0, 1)
    finally:
        algo.cleanup()


def test_ppo_checkpoint_restore(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=1, num_envs_per_worker=2)
        .training(train_batch_size=256, sgd_minibatch_size=64, num_sgd_iter=2)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    algo.step()
    ckpt = algo.save_checkpoint()
    w_before = algo.get_policy_weights()
    algo.step()  # weights move on
    algo.load_checkpoint(ckpt)
    w_after = algo.get_policy_weights()
    flat_b = np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(w_before)])
    flat_a = np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(w_after)])
    np.testing.assert_allclose(flat_b, flat_a)
    algo.cleanup()


def test_dqn_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_envs_per_worker=4)
        .training(
            lr=1e-3,
            train_batch_size=64,
            learning_starts=500,
            target_network_update_freq=100,
            epsilon_timesteps=4000,
            rollout_steps_per_iter=500,
            train_intensity=2,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(20):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"DQN failed to learn CartPole (best={best})"
    finally:
        algo.cleanup()


def test_ppo_under_tune(ray_cluster):
    """Algorithms are Tune Trainables (reference: Algorithm extends
    Trainable; tune.Tuner(PPO) runs a sweep)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu import tune
    from ray_tpu.rllib import PPO

    tuner = tune.Tuner(
        PPO,
        param_space={
            "env": "CartPole-v1",
            "num_rollout_workers": 1,
            "num_envs_per_worker": 2,
            "train_batch_size": 256,
            "sgd_minibatch_size": 64,
            "num_sgd_iter": 2,
            "lr": tune.grid_search([3e-4, 1e-3]),
        },
        tune_config=tune.TuneConfig(metric="episode_reward_mean", mode="max"),
        run_config=tune.RunConfig(stop={"training_iteration": 2}),
    )
    results = tuner.fit()
    assert len(results) == 2
    assert results.get_best_result() is not None


def test_rollout_worker_fault_tolerance(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.evaluation.rollout_worker import WorkerSet

    import gymnasium as gym

    probe = gym.make("CartPole-v1")
    spec = RLModuleSpec.from_spaces(probe.observation_space, probe.action_space)
    probe.close()
    ws = WorkerSet("CartPole-v1", spec, num_workers=2, num_envs_per_worker=1)
    from ray_tpu.rllib.core.learner import Learner
    from ray_tpu.rllib.algorithms.ppo.ppo import ppo_loss

    learner = Learner(spec, ppo_loss)
    ws.sync_weights(learner.get_weights())
    batches = ws.sample(16)
    assert len(batches) == 2
    # Kill one worker's actor (kill lands asynchronously); keep sampling —
    # the round where the death lands must still succeed with the survivor,
    # and after a respawn + weight sync the set must be back to full size.
    import time

    ray_tpu.kill(ws._workers[0])
    saw_degraded = False
    for _ in range(20):
        batches = ws.sample(8)
        assert len(batches) >= 1
        if len(batches) < 2:
            saw_degraded = True
            break
        time.sleep(0.2)
    assert saw_degraded, "kill never landed"
    ws.sync_weights(learner.get_weights())
    batches = ws.sample(8)
    assert len(batches) == 2
    ws.stop()


def test_offline_json_roundtrip(tmp_path):
    """JsonWriter/JsonReader roundtrip + return-to-go targets."""
    from ray_tpu.rllib.offline import JsonReader, JsonWriter
    from ray_tpu.rllib.policy.sample_batch import VALUE_TARGETS

    w = JsonWriter(str(tmp_path))
    w.write(
        SampleBatch(
            {
                "obs": np.arange(8, dtype=np.float32).reshape(4, 2),
                "actions": np.array([0, 1, 0, 1]),
                "rewards": np.array([1.0, 1.0, 1.0, 1.0], np.float32),
                "dones": np.array([False, True, False, True]),
            }
        )
    )
    w.close()
    r = JsonReader(str(tmp_path), gamma=0.5)
    b = r.next()
    assert len(b) == 4
    # episode 1: returns [1 + .5, 1]; episode 2 same
    assert np.allclose(b[VALUE_TARGETS], [1.5, 1.0, 1.5, 1.0])
    mini = r.next(2)
    assert len(mini) == 2


def test_bc_imitates_expert(ray_cluster, tmp_path):
    """BC learns an obs->action rule from offline data (reference:
    rllib/algorithms/bc tests): expert picks action = 1 iff obs[0] > 0."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.offline import JsonWriter

    rng = np.random.default_rng(0)
    obs = rng.uniform(-1, 1, size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 0] > 0).astype(np.int64)
    w = JsonWriter(str(tmp_path))
    w.write(
        SampleBatch(
            {
                "obs": obs,
                "actions": actions,
                "rewards": np.ones(2000, np.float32),
                "dones": (np.arange(2000) % 100 == 99),
            }
        )
    )
    w.close()

    cfg = (
        BCConfig()
        .environment("CartPole-v1")  # spaces only; no rollouts
        .rollouts(num_rollout_workers=0)
        .training(lr=5e-3, train_batch_size=512)
        .debugging(seed=0)
    )
    cfg.offline_data(input_=str(tmp_path))
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        first = None
        for _ in range(60):
            r = algo.step()
            if first is None:
                first = r["bc_logp"]
        assert r["bc_logp"] > first, (first, r["bc_logp"])
        # The learned policy reproduces the expert rule.
        correct = 0
        probe = rng.uniform(-1, 1, size=(50, 4)).astype(np.float32)
        for o in probe:
            a = algo.compute_single_action(o)
            correct += int(a == int(o[0] > 0))
        assert correct >= 45, f"BC policy only matched {correct}/50 expert actions"
    finally:
        algo.cleanup()


def test_marwil_prefers_high_return_actions(ray_cluster, tmp_path):
    """MARWIL upweights trajectories with higher return-to-go: with mixed
    expert/anti-expert data where the expert earns more reward, beta>0 must
    recover the expert rule."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import MARWILConfig
    from ray_tpu.rllib.offline import JsonWriter

    rng = np.random.default_rng(1)
    n = 3000
    obs = rng.uniform(-1, 1, size=(n, 4)).astype(np.float32)
    expert_a = (obs[:, 0] > 0).astype(np.int64)
    # half the data follows the expert (reward 1), half does the opposite (reward 0)
    follow = rng.uniform(size=n) < 0.5
    actions = np.where(follow, expert_a, 1 - expert_a)
    rewards = np.where(follow, 1.0, 0.0).astype(np.float32)
    dones = np.ones(n, bool)  # 1-step episodes: return == immediate reward
    w = JsonWriter(str(tmp_path))
    w.write(SampleBatch({"obs": obs, "actions": actions, "rewards": rewards, "dones": dones}))
    w.close()

    cfg = (
        MARWILConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0)
        .training(lr=5e-3, train_batch_size=1024, beta=2.0)
        .debugging(seed=0)
    )
    cfg.offline_data(input_=str(tmp_path))
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(80):
            algo.step()
        probe = rng.uniform(-1, 1, size=(50, 4)).astype(np.float32)
        correct = sum(
            int(algo.compute_single_action(o) == int(o[0] > 0)) for o in probe
        )
        assert correct >= 40, f"MARWIL matched expert on only {correct}/50"
    finally:
        algo.cleanup()


def test_impala_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4)
        .training(
            lr=1e-3,
            train_batch_size=2048,
            entropy_coeff=0.01,
            num_sgd_iter=2,
            broadcast_interval=1,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"IMPALA failed to learn CartPole (best={best})"
    finally:
        algo.cleanup()


def test_connectors_mean_std_filter():
    """MeanStdFilter: running normalization + Chan merge across workers
    (reference: rllib/utils/filter.py + connector pipelines)."""
    from ray_tpu.rllib.connectors import (
        ClipActions,
        ConnectorPipeline,
        FlattenObservations,
        MeanStdFilter,
    )

    rng = np.random.default_rng(0)
    data = rng.normal(loc=5.0, scale=3.0, size=(500, 4))
    f = MeanStdFilter()
    out = f(data)
    assert abs(float(np.mean(out))) < 0.2
    assert abs(float(np.std(out)) - 1.0) < 0.2
    # transform() does not update stats
    st = f.get_state()
    f.transform(rng.normal(size=(100, 4)))
    assert f.get_state()["count"] == st["count"]
    # Chan merge of two shards == one filter over all data
    f1, f2, fall = MeanStdFilter(), MeanStdFilter(), MeanStdFilter()
    a, b = data[:200], data[200:]
    f1(a)
    f2(b)
    fall(data)
    merged = MeanStdFilter()
    merged.merge_states([f1.get_state(), f2.get_state()])
    np.testing.assert_allclose(merged.get_state()["mean"], fall.get_state()["mean"], rtol=1e-9)
    np.testing.assert_allclose(merged.get_state()["m2"], fall.get_state()["m2"], rtol=1e-9)
    # pipeline composes
    pipe = ConnectorPipeline([FlattenObservations(), MeanStdFilter()])
    assert pipe(rng.normal(size=(10, 2, 2))).shape == (10, 4)
    clip = ClipActions(low=-1.0, high=1.0)
    assert np.all(np.abs(clip(np.array([-5.0, 0.2, 9.0]))) <= 1.0)


def test_ppo_with_observation_filter(ray_cluster):
    """End-to-end: PPO with MeanStdFilter connectors still learns and the
    filter stats synchronize across workers."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=2, observation_filter="MeanStdFilter")
        .training(lr=3e-4, train_batch_size=1024, sgd_minibatch_size=128, num_sgd_iter=4)
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        r = None
        for _ in range(4):
            r = algo.step()
        assert np.isfinite(r["policy_loss"])
        # Both workers hold identical (merged) filter stats after sync.
        states = [
            ray_tpu.get(w.get_filter_state.remote()) for w in algo.workers._workers
        ]
        assert states[0]["count"] == states[1]["count"] > 0
        np.testing.assert_allclose(states[0]["mean"], states[1]["mean"])
        # Delta-sync accounting: the merged count equals real samples seen
        # (full-state re-merging would compound ~2x per iteration).
        total_sampled = 4 * 1024  # iterations * train_batch_size
        assert states[0]["count"] <= total_sampled * 1.2, states[0]["count"]
    finally:
        algo.cleanup()
