"""Connector pipeline tests (reference: rllib/connectors/connector.py:320
ConnectorPipeline, agent/pipeline.py:21, tests/connectors/):
composition, stateful stages, serialize/deserialize round-trips, and two
algorithms sampling through pipelines on rollout AND eval workers."""

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (
    ActionConnectorPipeline,
    AgentConnectorPipeline,
    ClipActions,
    ClipObservations,
    ConnectorPipeline,
    FrameStack,
    MeanStdFilter,
    ObsPreprocessor,
    UnsquashActions,
    ViewRequirementConnector,
)


def test_pipeline_composition_ops():
    p = AgentConnectorPipeline([ClipObservations(-1, 1)])
    p.append(ViewRequirementConnector(input_dim=4))
    p.prepend(ObsPreprocessor(lambda o: o * 2.0))
    p.insert_after("ObsPreprocessor", FrameStack(1))
    assert [type(c).__name__ for c in p.connectors] == [
        "ObsPreprocessor", "FrameStack", "ClipObservations", "ViewRequirementConnector",
    ]
    p.remove("FrameStack")
    assert "FrameStack" not in repr(p)
    with pytest.raises(ValueError):
        p.remove("FrameStack")
    obs = np.full((3, 4), 0.9, np.float32)
    out = p(obs)  # *2 -> clip to 1 -> view check
    assert out.shape == (3, 4) and np.allclose(out, 1.0)


def test_frame_stack_resets_on_episode_done():
    fs = FrameStack(3)
    o1 = np.array([[1.0], [10.0]])
    o2 = np.array([[2.0], [20.0]])
    o3 = np.array([[3.0], [30.0]])
    assert fs(o1).tolist() == [[1, 1, 1], [10, 10, 10]]  # seeded with first obs
    assert fs(o2).tolist() == [[1, 1, 2], [10, 10, 20]]
    # env slot 1 finishes an episode; slot 0 continues
    fs.on_episode_done(np.array([False, True]))
    out = fs(o3)
    assert out[0].tolist() == [1, 2, 3]      # continuing: true history
    assert out[1].tolist() == [30, 30, 30]   # new episode: re-seeded


def test_view_requirement_flattens_and_validates():
    vr = ViewRequirementConnector(input_dim=12, flatten=True)
    out = vr(np.zeros((5, 2, 2, 3)))
    assert out.shape == (5, 12) and out.dtype == np.float32
    with pytest.raises(ValueError, match="view requirement"):
        vr(np.zeros((5, 7)))


def test_action_stages():
    unsquash = UnsquashActions(low=np.array([0.0]), high=np.array([10.0]))
    assert np.allclose(unsquash(np.array([[-1.0], [0.0], [1.0], [5.0]])), [[0], [5], [10], [10]])
    clip = ClipActions(low=-2, high=2)
    assert np.allclose(clip(np.array([-5.0, 0.5, 5.0])), [-2, 0.5, 2])


def test_pipeline_serialize_roundtrip_preserves_state():
    """VERDICT done-bar: composition round-trips serialize/deserialize WITH
    stateful stages' learned statistics and buffers intact."""
    p = AgentConnectorPipeline([MeanStdFilter(), FrameStack(2)])
    rng = np.random.RandomState(0)
    for _ in range(10):
        p(rng.randn(4, 3).astype(np.float32) * 5 + 2)

    blob = p.serialize()
    q = ConnectorPipeline.deserialize(blob)
    assert isinstance(q, AgentConnectorPipeline)
    assert [type(c).__name__ for c in q.connectors] == ["MeanStdFilter", "FrameStack"]
    # identical learned stats: transform-only outputs match exactly
    probe = rng.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(p.transform(probe.copy()), q.transform(probe.copy()))
    # frame buffers survived too
    st_p, st_q = p.get_state(), q.get_state()
    np.testing.assert_allclose(st_p[1]["frames"], st_q[1]["frames"])

    ap = ActionConnectorPipeline([UnsquashActions(0.0, 4.0)])
    aq = ConnectorPipeline.deserialize(ap.serialize())
    assert isinstance(aq, ActionConnectorPipeline)
    assert np.allclose(aq(np.array([0.0])), [2.0])


def test_mean_std_filter_transform_does_not_learn():
    f = MeanStdFilter()
    f(np.ones((8, 2), np.float32))
    before = f.get_state()
    f.transform(np.full((8, 2), 100.0, np.float32))
    after = f.get_state()
    assert before["count"] == after["count"]


def _scale_obs(o):
    # module-level so plain pickle works in actor-creation args
    return np.asarray(o, np.float32) * 1.0


@pytest.mark.parametrize("algo_name", ["ppo", "a2c"])
def test_algorithms_sample_through_pipelines(ray_start_regular, algo_name):
    """Two algorithm families sample via rollout workers whose obs flow
    through an AgentConnectorPipeline with a custom preprocess stage, and
    evaluation runs through the SAME pipeline config."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    stages = [ObsPreprocessor(_scale_obs)]
    if algo_name == "ppo":
        from ray_tpu.rllib import PPOConfig

        cfg = (
            PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                      agent_connectors=stages, observation_filter="MeanStdFilter")
            .training(train_batch_size=200, sgd_minibatch_size=64, num_sgd_iter=2)
            .evaluation(evaluation_interval=1, evaluation_duration=2)
        )
    else:
        from ray_tpu.rllib import A2CConfig

        cfg = (
            A2CConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=2,
                      agent_connectors=stages)
            .training(train_batch_size=200)
            .evaluation(evaluation_interval=1, evaluation_duration=2)
        )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        r = algo.step()
        assert r.get("timesteps_total", r.get("num_env_steps_sampled", 1)) > 0
        # the training workers really hold a pipeline with our stage
        w = algo.workers._workers[0]
        blobs = ray_tpu.get(w.get_connector_state.remote(), timeout=120)
        names = [
            type(c).__name__
            for c in ConnectorPipeline.deserialize(blobs["agent"]).connectors
        ]
        assert "ObsPreprocessor" in names
        if algo_name == "ppo":
            assert names[0] == "MeanStdFilter"  # filter is a pipeline stage
        # eval rides the SAME pipeline config on its own workers
        ev = algo.evaluate()
        assert "evaluation" in ev or ev  # eval ran
        ew = algo.eval_workers._workers[0]
        eblobs = ray_tpu.get(ew.get_connector_state.remote(), timeout=120)
        enames = [
            type(c).__name__
            for c in ConnectorPipeline.deserialize(eblobs["agent"]).connectors
        ]
        assert "ObsPreprocessor" in enames
    finally:
        algo.cleanup()
