"""Tier-1 dispatch-path smoke (microbench.py --smoke).

Runs the sync/async task, actor-call, and 1 MiB object-plane loops at tiny
counts (CPU-only, <30 s on an unloaded box) in a subprocess, so breakage of
the dispatch stack fails pytest here instead of only surfacing at the next
bench round.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_microbench_smoke(tmp_path):
    out = tmp_path / "smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "microbench.py"), "--smoke", "--out", str(out)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,  # generous for loaded CI boxes; ~5 s unloaded
    )
    assert proc.returncode == 0, (
        f"microbench --smoke failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "task_sync_per_s",
        "task_async100_per_s",
        "actor_call_sync_per_s",
        "actor_call_async100_per_s",
        "put_1mib_per_s",
        "putget_1mib_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero in smoke artifact: {data}"
