"""Tier-1 dispatch-path smoke (microbench.py --smoke).

Runs the sync/async task, actor-call, and 1 MiB object-plane loops at tiny
counts (CPU-only, <30 s on an unloaded box) in a subprocess, so breakage of
the dispatch stack fails pytest here instead of only surfacing at the next
bench round.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_microbench_smoke(tmp_path):
    out = tmp_path / "smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "microbench.py"), "--smoke", "--out", str(out)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,  # generous for loaded CI boxes; ~5 s unloaded
    )
    assert proc.returncode == 0, (
        f"microbench --smoke failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "task_sync_per_s",
        "task_async100_per_s",
        "actor_call_sync_per_s",
        "actor_call_async100_per_s",
        "put_1mib_per_s",
        "putget_1mib_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero in smoke artifact: {data}"


def test_transfer_smoke(tmp_path):
    """<30s --transfer --quick pass: raw-vs-msgpack push A/B, pull striping
    over the modeled per-source link, cut-through broadcast, and the
    dispatch-plane guards all produce nonzero numbers. Perf certification
    lives in the committed TRANSFER_r10.json (full shapes); this exists so
    transfer-plane breakage fails pytest instead of the next bench round."""
    out = tmp_path / "transfer.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--transfer",
            "--quick",
            "--round",
            "10",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, (
        f"microbench --transfer failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    assert not [k for k in data if k.endswith("_error")], data
    for key in (
        "push_raw_mib_per_s",
        "push_msgpack_mib_per_s",
        "pull_1replica_mib_per_s",
        "pull_2replica_mib_per_s",
        "broadcast_aggregate_mib_per_s",
        "putget_1mib_per_s",
        "shuffle_push_rows_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero in transfer artifact: {data}"
    # The negotiated default must actually BE the raw path (a silent
    # fallback to msgpack everywhere would still produce numbers).
    assert data.get("transfer_chunks_raw", 0) > 0, data


def test_serve_llm_smoke(tmp_path):
    """<30s --serve --quick pass (ISSUE 11): the closed-loop generator runs
    both arms (serial-batch baseline + continuous batching) against the
    serve.llm engine and produces nonzero TTFT/tokens-per-second numbers
    with prefix-cache hits. Perf certification (>=2x tokens/s, p99 TTFT
    reduced at 8 streams) lives in the committed SERVEBENCH_r11.json; this
    exists so engine/scheduler breakage fails pytest instead of the next
    bench round — the quick arms are too short/noisy to re-certify ratios."""
    out = tmp_path / "servebench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--serve",
            "--quick",
            "--round",
            "11",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, (
        f"microbench --serve failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "serve_serial_tokens_per_s",
        "serve_continuous_tokens_per_s",
        "serve_serial_ttft_p99_ms",
        "serve_continuous_ttft_p99_ms",
        "serve_continuous_tpot_mean_ms",
    ):
        assert data.get(key, 0), f"{key} missing/zero in serve artifact: {data}"
    # The shared system prompt must actually ride the prefix cache.
    assert data.get("serve_continuous_prefix_hit_blocks", 0) > 0, data


def test_recorder_overhead_smoke(tmp_path):
    """<30s --recorder-overhead --quick pass: the always-on observability
    plane (flight recorder + 1-in-64 hop sampling) A/Bs against itself in
    one cluster and stays under a lenient bound. The committed artifact
    (OBSBENCH_r8.json, 150 pairs) records ~2%; the bound here is loose
    because this 1-core CI box shows +-10% single-pair noise and the quick
    pass only runs 8 pairs — it exists to catch an accidental O(task)
    instrumentation blowup (e.g. a per-task lock or RPC), not to re-certify
    the 3% acceptance number."""
    out = tmp_path / "obsbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--recorder-overhead",
            "--quick",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, (
        f"microbench --recorder-overhead failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    assert data.get("recorder_on_task_sync_per_s", 0) > 0
    assert data.get("recorder_off_task_sync_per_s", 0) > 0
    assert len(data.get("recorder_pair_ratios", [])) >= 4
    assert data["recorder_overhead_pct"] < 25.0, data


def test_microbench_pipeline_smoke(tmp_path):
    """<60s --pipeline --quick pass (ISSUE 12): all four arms (spmd
    pipeline_apply, classic device-dispatch, classic host, MPMD compiled)
    produce throughput numbers at M=4, the MPMD outputs are bit-exact vs
    pipeline_apply, and the steady-state evidence holds — 0 raylet RPCs
    per iteration, 0 host-store activation objects, 0 host-fallback
    transfers (deterministic counters, not timing). Perf certification
    (>=2x vs classic dispatch, bubble at M in {4,16}) lives in the
    committed PIPEBENCH_r12.json — the quick arms are too short/noisy to
    re-certify ratios."""
    out = tmp_path / "pipebench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--pipeline",
            "--quick",
            "--round",
            "12",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=360,
    )
    assert proc.returncode == 0, (
        f"microbench --pipeline failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "pipeline_spmd_m4_iter_per_s",
        "pipeline_classic_m4_iter_per_s",
        "pipeline_classic_host_m4_iter_per_s",
        "pipeline_mpmd_m4_iter_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    assert data["pipeline_parity_bitexact"] is True, data
    assert data["pipeline_mpmd_m4_raylet_rpcs_per_iter"] == 0, data
    assert data["pipeline_mpmd_m4_store_objects_delta"] == 0, data
    assert data["pipeline_mpmd_m4_host_transfers_delta"] == 0, data
    assert data["pipeline_mpmd_m4_chan_sends"] > 0, data


def test_microbench_device_objects_smoke(tmp_path):
    """<30s device-object plane case (microbench.py --device-objects
    --quick): host and device paths both produce throughput numbers, and
    the zero-copy evidence holds — the same-process device loop adds ZERO
    objects to the node store (deterministic counter, not timing) while
    every iteration resolves as a local (live-array) transfer."""
    out = tmp_path / "devbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--device-objects",
            "--quick",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, (
        f"microbench --device-objects failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "host_putget_1mib_per_s",
        "devobj_putget_1mib_per_s",
        "host_putget_32mib_per_s",
        "devobj_putget_32mib_per_s",
        "handoff_host_1mib_per_s",
        "handoff_devobj_1mib_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    # Zero host-shm copies of the payload on the same-process device path
    # (<= 0: the preceding host loop's async frees may still be draining).
    assert data["devobj_putget_1mib_store_objects_delta"] <= 0, data
    assert data["devobj_putget_32mib_store_objects_delta"] <= 0, data
    assert data["devobj_putget_1mib_local_transfers"] > 0, data


def test_microbench_collective_smoke(tmp_path):
    """<60s --collective --quick pass (ISSUE 15): both weight-sync arms
    (K-serial-unicast baseline, group broadcast) produce latency/throughput
    numbers at K=2, the device path's zero-host-store evidence holds
    (deterministic counters), residents drain after every sync, and the
    end-to-end Podracer IMPALA rows exist with every measured iteration's
    sync riding the broadcast plane. Perf certification (>=2x aggregate at
    K=8) lives in the committed COLLBENCH_r15.json — quick arms are too
    short/noisy to re-certify ratios."""
    out = tmp_path / "collbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--collective",
            "--quick",
            "--round",
            "15",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=360,
    )
    assert proc.returncode == 0, (
        f"microbench --collective failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in (
        "wsync_serial_k2_s",
        "wsync_broadcast_k2_s",
        "wsync_serial_k2_mib_per_s",
        "wsync_broadcast_k2_mib_per_s",
        "podracer_host_iters_per_s",
        "podracer_device_broadcast_iters_per_s",
    ):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    # Device path: zero host-store copies of the payload, residents drained.
    assert data["wsync_broadcast_k2_store_objects_delta"] == 0, data
    assert data["wsync_k2_residents_after"] == 0, data
    # Every measured Podracer iteration's sync rode the broadcast plane.
    assert data["podracer_device_broadcasts"] >= 2, data
    # ISSUE 16 relay-tree arm: mid-tree members actually forwarded payload,
    # nothing touched the host store, and the allreduce oracle held
    # bit-exact (deterministic counters — ratio certification lives in the
    # committed COLLBENCH_r16.json full sweep).
    for key in ("relay_tree_k3_s", "relay_flat_k3_s", "allreduce_tree_k3_s"):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    assert data["relay_k3_relay_forwards"] > 0, data
    assert data["relay_k3_store_objects_delta"] == 0, data
    assert data["allreduce_k3_bit_exact"] == 1, data
    # ISSUE 20 reducescatter verb: tree and ring arms both produced rows,
    # every rank's shard matched the float32 oracle bit-exact, and the
    # tree arm's shards rode the direct mailboxes (scatter_bytes moved).
    for key in ("reducescatter_tree_k3_s", "reducescatter_ring_k3_s"):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    assert data["reducescatter_k3_bit_exact"] == 1, data
    assert data["reducescatter_k3_scatter_bytes"] > 0, data


def test_microbench_resize_smoke(tmp_path):
    """<90s --collective --resize --quick pass (ISSUE 17): IMPALA on the
    device-broadcast plane through a scripted 2→4→2 sampler resize. The
    suite's inline oracle is the real assertion — after the first
    post-resize iteration every measured weight sync rides the broadcast
    plane (fleet-wide host-sync fallback delta == 0 in every phase, which
    a failed roster join/evict would break). Full-shape 8→16→8 evidence
    lives in the committed RESIZEBENCH_r17.json."""
    out = tmp_path / "resizebench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--collective",
            "--resize",
            "--quick",
            "--round",
            "17",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=360,
    )
    assert proc.returncode == 0, (
        f"microbench --collective --resize failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    assert data["resize_schedule"] == [2, 4, 2], data
    for phase, n in enumerate(data["resize_schedule"]):
        assert data.get(f"resize_p{phase}_n{n}_iters_per_s", 0) > 0, data
        # Plane syncs cover the whole fleet every measured iteration...
        assert data[f"resize_p{phase}_n{n}_plane_syncs"] >= n * 2, data
        # ...and ZERO host-sync fallbacks after the first post-resize iter.
        assert data[f"resize_p{phase}_n{n}_host_fallbacks"] == 0, data
    # Grow and shrink both happened and were timed.
    assert data.get("resize_p1_to4_s", 0) > 0, data
    assert data.get("resize_p2_to2_s", 0) > 0, data
    # After the final shrink the roster is back to learner + 2 samplers.
    assert data["resize_final_roster_ranks"] == [0, 1, 2], data


@pytest.mark.slow
def test_collective_k8_sweep(tmp_path):
    """Full-shape K in {2,4,8} sweep (slow): the broadcast arm must beat
    the K-serial-unicast arm at K=8. The committed COLLBENCH_r15.json
    certifies >=2x on an idle box; this bound is looser because shared CI
    boxes inflate the (concurrency-sensitive) broadcast arm more than the
    serial one."""
    out = tmp_path / "collbench_full.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--collective",
            "--round",
            "15",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"microbench --collective failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for k in (2, 4, 8):
        assert data.get(f"wsync_broadcast_k{k}_mib_per_s", 0) > 0, data
        assert data[f"wsync_broadcast_k{k}_store_objects_delta"] == 0, data
        assert data[f"wsync_k{k}_residents_after"] == 0, data
    assert data["wsync_speedup_k8"] > 1.2, data
    # ISSUE 16: under the modeled per-process egress link the relay tree
    # must beat the flat fan-out at K=8 and the gap must WIDEN with K
    # (root egress is O(log K) vs O(K)); the allreduce oracle stays
    # bit-exact at every K.
    for k in (4, 8):
        assert data.get(f"relay_tree_k{k}_agg_mib_per_s", 0) > 0, data
        assert data[f"relay_k{k}_store_objects_delta"] == 0, data
        assert data[f"relay_k{k}_relay_forwards"] > 0, data
        assert data[f"allreduce_k{k}_bit_exact"] == 1, data
        assert data.get(f"reducescatter_tree_k{k}_agg_mib_per_s", 0) > 0, data
        assert data[f"reducescatter_k{k}_bit_exact"] == 1, data
        assert data[f"reducescatter_k{k}_scatter_bytes"] > 0, data
    assert data["relay_tree_speedup_k8"] > 1.2, data
    assert data["relay_tree_speedup_k8"] > data["relay_tree_speedup_k4"], data
    assert (
        data["relay_tree_k8_root_egress_frac"] < data["relay_tree_k4_root_egress_frac"]
    ), data


def test_microbench_sim_smoke(tmp_path):
    """--sim --quick pass (ISSUE 19): the control-plane scale harness boots
    64/128-shell sim clusters in both heartbeat arms and produces the full
    evidence shape — delta arm with ZERO steady-state view rows vs the
    legacy full-view arm's per-node byte tax, node-death index vs scan,
    locality arms with 100% holder hits and a no-locality baseline, the
    bounded task-event ring with an exact dropped count, and a passing SLO
    scorecard. Scale certification (512/1000 shells, sub-quadratic curve)
    lives in the committed SIMBENCH_r19.json — the quick arms only prove
    the machinery."""
    out = tmp_path / "simbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--sim",
            "--quick",
            "--round",
            "19",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=360,
    )
    assert proc.returncode == 0, (
        f"microbench --sim failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    sweep = data["sim_sweep"]
    for arm in ("n64_delta", "n64_legacy", "n128_delta", "n128_legacy"):
        assert sweep[arm]["tasks_per_s"] > 0, sweep
        assert sweep[arm]["placement_p99_ms"] > 0, sweep
    # The fan-in fix, as counters: delta arm serves ZERO full replies and
    # ZERO steady-state view rows; the legacy arm pays O(N) rows per reply.
    for n in (64, 128):
        assert sweep[f"n{n}_delta"]["hb_full_replies"] == 0, sweep
        assert sweep[f"n{n}_delta"]["hb_view_rows_per_interval"] == 0, sweep
        assert sweep[f"n{n}_legacy"]["hb_view_bytes_per_node_per_interval"] > 0, sweep
    # Per-node heartbeat bytes GROW with N on the legacy arm (the quadratic
    # signature) — the delta arm's stay flat at zero.
    assert (
        sweep["n128_legacy"]["hb_view_bytes_per_node_per_interval"]
        > sweep["n64_legacy"]["hb_view_bytes_per_node_per_interval"]
    ), sweep
    # Node-death via the per-node location index beats the full-table scan.
    death = data["sim_node_death"]
    assert death["index"]["victim_rows"] == death["scan"]["victim_rows"] > 0, death
    assert death["index"]["on_node_death_ms"] < death["scan"]["on_node_death_ms"], death
    # Locality arm pins every ref-arg task to its holder, flight-evidenced;
    # the no-locality arm is the measured zero baseline.
    loc = data["sim_locality"]
    assert loc["locality"]["holder_hit_frac"] == 1.0, loc
    assert loc["locality"]["locality_hit_events"] > 0, loc
    assert loc["no_locality"]["holder_hits"] == 0, loc
    # Event flood: ring bounded, drops counted exactly.
    ev = data["sim_task_events"]
    assert ev["ring_size_after"] == ev["ring_maxlen"], ev
    assert ev["events_dropped_total"] == ev["events_sent"] - ev["ring_maxlen"], ev
    # Chaos cells all posted passing SLO verdicts.
    assert data["sim_slo_ok"] is True, data.get("sim_slo_scorecard")


def test_microbench_dag_smoke(tmp_path):
    """<30s classic-vs-compiled DAG case (microbench.py --dag --quick):
    both paths produce throughput numbers, and the compiled loop's
    control-plane evidence holds — 0 raylet RPCs and 0 new ObjectRefs per
    iteration (deterministic counters, not timing)."""
    out = tmp_path / "dagbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--dag",
            "--quick",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,  # generous for loaded CI boxes; ~7 s unloaded
    )
    assert proc.returncode == 0, (
        f"microbench --dag failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    for key in ("dag_classic_per_s", "dag_compiled_per_s"):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    assert data["dag_compiled_raylet_rpcs_per_iter"] == 0
    assert data["dag_compiled_new_object_refs_per_iter"] == 0
    # Compiled stamps exist and contain no raylet stage.
    compiled_budget = data["dag_hop_budget"]["compiled"]
    assert compiled_budget["count"] > 0
    assert not any("raylet" in s for s in compiled_budget["stages_us"])


def test_serve_disagg_smoke(tmp_path):
    """--serve-disagg --quick pass (ISSUE 20): the disaggregated arm boots
    a real serve instance (2 prefill + 2 decode replicas), streams mixed
    long-prefill/short-decode load, and the machinery evidence holds on
    deterministic counters — every short stream rode a prefill->decode KV
    handoff with ZERO store objects minted, the warm-seeded cluster prefix
    row produced a cross-replica import hit, and every replica's KV pool
    drained back to full. The tiny quick model is dispatch-bound on one
    host CPU, so TTFT/throughput RATIOS are certified by the committed
    DISAGGBENCH_r20.json full sweep (compute-bound model), not here."""
    out = tmp_path / "disaggbench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--serve-disagg",
            "--quick",
            "--round",
            "20",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=360,
    )
    assert proc.returncode == 0, (
        f"microbench --serve-disagg failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    # Both arms streamed real tokens.
    for key in ("mono_tokens_per_s", "disagg_tokens_per_s"):
        assert data.get(key, 0) > 0, f"{key} missing/zero: {data}"
    # Monolithic arm never handed off; disaggregated arm always did.
    assert data["mono_kv_leak_blocks"] == 0, data
    assert data["disagg_handoffs"] > 0, data
    assert data["disagg_handoff_failed"] == 0, data
    # Zero raylet-store traffic on the handoff path (sealed device objects
    # over direct mailboxes, not plasma).
    assert data["disagg_store_objects_delta"] == 0, data
    assert data["mono_store_objects_delta"] == 0, data
    # Cluster prefix tier: the warm phase's shared system prompt produced
    # at least one cross-replica import instead of a recompute.
    assert data["disagg_prefix_import_hits"] > 0, data
    # KV pools fully restored once idle (free + cached == total).
    assert data["disagg_kv_leak_blocks"] == 0, data
    # Flight evidence rode along (codes 50/51).
    assert data["disagg_handoff_flight_events"] > 0, data
    assert data["disagg_prefix_import_flight_events"] > 0, data


@pytest.mark.slow
def test_serve_disagg_full_sweep(tmp_path):
    """Full compute-bound sweep (slow): disaggregation must materially cut
    short-stream p99 TTFT under mixed load at an EQUAL replica budget
    without giving up aggregate throughput. The committed
    DISAGGBENCH_r20.json certifies -69.9% p99 TTFT and 1.21x tokens on an
    idle box; these bounds are looser because shared CI boxes inflate the
    (latency-sensitive) closed-loop arms unevenly."""
    out = tmp_path / "disaggbench_full.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "microbench.py"),
            "--serve-disagg",
            "--round",
            "20",
            "--out",
            str(out),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"microbench --serve-disagg failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    data = json.loads(out.read_text())
    assert data["disagg_short_ttft_p99_ms"] < data["mono_short_ttft_p99_ms"], data
    assert data["disagg_short_ttft_p99_reduction_pct"] > 20, data
    assert data["disagg_tokens_vs_mono"] >= 0.9, data
    assert data["disagg_handoff_failed"] == 0, data
    assert data["disagg_prefix_import_hits"] > 0, data
    assert data["disagg_store_objects_delta"] == 0, data
    assert data["disagg_kv_leak_blocks"] == 0, data
