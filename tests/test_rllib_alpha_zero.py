"""AlphaZero (single-player MCTS) on state-cloneable CartPole.

Learning-gated (reference: rllib/algorithms/alpha_zero/ CartPole example):
self-play must improve substantially, and MCTS-planned evaluation must
reach near the horizon cap.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    ray_tpu.init(num_cpus=2, object_store_memory=96 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


def test_alpha_zero_learns_cartpole(ray_cluster):
    from ray_tpu.rllib import AlphaZeroConfig

    cfg = (
        AlphaZeroConfig()
        .environment("CartPole-v1")
        .training(
            num_sims=25,
            episodes_per_iter=3,
            updates_per_iter=30,
            horizon=200,
            lr=5e-3,
            temperature_timesteps=1500,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(22):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 120:
                break
        assert best >= 100, f"AlphaZero self-play failed to improve (best={best})"

        # Planning-mode evaluation: MCTS + learned net should max out (or
        # nearly max out) the horizon.
        totals = []
        for ep in range(2):
            obs, _ = algo.env.reset(seed=900 + ep)
            total, done = 0.0, False
            while not done:
                a = algo.compute_single_action(obs, use_mcts=True)
                obs, rr, term, trunc, _ = algo.env.step(a)
                total += rr
                done = term or trunc
            totals.append(total)
        assert np.mean(totals) >= 150, f"MCTS evaluation weak: {totals}"
    finally:
        algo.cleanup()


def test_alpha_zero_checkpoint_roundtrip(ray_cluster):
    from ray_tpu.rllib import AlphaZeroConfig

    cfg = (
        AlphaZeroConfig()
        .environment("CartPole-v1")
        .training(num_sims=8, episodes_per_iter=1, updates_per_iter=3, horizon=50)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    algo.step()
    ckpt = algo.save_checkpoint()
    algo2 = cfg.build()
    algo2.setup(cfg.to_dict())
    algo2.load_checkpoint(ckpt)
    assert algo2._timesteps_total == algo._timesteps_total
    import jax

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        algo.params, algo2.params,
    )
    algo.cleanup()
    algo2.cleanup()


def test_state_clone_wrapper_restores_exactly(ray_cluster):
    import gymnasium as gym

    from ray_tpu.rllib.algorithms.alpha_zero import StateCloneWrapper

    env = StateCloneWrapper(gym.make("CartPole-v1"), horizon=100)
    obs, _ = env.reset(seed=3)
    state = env.get_state()
    o1, *_ = env.step(0)
    env.set_state(state)
    o2, *_ = env.step(0)
    np.testing.assert_allclose(o1, o2)
    env.set_state(state)
    o3, *_ = env.step(1)
    assert not np.allclose(o1, o3)
    env.close()
