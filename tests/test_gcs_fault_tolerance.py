"""GCS fault-tolerance tests.

Modeled on the reference's python/ray/tests/test_gcs_fault_tolerance.py: the
GCS restarts from its persisted snapshot on the same address; raylets detect
the restart, re-register, and republish object locations; named actors and
the KV survive; the cluster keeps executing tasks.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import worker_context
from ray_tpu._private.config import init_config
from ray_tpu._private.core_worker import DRIVER, CoreWorker
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


def test_gcs_restart_preserves_state(tmp_path):
    init_config(None)
    persist = str(tmp_path / "gcs_snapshot.pkl")
    session_dir = str(tmp_path / "session")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)

    gcs = GcsServer(persist_path=persist)
    host, port = gcs.address
    raylet = Raylet(gcs.address, session_dir, resources={"CPU": 2})
    cw = CoreWorker(
        mode=DRIVER,
        gcs_address=gcs.address,
        raylet_address=raylet.address,
        arena_name=raylet.arena_name,
        node_id=raylet.node_id,
        session_dir=session_dir,
    )
    worker_context.set_core_worker(cw)
    try:

        @ray_tpu.remote(name="ft-actor")
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        cw.gcs.call("kv_put", {"key": "ft:probe", "value": b"survives", "overwrite": True})
        # Ensure the state is in the snapshot before the "crash".
        gcs.save_snapshot()
        gcs.stop()

        # Restart the GCS on the SAME address from the snapshot.
        gcs2 = GcsServer(host=host, port=port, persist_path=persist)
        try:
            # Raylet heartbeats hit "unknown", re-register, and come back.
            deadline = time.time() + 30
            alive = False
            while time.time() < deadline:
                nodes = gcs2.nodes
                if any(n.get("state") == "ALIVE" for n in nodes.values()):
                    alive = True
                    break
                time.sleep(0.2)
            assert alive, "raylet did not re-register after GCS restart"

            # KV survived.
            resp = cw.gcs.call("kv_get", {"key": "ft:probe"})
            assert resp.get("found") and bytes(resp["value"]) == b"survives"

            # Named actor survived (table restored) and still serves calls
            # (the actor process never died; calls are direct transport).
            h = ray_tpu.get_actor("ft-actor")
            assert ray_tpu.get(h.inc.remote(), timeout=60) == 2

            # New tasks still schedule.
            @ray_tpu.remote
            def f():
                return "post-restart"

            assert ray_tpu.get(f.remote(), timeout=60) == "post-restart"
        finally:
            gcs2.stop()
    finally:
        worker_context.set_core_worker(None)
        try:
            cw.shutdown()
        except Exception:
            pass
        raylet.stop()
