"""GCS fault-tolerance tests.

Modeled on the reference's python/ray/tests/test_gcs_fault_tolerance.py: the
GCS restarts from its persisted snapshot on the same address; raylets detect
the restart, re-register, and republish object locations; named actors and
the KV survive; the cluster keeps executing tasks.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import worker_context
from ray_tpu._private.config import init_config
from ray_tpu._private.core_worker import DRIVER, CoreWorker
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


def test_gcs_restart_preserves_state(tmp_path):
    init_config(None)
    persist = str(tmp_path / "gcs_snapshot.pkl")
    session_dir = str(tmp_path / "session")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)

    gcs = GcsServer(persist_path=persist)
    host, port = gcs.address
    raylet = Raylet(gcs.address, session_dir, resources={"CPU": 2})
    cw = CoreWorker(
        mode=DRIVER,
        gcs_address=gcs.address,
        raylet_address=raylet.address,
        arena_name=raylet.arena_name,
        node_id=raylet.node_id,
        session_dir=session_dir,
    )
    worker_context.set_core_worker(cw)
    try:

        @ray_tpu.remote(name="ft-actor")
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        cw.gcs.call("kv_put", {"key": "ft:probe", "value": b"survives", "overwrite": True})
        # Ensure the state is in the snapshot before the "crash".
        gcs.save_snapshot()
        gcs.stop()

        # Restart the GCS on the SAME address from the snapshot.
        gcs2 = GcsServer(host=host, port=port, persist_path=persist)
        try:
            # Raylet heartbeats hit "unknown", re-register, and come back.
            deadline = time.time() + 30
            alive = False
            while time.time() < deadline:
                nodes = gcs2.nodes
                if any(n.get("state") == "ALIVE" for n in nodes.values()):
                    alive = True
                    break
                time.sleep(0.2)
            assert alive, "raylet did not re-register after GCS restart"

            # KV survived.
            resp = cw.gcs.call("kv_get", {"key": "ft:probe"})
            assert resp.get("found") and bytes(resp["value"]) == b"survives"

            # Named actor survived (table restored) and still serves calls
            # (the actor process never died; calls are direct transport).
            h = ray_tpu.get_actor("ft-actor")
            assert ray_tpu.get(h.inc.remote(), timeout=60) == 2

            # New tasks still schedule.
            @ray_tpu.remote
            def f():
                return "post-restart"

            assert ray_tpu.get(f.remote(), timeout=60) == "post-restart"
        finally:
            gcs2.stop()
    finally:
        worker_context.set_core_worker(None)
        try:
            cw.shutdown()
        except Exception:
            pass
        raylet.stop()


def _boot(tmp_path, num_cpus=2):
    init_config(None)
    persist = str(tmp_path / "gcs_snapshot.pkl")
    session_dir = str(tmp_path / "session")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    gcs = GcsServer(persist_path=persist)
    raylet = Raylet(gcs.address, session_dir, resources={"CPU": num_cpus})
    cw = CoreWorker(
        mode=DRIVER,
        gcs_address=gcs.address,
        raylet_address=raylet.address,
        arena_name=raylet.arena_name,
        node_id=raylet.node_id,
        session_dir=session_dir,
    )
    worker_context.set_core_worker(cw)
    return gcs, raylet, cw, persist


def _teardown(cw, raylet, gcs2):
    worker_context.set_core_worker(None)
    try:
        cw.shutdown()
    except Exception:
        pass
    raylet.stop()
    if gcs2 is not None:
        gcs2.stop()


def _restart_gcs(gcs, persist):
    """Kill + restart the GCS on the same address, from its snapshot."""
    host, port = gcs.address
    gcs.stop()
    return GcsServer(host=host, port=port, persist_path=persist)


def test_gcs_restart_under_running_tasks(tmp_path):
    """Tasks submitted before, DURING, and after a GCS restart all complete:
    the data plane (leases + direct transport) rides out the control-plane
    outage (reference: test_gcs_fault_tolerance.py worker-reconnect cases)."""
    gcs, raylet, cw, persist = _boot(tmp_path)
    gcs2 = None
    try:

        @ray_tpu.remote
        def work(i):
            import time as _t

            _t.sleep(0.4)
            return i * 2

        before = [work.remote(i) for i in range(8)]
        time.sleep(0.3)  # let snapshots capture the function export
        gcs2 = _restart_gcs(gcs, persist)
        during = [work.remote(i) for i in range(8, 12)]
        assert ray_tpu.get(before, timeout=120) == [i * 2 for i in range(8)]
        assert ray_tpu.get(during, timeout=120) == [i * 2 for i in range(8, 12)]
        # Post-restart submissions too.
        assert ray_tpu.get([work.remote(99)], timeout=120) == [198]
    finally:
        _teardown(cw, raylet, gcs2 if gcs2 is not None else gcs)


def test_gcs_restart_during_pg_creation(tmp_path):
    """A placement group snapshotted PENDING (infeasible at creation time)
    completes after the restart once capacity exists: restored PGs are
    re-driven (reference: gcs_placement_group_manager recovery)."""
    from ray_tpu.util.placement_group import placement_group

    gcs, raylet, cw, persist = _boot(tmp_path, num_cpus=1)
    gcs2 = None
    second = None
    try:
        # Demands 3 CPUs; the single 1-CPU node cannot host it -> PENDING.
        pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
        time.sleep(0.4)  # PENDING state reaches the snapshot
        gcs2 = _restart_gcs(gcs, persist)

        # Add capacity AFTER the restart: two more raylets.
        session_dir = str(tmp_path / "session")
        second = [
            Raylet(gcs2.address, session_dir, resources={"CPU": 1}) for _ in range(2)
        ]
        deadline = time.time() + 60
        created = False
        while time.time() < deadline:
            info = gcs2.placement_groups.get(pg.id.hex())
            if info is not None and info["state"] == "CREATED":
                created = True
                break
            time.sleep(0.2)
        assert created, "restored PENDING placement group was never created"
    finally:
        if second:
            for r in second:
                r.stop()
        _teardown(cw, raylet, gcs2 if gcs2 is not None else gcs)


def test_actor_restart_across_gcs_restart(tmp_path):
    """An actor with max_restarts dies AFTER a GCS restart; the restarted
    GCS still owns the restart machinery (reference: actor FT across GCS
    failover)."""
    gcs, raylet, cw, persist = _boot(tmp_path)
    gcs2 = None
    try:

        @ray_tpu.remote(max_restarts=2, name="phoenix")
        class Phoenix:
            def __init__(self):
                self.n = 0

            def ping(self):
                self.n += 1
                return self.n

            def die(self):
                os._exit(1)

        p = Phoenix.remote()
        assert ray_tpu.get(p.ping.remote(), timeout=60) == 1
        time.sleep(0.4)  # ALIVE state reaches the snapshot
        gcs2 = _restart_gcs(gcs, persist)

        # Wait for the raylet to re-register with the restarted GCS.
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(n.get("state") == "ALIVE" for n in gcs2.nodes.values()):
                break
            time.sleep(0.2)

        try:
            ray_tpu.get(p.die.remote(), timeout=30)
        except Exception:
            pass  # the kill call dies with the actor
        # The restarted GCS restarts the actor; state resets (fresh __init__).
        deadline = time.time() + 90
        value = None
        while time.time() < deadline:
            try:
                value = ray_tpu.get(p.ping.remote(), timeout=10)
                break
            except Exception:
                time.sleep(0.5)
        assert value == 1, f"actor did not restart after GCS failover (got {value})"
    finally:
        _teardown(cw, raylet, gcs2 if gcs2 is not None else gcs)


def _hard_kill_gcs(gcs):
    """Simulate SIGKILL: tear the server down WITHOUT writing a snapshot.
    Whatever survives must come from the write-ahead log."""
    gcs._health_task.cancel()
    if gcs._persist_task is not None:
        gcs._persist_task.cancel()
    for c in gcs._raylet_clients.values():
        c.close()
    gcs.server.stop()


def test_gcs_wal_survives_kill_after_acknowledged_mutation(tmp_path):
    """The debounced snapshot alone had a ~150ms loss window; the WAL closes
    it (reference durability bar: redis_store_client.h — every acknowledged
    mutation survives). Snapshots are disabled entirely here, so restart
    state comes purely from WAL replay."""
    gcs, raylet, cw, persist = _boot(tmp_path)
    # No snapshots ever: durability must come from the WAL alone.
    gcs._persist_task.cancel()
    gcs._persist_task = None
    host, port = gcs.address
    gcs2 = None
    try:

        @ray_tpu.remote(name="wal-actor")
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        cw.gcs.call("kv_put", {"key": "wal:probe", "value": b"durable", "overwrite": True})
        # Immediately after the acknowledged mutations: hard kill, no snapshot.
        _hard_kill_gcs(gcs)
        assert not os.path.exists(persist), "snapshot must not exist — WAL only"
        assert os.path.exists(persist + ".wal")

        gcs2 = GcsServer(host=host, port=port, persist_path=persist)
        # KV mutation survived the kill.
        resp = cw.gcs.call("kv_get", {"key": "wal:probe"})
        assert resp.get("found") and bytes(resp["value"]) == b"durable"
        # Actor registration survived: named actor resolvable and serving
        # (the actor process itself never died).
        h = ray_tpu.get_actor("wal-actor")
        assert ray_tpu.get(h.inc.remote(), timeout=60) == 2
    finally:
        _teardown(cw, raylet, gcs2)


def test_gcs_wal_fsync_knob(tmp_path, monkeypatch):
    """RAY_TPU_WAL_FSYNC policies actually reach os.fsync/os.fdatasync:
    "1" syncs inside the mutating append, "everysec" batches an fdatasync
    from the persist loop within ~1s, "0" never syncs (flush only)."""
    from ray_tpu._private import gcs as gcs_module
    from ray_tpu._private.config import Config
    from ray_tpu._private.rpc import RpcClient

    # The env knob plumbs through the config registry.
    monkeypatch.setenv("RAY_TPU_WAL_FSYNC", "1")
    cfg = Config()
    cfg.apply_overrides(None)
    assert cfg.wal_fsync == "1"
    monkeypatch.delenv("RAY_TPU_WAL_FSYNC")

    init_config(None)
    calls = {"fsync": 0, "fdatasync": 0}
    real_fsync, real_fdatasync = os.fsync, os.fdatasync

    def counting_fsync(fd):
        calls["fsync"] += 1
        return real_fsync(fd)

    def counting_fdatasync(fd):
        calls["fdatasync"] += 1
        return real_fdatasync(fd)

    monkeypatch.setattr(gcs_module.os, "fsync", counting_fsync)
    monkeypatch.setattr(gcs_module.os, "fdatasync", counting_fdatasync)

    persist = str(tmp_path / "gcs_snapshot.pkl")
    gcs = GcsServer(persist_path=persist)
    client = RpcClient(tuple(gcs.address), label="gcs")
    try:
        # Mode "1": fsync before the handler replies.
        gcs._wal_fsync = "1"
        client.call("kv_put", {"key": "k1", "value": b"v", "overwrite": True})
        assert calls["fsync"] >= 1

        # Mode "0": no syncing at all.
        gcs._wal_fsync = "0"
        before = (calls["fsync"], calls["fdatasync"])
        client.call("kv_put", {"key": "k0", "value": b"v", "overwrite": True})
        assert (calls["fsync"], calls["fdatasync"]) == before

        # Mode "everysec" (the default): the persist loop fdatasyncs the
        # dirty WAL within ~1s and clears the dirty bit.
        # Mode "everysec": disable snapshot compaction (it fsyncs the
        # snapshot and truncates the WAL, legitimately clearing the dirty
        # bit before the 1s window) so the fdatasync branch itself runs.
        gcs._wal_fsync = "everysec"
        gcs.persist_path = ""
        client.call("kv_put", {"key": "ke", "value": b"v", "overwrite": True})
        deadline = time.time() + 5
        while time.time() < deadline and calls["fdatasync"] == before[1]:
            time.sleep(0.1)
        assert calls["fdatasync"] > before[1]
    finally:
        client.close()
        gcs.stop()


def test_gcs_wal_torn_tail_is_discarded(tmp_path):
    """A crash mid-append leaves a torn trailing record; replay applies the
    complete prefix and drops the tail instead of refusing to start."""
    gcs, raylet, cw, persist = _boot(tmp_path)
    gcs._persist_task.cancel()
    gcs._persist_task = None
    host, port = gcs.address
    gcs2 = None
    try:
        cw.gcs.call("kv_put", {"key": "wal:keep", "value": b"kept", "overwrite": True})
        _hard_kill_gcs(gcs)
        # Append a torn record (length prefix promises more bytes than exist).
        with open(persist + ".wal", "ab") as f:
            f.write((1 << 20).to_bytes(4, "big") + b"\x00\x01\x02")
        gcs2 = GcsServer(host=host, port=port, persist_path=persist)
        resp = cw.gcs.call("kv_get", {"key": "wal:keep"})
        assert resp.get("found") and bytes(resp["value"]) == b"kept"
    finally:
        _teardown(cw, raylet, gcs2)
