"""Legacy Policy facade tests.

The reference's legacy policy layer (rllib/policy/policy.py:175) is the API
external-serving and offline-eval code builds against: compute_single_action /
compute_actions / compute_log_likelihoods / postprocess_trajectory /
get-set_weights / export-from_checkpoint. Here Policy is a thin view over the
new-stack RLModule pure functions — these tests pin the surface and its
consistency with the underlying module math.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.policy import Policy, SampleBatch
from ray_tpu.rllib.policy.sample_batch import (
    ADVANTAGES,
    DONES,
    REWARDS,
    VALUE_TARGETS,
    VF_PREDS,
)


@pytest.fixture(scope="module")
def spaces():
    import gymnasium as gym

    obs = gym.spaces.Box(low=-1.0, high=1.0, shape=(4,), dtype=np.float32)
    act = gym.spaces.Discrete(3)
    return obs, act


@pytest.fixture(scope="module")
def cont_spaces():
    import gymnasium as gym

    obs = gym.spaces.Box(low=-1.0, high=1.0, shape=(6,), dtype=np.float32)
    act = gym.spaces.Box(low=-2.0, high=2.0, shape=(2,), dtype=np.float32)
    return obs, act


def test_compute_actions_shapes_and_fetches(spaces):
    policy = Policy.from_spaces(*spaces)
    obs = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    actions, state, info = policy.compute_actions(obs)
    assert actions.shape == (16,)
    assert state == []
    assert info["action_logp"].shape == (16,)
    assert info["vf_preds"].shape == (16,)
    assert np.all(actions >= 0) and np.all(actions < 3)


def test_single_action_greedy_deterministic_exploring_varies(spaces):
    policy = Policy.from_spaces(*spaces)
    obs = np.ones(4, np.float32)
    greedy = {policy.compute_single_action(obs, explore=False)[0] for _ in range(5)}
    assert len(greedy) == 1  # argmax: same every call
    explored = {policy.compute_single_action(obs, explore=True)[0] for _ in range(30)}
    assert len(explored) > 1  # fresh rng fold per call


def test_log_likelihoods_match_action_fetches(spaces):
    """logp returned by compute_actions must equal compute_log_likelihoods
    re-evaluated on the same (obs, action) pairs — one set of numerics."""
    policy = Policy.from_spaces(*spaces)
    obs = np.random.default_rng(1).normal(size=(32, 4)).astype(np.float32)
    actions, _, info = policy.compute_actions(obs, explore=True)
    logp = policy.compute_log_likelihoods(actions, obs)
    np.testing.assert_allclose(logp, info["action_logp"], rtol=1e-5, atol=1e-5)


def test_continuous_actions_and_logp(cont_spaces):
    policy = Policy.from_spaces(*cont_spaces)
    obs = np.random.default_rng(2).normal(size=(8, 6)).astype(np.float32)
    actions, _, info = policy.compute_actions(obs, explore=True)
    assert actions.shape == (8, 2)
    logp = policy.compute_log_likelihoods(actions, obs)
    np.testing.assert_allclose(logp, info["action_logp"], rtol=1e-4, atol=1e-4)
    a, _, one_info = policy.compute_single_action(obs[0], explore=False)
    assert a.shape == (2,)
    assert np.isfinite(one_info["vf_preds"])


def test_postprocess_trajectory_gae(spaces):
    policy = Policy.from_spaces(*spaces)
    rng = np.random.default_rng(3)
    n = 40
    batch = SampleBatch({
        REWARDS: rng.normal(size=n).astype(np.float32),
        DONES: (rng.random(n) < 0.1).astype(np.float32),
        VF_PREDS: rng.normal(size=n).astype(np.float32),
    })
    out = policy.postprocess_trajectory(batch, last_value=0.5)
    assert np.isfinite(out[ADVANTAGES]).all()
    np.testing.assert_allclose(
        out[VALUE_TARGETS], out[ADVANTAGES] + out[VF_PREDS], rtol=1e-5
    )


def test_weights_roundtrip_and_checkpoint(tmp_path, spaces):
    import jax

    policy = Policy.from_spaces(*spaces)
    obs = np.random.default_rng(4).normal(size=(4, 4)).astype(np.float32)
    ref_actions, _, ref_info = policy.compute_actions(obs, explore=False)

    # set_weights: a perturbed copy must change outputs; restoring the
    # originals must restore them.
    orig = policy.get_weights()
    bumped = jax.tree_util.tree_map(lambda x: x + 0.5, orig)
    policy.set_weights(bumped)
    _, _, bump_info = policy.compute_actions(obs, explore=False)
    assert not np.allclose(bump_info["vf_preds"], ref_info["vf_preds"])
    policy.set_weights(orig)

    path = str(tmp_path / "ckpt")
    policy.export_checkpoint(path)
    restored = Policy.from_checkpoint(path)
    got_actions, _, got_info = restored.compute_actions(obs, explore=False)
    np.testing.assert_array_equal(got_actions, ref_actions)
    np.testing.assert_allclose(got_info["vf_preds"], ref_info["vf_preds"], rtol=1e-6)


def test_algorithm_get_policy_end_to_end():
    """algo.get_policy() must hand back a Policy whose greedy actions match
    Algorithm.compute_single_action (the serving path equals the training
    snapshot)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        cfg = (
            PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, num_envs_per_worker=2)
            .training(train_batch_size=400, num_sgd_iter=2)
            .debugging(seed=0)
        )
        algo = cfg.build()
        algo.setup(cfg.to_dict())
        try:
            algo.step()
            policy = algo.get_policy()
            for obs in (np.zeros(4, np.float32), np.ones(4, np.float32)):
                a_algo = algo.compute_single_action(obs, explore=False)
                a_pol, _, _ = policy.compute_single_action(obs, explore=False)
                assert a_algo == a_pol
            # gamma/lambda flow into postprocessing config
            assert policy.config["gamma"] == pytest.approx(cfg.gamma)
        finally:
            algo.cleanup()
    finally:
        ray_tpu.shutdown()


def test_policy_applies_observation_filter(tmp_path, spaces):
    """A policy trained behind a MeanStdFilter must apply the SAME filter at
    inference (and carry it through checkpoints) — raw observations fed to
    the network would be distribution-shifted garbage."""
    import jax

    from ray_tpu.rllib.connectors import MeanStdFilter

    f = MeanStdFilter()
    rng = np.random.default_rng(5)
    f(rng.normal(loc=100.0, scale=3.0, size=(256, 4)))  # accumulate stats

    policy = Policy.from_spaces(*spaces)
    obs = rng.normal(loc=100.0, scale=3.0, size=(8, 4)).astype(np.float32)

    _, _, raw_info = policy.compute_actions(obs, explore=False)
    policy._obs_filter_state = f.get_state()
    _, _, filt_info = policy.compute_actions(obs, explore=False)
    # filtered obs are ~N(0,1) around the running mean; values must differ
    assert not np.allclose(filt_info["vf_preds"], raw_info["vf_preds"])
    # equivalent to filtering by hand
    byhand = np.asarray(f.transform(obs), np.float32)
    _, _, ref_info = Policy(policy.spec, policy.params).compute_actions(byhand, explore=False)
    np.testing.assert_allclose(filt_info["vf_preds"], ref_info["vf_preds"], rtol=1e-5)

    path = str(tmp_path / "fckpt")
    policy.export_checkpoint(path)
    restored = Policy.from_checkpoint(path)
    _, _, rest_info = restored.compute_actions(obs, explore=False)
    np.testing.assert_allclose(rest_info["vf_preds"], filt_info["vf_preds"], rtol=1e-6)
