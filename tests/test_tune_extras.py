"""Tests for the wider Tune surface: HyperBand (sync), PB2, BayesOptSearch,
Repeater, gated external searchers.

Reference analogs: python/ray/tune/tests/test_trial_scheduler.py (HyperBand
halving), test_trial_scheduler_pbt.py (PB2), test_searchers.py.
"""

import random

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


def _report_iters(config):
    for i in range(1, config.get("iters", 30) + 1):
        tune.report({"acc": config["lr"] * i, "training_iteration": i})


def test_hyperband_halves_brackets(ray_start_regular):
    from ray_tpu.tune.schedulers import HyperBandScheduler

    scheduler = HyperBandScheduler(metric="acc", mode="max", max_t=9, reduction_factor=3)
    results = tune.Tuner(
        _report_iters,
        param_space={"lr": tune.grid_search([9.0, 3.0, 1.0, 0.3, 0.1, 0.03])},
        tune_config=tune.TuneConfig(
            scheduler=scheduler, metric="acc", mode="max", max_concurrent_trials=3
        ),
    ).fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in results)
    # Synchronous halving: some trials cut at an early rung, at least one
    # survivor runs to the bracket budget.
    assert iters[0] < 9, f"no trial was halved: {iters}"
    assert iters[-1] >= 9, f"no trial reached max_t: {iters}"
    best = max(r.metrics.get("acc", 0) for r in results)
    assert best >= 9.0 * 9  # the lr=9 trial survived to the end


class _GrowTrainable(tune.Trainable):
    def setup(self, config):
        self.score = 0.0

    def step(self):
        self.score += self.config["rate"]
        return {"score": self.score}

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({"score": self.score})

    def load_checkpoint(self, checkpoint):
        self.score = checkpoint.to_dict()["score"]


def test_pb2_exploits_with_gp(ray_start_regular):
    from ray_tpu.tune.schedulers import PB2

    pb2 = PB2(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"rate": tune.uniform(0.1, 10.0)}, seed=0,
    )
    results = tune.Tuner(
        _GrowTrainable,
        param_space={"rate": tune.grid_search([0.1, 0.1, 8.0, 8.0])},
        tune_config=tune.TuneConfig(scheduler=pb2, metric="score", mode="max",
                                    max_concurrent_trials=4),
        run_config=RunConfig(stop={"training_iteration": 12}),
    ).fit()
    best = results.get_best_result("score", "max").metrics["score"]
    assert best >= 8.0 * 10  # top performer kept running
    # GP-guided explore keeps mutated rates inside the declared box.
    for r in results:
        assert 0.05 <= r.config["rate"] <= 10.5


def _quadratic(config):
    tune.report({"score": -((config["x"] - 3.0) ** 2)})


def test_bayesopt_finds_quadratic_max(ray_start_regular):
    from ray_tpu.tune.search import BayesOptSearch

    searcher = BayesOptSearch(
        {"x": tune.uniform(0.0, 6.0)}, metric="score", mode="max",
        random_startup_trials=4, seed=0,
    )
    results = tune.Tuner(
        _quadratic,
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=15,
                                    search_alg=searcher, max_concurrent_trials=1),
    ).fit()
    best = results.get_best_result("score", "max")
    assert best.metrics["score"] > -0.5, f"BO missed the optimum: {best.metrics}"


def test_bayesopt_handles_mixed_domains():
    """Unit-cube mapping roundtrips ints / categoricals / log floats."""
    from ray_tpu.tune.search.bayesopt import _Dim

    d = _Dim("lr", tune.loguniform(1e-4, 1e-1))
    assert abs(d.to_unit(1e-4)) < 1e-9 and abs(d.to_unit(1e-1) - 1) < 1e-9
    assert 1e-4 <= d.from_unit(0.37) <= 1e-1
    c = _Dim("act", tune.choice(["relu", "tanh", "gelu"]))
    assert c.from_unit(c.to_unit("tanh")) == "tanh"
    i = _Dim("n", tune.randint(2, 10))
    assert i.from_unit(i.to_unit(7)) == 7


def test_repeater_averages_noisy_trials(ray_start_regular):
    from ray_tpu.tune.search import Repeater
    from ray_tpu.tune.search.hyperopt_like import HyperOptLikeSearch

    rng = random.Random(0)

    def noisy(config):
        tune.report({"score": -((config["x"] - 3.0) ** 2) + rng.gauss(0, 0.5)})

    inner = HyperOptLikeSearch({"x": tune.uniform(0, 6)}, metric="score", mode="max",
                               n_initial_points=2, seed=0)
    searcher = Repeater(inner, repeat=3)
    results = tune.Tuner(
        noisy,
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=12,
                                    search_alg=searcher, max_concurrent_trials=1),
    ).fit()
    assert len(results) == 12
    # Every group of 3 shares the same x (the repeated config).
    xs = [round(r.config["x"], 6) for r in results]
    assert len(set(xs)) <= 4
    # __trial_index__ marks the repeat index inside each group.
    idxs = sorted(r.config.get("__trial_index__") for r in results)
    assert idxs.count(0) == 4 and idxs.count(2) == 4


def test_gated_searchers_raise_with_guidance():
    # TuneBOHB is no longer gated — it has a self-contained KDE
    # implementation (see test_tune_bohb_rcs.py).
    from ray_tpu.tune.search import AxSearch, OptunaSearch

    for cls, pkg in ((OptunaSearch, "optuna"), (AxSearch, "ax-platform")):
        with pytest.raises(ImportError, match=pkg):
            cls()
