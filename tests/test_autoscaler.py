"""Autoscaler tests.

Modeled on the reference's test_resource_demand_scheduler.py and
test_autoscaler_fake_multinode.py: pure planning-logic units plus an
end-to-end scale-up/scale-down flow against a real head node with the fake
multi-node provider launching real worker processes.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.resource_demand_scheduler import ResourceDemandScheduler


class TestResourceDemandScheduler:
    def test_no_launch_when_demand_fits(self):
        s = ResourceDemandScheduler({"cpu": {"resources": {"CPU": 4}, "max_workers": 4}}, 8)
        plan = s.get_nodes_to_launch(
            existing_avail=[{"CPU": 4}],
            demands=[{"CPU": 1}, {"CPU": 2}],
            counts_by_type={},
            total_existing=0,
        )
        assert plan == {}

    def test_launch_for_unmet_demand(self):
        s = ResourceDemandScheduler({"cpu": {"resources": {"CPU": 2}, "max_workers": 4}}, 8)
        plan = s.get_nodes_to_launch(
            existing_avail=[{"CPU": 0}],
            demands=[{"CPU": 1}] * 5,
            counts_by_type={},
            total_existing=0,
        )
        assert plan == {"cpu": 3}  # 5 x CPU:1 onto CPU:2 nodes

    def test_picks_cheapest_feasible_type(self):
        s = ResourceDemandScheduler(
            {
                "cpu": {"resources": {"CPU": 2}, "max_workers": 4},
                "tpu": {"resources": {"CPU": 8, "TPU": 4}, "max_workers": 2},
            },
            8,
        )
        plan = s.get_nodes_to_launch([], [{"CPU": 1}], {}, 0)
        assert plan == {"cpu": 1}
        plan = s.get_nodes_to_launch([], [{"TPU": 4}], {}, 0)
        assert plan == {"tpu": 1}

    def test_respects_max_workers(self):
        s = ResourceDemandScheduler({"cpu": {"resources": {"CPU": 1}, "max_workers": 2}}, 8)
        plan = s.get_nodes_to_launch([], [{"CPU": 1}] * 5, {"cpu": 1}, 1)
        assert plan == {"cpu": 1}  # type cap 2, one already exists

    def test_infeasible_demand_ignored(self):
        s = ResourceDemandScheduler({"cpu": {"resources": {"CPU": 2}, "max_workers": 4}}, 8)
        plan = s.get_nodes_to_launch([], [{"GPU": 1}], {}, 0)
        assert plan == {}


class _RecordingProvider:
    """Provider stub recording create/terminate calls."""

    def __init__(self):
        self.created = []
        self.terminated = []
        self._alive = []

    def non_terminated_nodes(self):
        return list(self._alive)

    def node_tags(self, nid):
        return {}

    def create_node(self, node_config, tags, count):
        out = []
        for i in range(count):
            nid = f"stub-{len(self.created)}"
            self.created.append((nid, node_config))
            self._alive.append(nid)
            out.append(nid)
        return out

    def terminate_node(self, nid):
        self.terminated.append(nid)
        self._alive.remove(nid)

    def shutdown(self):
        pass


def test_autoscaler_launches_for_pending_pg(ray_start_regular):
    """A PENDING STRICT_PACK placement group produces a merged gang demand."""
    provider = _RecordingProvider()
    node = ray_tpu._global_node
    config = {
        "cluster_name": "t",
        "max_workers": 4,
        "idle_timeout_s": 9999,
        "provider": {"type": "fake", "gcs_address": "%s:%d" % tuple(node.gcs_address)},
        "node_types": {"big": {"resources": {"CPU": 16}, "max_workers": 2}},
    }
    scaler = StandardAutoscaler(config, provider=provider)
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 8}, {"CPU": 8}], strategy="STRICT_PACK")
    # Head has only 4 CPUs -> PG stays PENDING -> autoscaler wants one `big`.
    scaler.update()
    assert len(provider.created) == 1
    assert provider.created[0][1]["resources"] == {"CPU": 16}
    # Second tick: demand still pending but a node of that type is already
    # launching (counted), so no duplicate launch beyond the cap logic.
    scaler.update()
    assert len(provider.created) <= 2


def test_no_relaunch_while_node_boots(ray_start_regular):
    """A launched-but-unregistered node's capacity covers the demand, so the
    same pending PG must not launch a second node on the next tick."""
    provider = _RecordingProvider()
    node = ray_tpu._global_node
    config = {
        "cluster_name": "t",
        "max_workers": 8,
        "idle_timeout_s": 9999,
        "provider": {"type": "fake", "gcs_address": "%s:%d" % tuple(node.gcs_address)},
        "node_types": {"big": {"resources": {"CPU": 16}, "max_workers": 8}},
    }
    scaler = StandardAutoscaler(config, provider=provider)
    from ray_tpu.util.placement_group import placement_group

    placement_group([{"CPU": 16}], strategy="STRICT_PACK")
    for _ in range(3):
        scaler.update()
    # Stub nodes never register with the GCS, so they stay "booting";
    # their capacity must still absorb the demand after the first launch.
    assert len(provider.created) == 1


def test_infeasible_demand_does_not_pin_idle_nodes(ray_start_regular):
    """Demand no node type can satisfy must not block idle termination."""
    provider = _RecordingProvider()
    node = ray_tpu._global_node
    config = {
        "cluster_name": "t",
        "max_workers": 4,
        "idle_timeout_s": 9999,
        "provider": {"type": "fake", "gcs_address": "%s:%d" % tuple(node.gcs_address)},
        "node_types": {"cpu": {"resources": {"CPU": 2}, "max_workers": 4}},
    }
    scaler = StandardAutoscaler(config, provider=provider)
    from ray_tpu.util.placement_group import placement_group

    placement_group([{"GPU": 1}], strategy="PACK")  # never satisfiable
    scaler.update()
    assert provider.created == []
    # Feasibility classifier: GPU demand matches no node type and no node;
    # CPU demand matches the cpu node type. The idle-termination path only
    # yields to feasible demand.
    assert scaler._shape_feasible({"GPU": 1}, []) is False
    assert scaler._shape_feasible({"CPU": 1}, []) is True
    # update() must reach the idle-termination block (no early busy-return):
    # with an infeasible pending PG the idle clock for a fake worker entry
    # still advances.
    scaler._idle_since["sentinel"] = 1.0
    scaler.update()
    assert "sentinel" in scaler._idle_since  # not cleared by infeasible demand


def test_autoscaler_end_to_end_scale_up_down():
    """Real flow: queued tasks -> fake provider launches a real worker node ->
    tasks run -> node terminated after idling."""
    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    node = ray_tpu._global_node
    config = {
        "cluster_name": "e2e",
        "max_workers": 1,
        "idle_timeout_s": 3,
        "provider": {"type": "fake", "gcs_address": "%s:%d" % tuple(node.gcs_address)},
        "node_types": {"cpu_worker": {"resources": {"CPU": 2}, "max_workers": 1}},
    }
    scaler = StandardAutoscaler(config)
    try:

        @ray_tpu.remote(num_cpus=2)
        def two_cpu_task():
            return os.getpid()

        ref = two_cpu_task.remote()  # needs 2 CPUs; head has 1 -> queued
        deadline = time.time() + 90
        launched = False
        while time.time() < deadline:
            scaler.update()
            if scaler.provider.non_terminated_nodes():
                launched = True
                break
            time.sleep(1)
        assert launched, "autoscaler never launched a worker node"
        # The task must complete on the new node.
        assert isinstance(ray_tpu.get(ref, timeout=90), int)
        # After going idle, the node is terminated.
        deadline = time.time() + 60
        while time.time() < deadline:
            scaler.update()
            if not scaler.provider.non_terminated_nodes():
                break
            time.sleep(1)
        assert not scaler.provider.non_terminated_nodes(), "idle node was not terminated"
    finally:
        scaler.shutdown()
        ray_tpu.shutdown()


def test_request_resources_standing_floor(ray_start_regular):
    """sdk.request_resources is a standing demand floor: it launches to
    cover the request, booting capacity satisfies it across ticks, and
    clearing it stops influencing the plan (reference: autoscaler/sdk)."""
    provider = _RecordingProvider()
    node = ray_tpu._global_node
    config = {
        "cluster_name": "t",
        "max_workers": 4,
        "idle_timeout_s": 9999,
        "provider": {"type": "fake", "gcs_address": "%s:%d" % tuple(node.gcs_address)},
        "node_types": {"big": {"resources": {"CPU": 16}, "max_workers": 2}},
    }
    scaler = StandardAutoscaler(config, provider=provider)
    from ray_tpu.autoscaler import request_resources

    request_resources(bundles=[{"CPU": 16}])
    scaler.update()
    assert len(provider.created) == 1  # head's CPUs can't hold CPU:16
    assert provider.created[0][1]["resources"] == {"CPU": 16}
    # Standing request + booting node capacity: no duplicate launch.
    scaler.update()
    assert len(provider.created) == 1
    # num_cpus that already fits on the head adds nothing.
    request_resources(num_cpus=1)
    scaler.update()
    assert len(provider.created) == 1
    # Clearing the request leaves the plan untouched.
    request_resources()
    scaler.update()
    assert len(provider.created) == 1


def test_cover_request_first_fit():
    """The standing request protects only the nodes needed to COVER it
    (fit against TOTALS — a busy covering node still counts, no churn) and
    returns the uncovered remainder as launch demand."""
    scaler = StandardAutoscaler.__new__(StandardAutoscaler)
    nodes = [
        {"node_id": "a", "resources_total": {"CPU": 4}},
        {"node_id": "b", "resources_total": {"CPU": 16}},
        {"node_id": "c", "resources_total": {"CPU": 16}},
    ]
    protected, uncovered = scaler._cover_request([{"CPU": 16}], nodes)
    assert protected == {"b"} and uncovered == []
    protected, uncovered = scaler._cover_request(
        [{"CPU": 2}, {"CPU": 2}, {"CPU": 16}], nodes
    )
    assert protected == {"a", "b"} and uncovered == []  # small shapes share "a"
    assert scaler._cover_request([], nodes) == (set(), [])
    # Infeasible-for-the-fleet shapes come back as launch demand.
    protected, uncovered = scaler._cover_request([{"GPU": 1}], nodes)
    assert protected == set() and uncovered == [{"GPU": 1}]
    # Three big shapes onto two big nodes: one uncovered.
    protected, uncovered = scaler._cover_request([{"CPU": 16}] * 3, nodes)
    assert protected == {"b", "c"} and uncovered == [{"CPU": 16}]
