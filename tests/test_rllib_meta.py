"""Tests for the meta/model-based RL genre: MAML and MBMPO.

Mirrors the reference's rllib/algorithms/{maml,mbmpo}/tests: the
learning-shaped assertion is the ADAPTATION DELTA — a meta-trained policy
must gain more from one inner step on a fresh task than an untrained one —
plus supervised sanity on the learned dynamics ensemble for MBMPO.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.env.meta_env import PointGoalEnv


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_point_goal_env_task_api():
    env = PointGoalEnv({"seed": 3})
    tasks = env.sample_tasks(4)
    assert len(tasks) == 4
    env.set_task(tasks[0])
    assert np.allclose(env.get_task(), tasks[0])
    obs, _ = env.reset()
    assert obs.shape == (2,)
    total = 0
    for _ in range(env.horizon):
        obs, r, term, trunc, _ = env.step(np.array([1.0, 0.0], np.float32))
        assert not term
        total += 1
        if trunc:
            break
    assert total == env.horizon


def test_maml_learns_to_adapt(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import MAMLConfig

    cfg = (
        MAMLConfig()
        .environment(PointGoalEnv, env_config={"seed": 0})
        .rollouts(num_rollout_workers=2)
        .training(
            lr=5e-3, inner_lr=0.3, meta_batch_size=8, episodes_per_task=8,
            maml_optimizer_steps=5, model_hiddens=(32, 32),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        deltas, posts = [], []
        for _ in range(15):
            r = algo.step()
            deltas.append(r["adaptation_delta"])
            posts.append(r["post_adaptation_reward_mean"])
        # Meta-training must produce positive adaptation gain on held-out
        # tasks (goals are freshly sampled every iteration) and the
        # post-adaptation return must improve over training.
        assert np.mean(deltas[-5:]) > 0.5, f"no adaptation gain: {deltas}"
        assert np.mean(posts[-4:]) > np.mean(posts[:4]) + 1.0, (
            f"post-adaptation return did not improve: {posts}"
        )
        # Public deploy-time adaptation API.
        task = algo._task_env.sample_tasks(1)[0]
        adapted = algo.adapt_to_task(task)
        assert set(adapted.keys()) == set(algo.get_policy_weights().keys())
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_mbmpo_model_based_progress(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import MBMPOConfig

    cfg = (
        MBMPOConfig()
        .environment(PointGoalEnv, env_config={"seed": 0})
        .training(
            lr=1e-3, inner_lr=0.2, maml_optimizer_steps=3,
            ensemble_size=3, dynamics_train_epochs=60,
            real_episodes_per_iter=15, imagined_episodes_per_task=16,
            model_hiddens=(32, 32),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        results = [algo.step() for _ in range(8)]
        dyn_losses = [r["dynamics_loss"] for r in results]
        rewards = [r["real_episode_reward_mean"] for r in results]
        # The ensemble must actually fit the (linear) point dynamics...
        assert dyn_losses[-1] < dyn_losses[0] * 0.5, f"model not learning: {dyn_losses}"
        assert dyn_losses[-1] < 1e-2
        # ...and policy updates computed ONLY on imagined data must move
        # the REAL-env return up.
        assert np.mean(rewards[-3:]) > np.mean(rewards[:3]) + 0.5, (
            f"no real-env progress from imagined training: {rewards}"
        )
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_mbmpo_learned_dynamics_match_truth(ray_cluster):
    """The ensemble's mean prediction should approximate the true
    transition function on in-distribution states."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.rllib import MBMPOConfig
    from ray_tpu.rllib.algorithms.mbmpo.mbmpo import _dyn_apply

    cfg = (
        MBMPOConfig()
        .environment(PointGoalEnv, env_config={"seed": 1})
        .training(
            ensemble_size=3, dynamics_train_epochs=80,
            real_episodes_per_iter=25, imagined_episodes_per_task=8,
            maml_optimizer_steps=1, model_hiddens=(32, 32),
        )
        .debugging(seed=1)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        algo.step()
        algo.step()  # two rounds of real data + ensemble fitting
        obs = jnp.asarray(algo._replay_obs[:64])
        act = jnp.asarray(algo._replay_act[:64])
        true_next = PointGoalEnv.transition_fn(obs, act, step_size=0.15)
        preds = []
        for k in range(cfg.ensemble_size):
            model = algo._model_slice(k)
            preds.append(obs + _dyn_apply(model, jnp.concatenate([obs, act], -1)))
        mean_pred = jnp.mean(jnp.stack(preds), axis=0)
        max_err = float(jnp.abs(mean_pred - true_next).max())
        mean_err = float(jnp.abs(mean_pred - true_next).mean())
        assert max_err < 0.15, f"learned dynamics off by {max_err} (max)"
        assert mean_err < 0.05, f"learned dynamics off by {mean_err} (mean)"
    finally:
        algo.cleanup()
