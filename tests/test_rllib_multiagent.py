"""ApexDQN (distributed prioritized replay) and QMIX (monotonic value
factorization) learning tests (reference: rllib/algorithms/{apex_dqn,qmix};
VERDICT r1 #9)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_apex_dqn_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import ApexDQNConfig

    cfg = (
        ApexDQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4)
        .training(
            lr=1e-3,
            train_batch_size=64,
            learning_starts=500,
            target_network_update_freq=50,
            num_replay_shards=2,
            rollout_fragment_length=25,
            train_rounds_per_iter=10,
            updates_per_round=8,
            weight_sync_period_updates=16,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(30):
            r = algo.step()
            best = max(best, r.get("episode_reward_mean") or 0.0)
            if best >= 100:
                break
        assert best >= 100, f"ApexDQN failed to improve on CartPole (best={best})"
        assert r["replay_size"] > 0
    finally:
        algo.cleanup()


class TwoStepGame:
    """Cooperative matrix game from the QMIX paper: agent 0's first action
    selects which payoff matrix the pair plays next step; the global optimum
    (8) needs coordinated (1, 1) in state 2, which VDN-style additive mixing
    cannot represent but monotonic mixing can."""

    possible_agents = ["a0", "a1"]

    def __init__(self, config=None):
        import gymnasium as gym

        self._obs_space = gym.spaces.Box(0.0, 1.0, (3,), np.float32)
        self._act_space = gym.spaces.Discrete(2)
        self._state = 0

    @property
    def observation_space(self):
        return self._obs_space

    @property
    def action_space(self):
        return self._act_space

    def _obs(self):
        o = np.zeros(3, np.float32)
        o[self._state] = 1.0
        return {a: o.copy() for a in self.possible_agents}

    def reset(self, *, seed=None):
        self._state = 0
        return self._obs(), {}

    def step(self, action_dict):
        if self._state == 0:
            self._state = 1 if action_dict["a0"] == 0 else 2
            return self._obs(), {a: 0.0 for a in self.possible_agents}, {"__all__": False}, {"__all__": False}, {}
        if self._state == 1:
            r = 7.0
        else:
            matrix = np.array([[0.0, 1.0], [1.0, 8.0]])
            r = float(matrix[action_dict["a0"], action_dict["a1"]])
        rewards = {a: r / 2 for a in self.possible_agents}
        return self._obs(), rewards, {"__all__": True}, {"__all__": False}, {}

    def close(self):
        pass


def _make_two_step(config):
    from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

    class _Env(TwoStepGame, MultiAgentEnv):
        pass

    return _Env(config)


def test_qmix_learns_two_step_game():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import QMIXConfig

    cfg = (
        QMIXConfig()
        .environment(_make_two_step)
        .training(
            lr=3e-3,
            train_batch_size=64,
            learning_starts=128,
            target_network_update_freq=40,
            rollout_steps_per_iter=400,
            epsilon_timesteps=3000,
            final_epsilon=0.05,
            gamma=0.99,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = -1e9
    try:
        for _ in range(15):
            r = algo.step()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            if best >= 7.5:
                break
        # Optimal coordinated play earns 8; the uncoordinated trap pays 7.
        assert best >= 7.5, f"QMIX failed to coordinate (best={best})"
        # Greedy joint policy picks the (1,*) branch then (1,1).
        obs, _ = _make_two_step({}).reset()
        acts = algo.compute_actions(obs)
        assert acts["a0"] == 1
    finally:
        algo.cleanup()
