"""Elastic collective groups: epochal membership (ISSUE 17).

- Roster unit cells: join/leave/re-register bump the roster epoch and the
  member set converges (verify-and-retry join over the CAS-less KV).
- Broadcast snapshots the roster at send time: a dead member is EVICTED
  into the next epoch (one batch), later broadcasts address survivors
  only, and a respawned member that re-registers at its old rank is back
  on the fast path at its NEW address — the roster-epoch-keyed address
  cache drops on the bump (the stale-cache satellite).
- Destroy-vs-concurrent-verb race: a rank parked in bcast_recv_payload
  while the group is destroyed surfaces a typed CollectiveError well
  before its timeout (never hangs); verbs after destroy fail typed at
  entry.
- GCS hygiene: every collective KV row of a group (roster-epoch counter,
  roster back-window, member address rows) is back to baseline after
  teardown — the leak test satellite.
- Chaos: membership-churn cell — seeded SIGKILL of a sampler
  mid-broadcast, respawn, re-register, and the NEXT device-object
  broadcast rides the group plane (bcast_recvs up, host_sync_fallbacks
  flat on the replacement).

Quick cells share one module-scoped cluster; the churn chaos cell builds
its own 2-node Cluster because it pushes a seeded kill plan into a
specific worker process.
"""

import gc
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import (
    CollectiveBroadcastError,
    CollectiveError,
    RayTpuError,
)


@pytest.fixture(scope="module")
def elastic_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _gcs():
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker().gcs


@ray_tpu.remote
class Member:
    def pid(self):
        return os.getpid()

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def bcast_recv(self, group_name, src_rank, tag, timeout=30.0):
        from ray_tpu.util import collective as col

        out = col.get_group(group_name).bcast_recv_payload(src_rank, tag, timeout=timeout)
        return np.asarray(out).sum().item()

    def consume(self, w):
        return float(np.asarray(w).reshape(-1)[0]), int(np.asarray(w).size)

    def coll_stats(self):
        from ray_tpu.util.collective.p2p import COLL

        return {k: getattr(COLL, k) for k in COLL.__slots__}

    def destroy_group(self, group_name):
        from ray_tpu.util import collective as col

        col.destroy_collective_group(group_name)
        return True

    def destroy_race(self, group_name):
        """Park in bcast_recv_payload on a tag nobody sends, destroy the
        group from the actor main flow 0.5s later, and report how the wait
        ended. The recv must abort TYPED well before its 60s window."""
        import threading

        from ray_tpu.util import collective as col

        g = col.get_group(group_name)
        out = {}

        def _recv():
            t0 = time.monotonic()
            try:
                g.bcast_recv_payload(0, "never-sent", timeout=60.0)
                out["recv"] = "no-error"
            except CollectiveError as e:
                out["recv"] = f"typed:{type(e).__name__}:{e}"
            except Exception as e:  # raw timeout/hang = the bug
                out["recv"] = f"raw:{type(e).__name__}"
            out["elapsed"] = time.monotonic() - t0

        th = threading.Thread(target=_recv, daemon=True)
        th.start()
        time.sleep(0.5)
        col.destroy_collective_group(group_name)
        th.join(timeout=30)
        out["joined"] = not th.is_alive()
        try:
            g.bcast_send_payload(np.zeros((4,), np.float32), "after-destroy")
            out["send"] = "no-error"
        except CollectiveError as e:
            out["send"] = f"typed:{type(e).__name__}"
        return out


# ---------------------------------------------------------------------------
# roster unit cells
# ---------------------------------------------------------------------------


def test_roster_join_leave_rejoin_epochs(elastic_cluster):
    """join/leave/re-register each bump the roster epoch; the member set
    converges; teardown sweeps every row."""
    from ray_tpu.util.collective import p2p

    gcs, group = _gcs(), "rg-unit"
    try:
        e1 = p2p.roster_join(gcs, group, 0, world_size=2)
        assert e1 == 1
        e2 = p2p.roster_join(gcs, group, 1, world_size=2)
        assert e2 == 2
        snap = p2p.fetch_roster(gcs, group)
        assert snap == {"epoch": 2, "ranks": [0, 1], "world_size": 2}
        e3 = p2p.roster_leave(gcs, group, 1)
        assert e3 == 3
        assert p2p.fetch_roster(gcs, group)["ranks"] == [0]
        # Re-register at an already-listed rank still bumps the epoch:
        # that bump is what drops every peer's address cache.
        e4 = p2p.roster_join(gcs, group, 0, world_size=2)
        assert e4 == 4
        assert p2p.fetch_roster(gcs, group)["ranks"] == [0]
        # Leaving a rank that is not listed is a no-op, not a bump.
        assert p2p.roster_leave(gcs, group, 7) is None
        assert p2p.fetch_roster_epoch(gcs, group) == 4
    finally:
        p2p.sweep_group_kv(gcs, group, world_size=2)
    assert p2p.fetch_roster(gcs, group) is None
    assert p2p.fetch_roster_epoch(gcs, group) == 0


def test_group_kv_rows_return_to_baseline_after_destroy(elastic_cluster):
    """The leak test: count the group's KV rows before, during, and after
    a full create → broadcast → destroy cycle. After teardown the count is
    back to the before-count (zero)."""
    import jax.numpy as jnp

    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import p2p

    gcs, group = _gcs(), "kvbase2"
    keys = (
        [p2p.roster_epoch_key(group)]
        + [p2p.roster_key(group, e) for e in range(1, 9)]
        + [p2p.member_addr_key(group, r) for r in range(2)]
    )

    def count():
        return sum(1 for k in keys if gcs.call("kv_get", {"key": k}).get("found"))

    assert count() == 0
    m = Member.remote()
    col.init_collective_group(2, 0, backend="cpu", group_name=group)
    ray_tpu.get(m.init_collective.remote(2, 1, "cpu", group), timeout=60)
    pending = m.bcast_recv.remote(group, 0, "t1", 30.0)
    info = col.get_group(group).bcast_send_payload(
        jnp.ones((512,), jnp.float32), "t1", timeout=30
    )
    assert info["ok_ranks"] == [1], info
    assert ray_tpu.get(pending, timeout=60) == 512.0
    assert count() >= 3  # repoch + live roster row + addr rows
    ray_tpu.get(m.destroy_group.remote(group), timeout=60)
    col.destroy_collective_group(group)  # rank 0 last: sweeps to baseline
    assert count() == 0


# ---------------------------------------------------------------------------
# elastic broadcast: eviction + re-register back onto the fast path
# ---------------------------------------------------------------------------


def test_broadcast_evicts_dead_rank_and_rejoiner_rides_fast_path(elastic_cluster):
    """Kill rank 2 → the next broadcast evicts it into a new roster epoch
    (one batch) and delivers to survivors; a fresh actor re-registering at
    rank 2 lands at a NEW address under the same rank row, and the next
    broadcast reaches it over the group plane — the roster-epoch-keyed
    address cache dropped on the bump (stale-cache satellite)."""
    import jax.numpy as jnp

    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import p2p

    gcs, group = _gcs(), "elastic3"
    a, b = Member.remote(), Member.remote()
    col.init_collective_group(3, 0, backend="cpu", group_name=group)
    try:
        ray_tpu.get([a.init_collective.remote(3, 1, "cpu", group),
                     b.init_collective.remote(3, 2, "cpu", group)], timeout=60)
        pid_b = ray_tpu.get(b.pid.remote(), timeout=60)
        g = col.get_group(group)
        payload = jnp.ones((256,), jnp.float32)
        pend = [a.bcast_recv.remote(group, 0, "t1"), b.bcast_recv.remote(group, 0, "t1")]
        info = g.bcast_send_payload(payload, "t1", timeout=30)
        assert sorted(info["ok_ranks"]) == [1, 2], info
        assert ray_tpu.get(pend, timeout=60) == [256.0, 256.0]
        epoch_before = p2p.fetch_roster_epoch(gcs, group)

        # kill() relays through the GCS — wait until the hosting process is
        # actually GONE, or the broadcast below races a live inbox.
        ray_tpu.kill(b)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                os.kill(pid_b, 0)
                time.sleep(0.1)
            except ProcessLookupError:
                break
        else:
            pytest.fail("victim worker process survived kill()")
        pend = a.bcast_recv.remote(group, 0, "t2")
        info = g.bcast_send_payload(payload, "t2", timeout=10)
        assert info["ok_ranks"] == [1], info
        assert 2 in info["failed"], info
        assert info["evicted_ranks"] == [2], info
        assert ray_tpu.get(pend, timeout=60) == 256.0
        snap = p2p.fetch_roster(gcs, group)
        assert snap["ranks"] == [0, 1], snap  # dead rank out, epoch advanced
        assert snap["epoch"] > epoch_before

        # Survivor-only broadcast: the dead rank is not even addressed.
        pend = a.bcast_recv.remote(group, 0, "t3")
        info = g.bcast_send_payload(payload, "t3", timeout=10)
        assert info["ok_ranks"] == [1] and info["failed"] == {}, info
        assert info["roster_epoch"] == snap["epoch"], info
        assert ray_tpu.get(pend, timeout=60) == 256.0

        # Respawn + re-register at the old rank: NEW address, same rank
        # row — only the roster-epoch bump tells the sender to refetch.
        c = Member.remote()
        ray_tpu.get(c.init_collective.remote(3, 2, "cpu", group), timeout=60)
        assert p2p.fetch_roster(gcs, group)["ranks"] == [0, 1, 2]
        pend = [a.bcast_recv.remote(group, 0, "t4"), c.bcast_recv.remote(group, 0, "t4")]
        info = g.bcast_send_payload(payload, "t4", timeout=30)
        assert sorted(info["ok_ranks"]) == [1, 2], info  # rejoiner on fast path
        assert info["failed"] == {}, info
        assert ray_tpu.get(pend, timeout=60) == [256.0, 256.0]
    finally:
        col.destroy_collective_group(group)


def test_destroy_racing_bcast_recv_raises_typed_never_hangs(elastic_cluster):
    m = Member.remote()
    ray_tpu.get(m.init_collective.remote(2, 1, "cpu", "race2"), timeout=60)
    out = ray_tpu.get(m.destroy_race.remote("race2"), timeout=90)
    assert out["joined"], out
    assert out["recv"].startswith("typed:CollectiveError"), out
    assert "destroyed" in out["recv"], out
    assert out["elapsed"] < 30, out  # aborted, not timed out at 60s
    assert out["send"] == "typed:CollectiveError", out


# ---------------------------------------------------------------------------
# chaos: membership churn — SIGKILL mid-broadcast, respawn, re-register
# ---------------------------------------------------------------------------


def test_membership_churn_sigkill_respawn_next_broadcast_fast_path():
    """The churn cell: a seeded kill plan SIGKILLs the rank-2 sampler while
    it answers the fan-out's p2p_ack (mid-broadcast). The broadcast names
    the dead rank AND evicts it from the roster; a respawned sampler
    re-registers at rank 2; the NEXT device-object broadcast covers the
    whole fleet over the group plane — the replacement resolves from its
    inbox (bcast_recvs up) with the host-sync fallback counter flat."""
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental import device_object
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import p2p

    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=2, object_store_memory=96 * 1024 * 1024)
            for _ in range(2)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        samplers = [Member.remote() for _ in range(3)]
        group = "churn4"
        col.init_collective_group(4, 0, backend="cpu", group_name=group)
        ray_tpu.get(
            [s.init_collective.remote(4, i + 1, "cpu", group) for i, s in enumerate(samplers)],
            timeout=60,
        )
        pids = ray_tpu.get([s.pid.remote() for s in samplers], timeout=60)
        victim_pid = pids[1]  # rank 2 dies mid-broadcast
        plan = {
            "rules": [
                {"kind": "kill", "method": ["p2p_ack"], "side": "resp",
                 "after": 0, "times": 1}
            ]
        }
        io = EventLoopThread.get()
        pushed = False
        for n in nodes:
            for w in n.workers.values():
                if w.pid == victim_pid and w.client is not None:
                    io.run(
                        w.client.acall(
                            "chaos_set_plan", {"plan": plan, "seed": 17},
                            timeout=5, retries=0,
                        ),
                        timeout=6,
                    )
                    pushed = True
        assert pushed, "victim worker not found for plan push"

        import jax.numpy as jnp

        ref = ray_tpu.put(
            jnp.arange(65536.0, dtype=jnp.float32), tensor_transport="collective"
        )
        with pytest.raises(CollectiveBroadcastError) as ei:
            device_object.broadcast(ref, group, timeout=30)
        err = ei.value
        assert list(err.failed) == [2], err.failed
        assert isinstance(err, RayTpuError)
        from ray_tpu._private import worker_context

        gcs = worker_context.get_core_worker().gcs
        snap = p2p.fetch_roster(gcs, group)
        assert 2 not in snap["ranks"], snap  # evicted in one batch

        # Respawn + re-register the dead rank, then broadcast AGAIN: the
        # whole fleet — replacement included — is on the group plane.
        replacement = Member.remote()
        ray_tpu.get(replacement.init_collective.remote(4, 2, "cpu", group), timeout=60)
        assert p2p.fetch_roster(gcs, group)["ranks"] == [0, 1, 2, 3]
        ref2 = ray_tpu.put(
            jnp.arange(32768.0, dtype=jnp.float32), tensor_transport="collective"
        )
        info = device_object.broadcast(ref2, group, timeout=30)
        assert sorted(info["ok_ranks"]) == [1, 2, 3], info
        assert info["failed"] == {}, info
        fleet = [samplers[0], replacement, samplers[2]]
        vals = ray_tpu.get([s.consume.remote(ref2) for s in fleet], timeout=60)
        assert vals == [(0.0, 32768)] * 3
        stats = ray_tpu.get(replacement.coll_stats.remote(), timeout=30)
        assert stats["bcast_recvs"] >= 1, stats  # inbox, not pull
        assert stats["host_sync_fallbacks"] == 0, stats  # fallback counter FLAT
        del ref, ref2, err, ei
        gc.collect()
        from ray_tpu.experimental.device_object.manager import active_manager

        deadline = time.monotonic() + 30
        mgr = active_manager()
        while mgr.usage()["resident_count"] > 0 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert mgr.usage()["resident_count"] == 0
    finally:
        cluster.shutdown()
