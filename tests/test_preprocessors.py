"""Preprocessor + predictor tests.

Modeled on the reference's python/ray/data/tests/test_preprocessors.py and
python/ray/train/tests/test_batch_predictor.py.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.data.preprocessor import PreprocessorNotFittedError
from ray_tpu.data.preprocessors import (
    BatchMapper,
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    SimpleImputer,
    StandardScaler,
)
from ray_tpu.train import BatchPredictor, JaxPredictor


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _ds(rows):
    return rdata.from_items(rows)


def test_standard_scaler(ray_cluster):
    ds = _ds([{"a": 1.0}, {"a": 2.0}, {"a": 3.0}])
    s = StandardScaler(["a"])
    out = s.fit_transform(ds).take_all()
    vals = [r["a"] for r in out]
    assert abs(np.mean(vals)) < 1e-9
    # transform_batch matches dataset transform
    b = s.transform_batch({"a": np.array([2.0])})
    assert abs(b["a"][0]) < 1e-9


def test_min_max_scaler(ray_cluster):
    ds = _ds([{"x": 0.0}, {"x": 5.0}, {"x": 10.0}])
    out = MinMaxScaler(["x"]).fit_transform(ds).take_all()
    assert [r["x"] for r in out] == [0.0, 0.5, 1.0]


def test_label_and_onehot_encoders(ray_cluster):
    ds = _ds([{"c": "red", "y": "no"}, {"c": "blue", "y": "yes"}, {"c": "red", "y": "yes"}])
    le = LabelEncoder("y").fit(ds)
    assert le.classes_ == ["no", "yes"]
    assert [r["y"] for r in le.transform(ds).take_all()] == [0, 1, 1]
    oh = OneHotEncoder(["c"]).fit(ds)
    rows = oh.transform(ds).take_all()
    assert rows[0]["c_red"] == 1 and rows[0]["c_blue"] == 0
    assert "c" not in rows[0]


def test_imputer(ray_cluster):
    ds = _ds([{"v": 1.0}, {"v": float("nan")}, {"v": 3.0}])
    rows = SimpleImputer(["v"]).fit_transform(ds).take_all()
    assert [r["v"] for r in rows] == [1.0, 2.0, 3.0]


def test_concatenator_and_batch_mapper(ray_cluster):
    ds = _ds([{"a": 1.0, "b": 2.0}, {"a": 3.0, "b": 4.0}])
    rows = Concatenator(columns=["a", "b"], output_column_name="feat").transform(ds).take_all()
    assert np.allclose(rows[0]["feat"], [1.0, 2.0])
    doubled = BatchMapper(lambda b: {"a": np.asarray(b["a"]) * 2, "b": b["b"]}).transform(ds)
    assert [r["a"] for r in doubled.take_all()] == [2.0, 6.0]


def test_chain_and_not_fitted(ray_cluster):
    ds = _ds([{"a": 1.0, "b": 10.0}, {"a": 3.0, "b": 30.0}])
    chain = Chain(StandardScaler(["a"]), MinMaxScaler(["b"]))
    with pytest.raises(PreprocessorNotFittedError):
        chain.transform(ds)
    rows = chain.fit_transform(ds).take_all()
    assert rows[0]["b"] == 0.0 and rows[1]["b"] == 1.0


def test_jax_predictor_and_batch_predictor(ray_cluster):
    import jax.numpy as jnp

    # "model": y = x @ w with w = [[2.],[3.]]
    params = {"w": np.array([[2.0], [3.0]], dtype=np.float32)}

    def apply_fn(params, x):
        return jnp.asarray(x) @ jnp.asarray(params["w"])

    ckpt = Checkpoint.from_dict({"params": params, "apply_fn": apply_fn})
    pred = JaxPredictor.from_checkpoint(ckpt, input_column="feat")
    out = pred.predict({"feat": np.array([[1.0, 1.0]], dtype=np.float32)})
    assert np.allclose(out["predictions"], [[5.0]])

    ds = rdata.from_items([{"a": float(i), "b": float(i)} for i in range(8)])
    ds = Concatenator(columns=["a", "b"], output_column_name="feat", dtype=np.float32).transform(ds)
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor, input_column="feat")
    scored = bp.predict(ds, batch_size=4, max_scoring_workers=2)
    preds = [float(np.ravel(r["predictions"])[0]) for r in scored.take_all()]
    assert preds == [5.0 * i for i in range(8)]


def test_extended_scalers_and_discretizers(ray_cluster):
    from ray_tpu.data.preprocessors import (
        CustomKBinsDiscretizer,
        MaxAbsScaler,
        RobustScaler,
        UniformKBinsDiscretizer,
    )

    rows = [{"x": float(i)} for i in range(100)]
    ds = _ds(rows)
    out = MaxAbsScaler(["x"]).fit_transform(ds).to_pandas()
    assert abs(out["x"].max() - 1.0) < 1e-9

    out = RobustScaler(["x"]).fit_transform(ds).to_pandas()
    # median maps to ~0, IQR to ~1 (reservoir covers all 100 values).
    assert abs(np.median(out["x"])) < 0.1
    assert 0.8 < (np.quantile(out["x"], 0.75) - np.quantile(out["x"], 0.25)) < 1.2

    out = UniformKBinsDiscretizer(["x"], bins=4).fit_transform(ds).to_pandas()
    assert set(out["x"].unique()) == {0, 1, 2, 3}
    assert out["x"].iloc[0] == 0 and out["x"].iloc[99] == 3

    out = CustomKBinsDiscretizer(["x"], bin_edges=[25.0, 50.0]).transform(ds).to_pandas()
    assert set(out["x"].unique()) == {0, 1, 2}


def test_normalizer_and_power_transform(ray_cluster):
    from ray_tpu.data.preprocessors import Normalizer, PowerTransformer

    ds = _ds([{"a": 3.0, "b": 4.0}, {"a": 0.0, "b": 0.0}])
    out = Normalizer(["a", "b"], norm="l2").transform(ds).to_pandas()
    assert abs(out.loc[0, "a"] - 0.6) < 1e-9 and abs(out.loc[0, "b"] - 0.8) < 1e-9
    assert out.loc[1, "a"] == 0.0  # zero-norm row passes through

    ds = _ds([{"x": 3.0}])
    out = PowerTransformer(["x"], power=0.0, method="box-cox").transform(ds).to_pandas()
    assert abs(out.loc[0, "x"] - np.log(3.0)) < 1e-9
    out = PowerTransformer(["x"], power=1.0, method="yeo-johnson").transform(ds).to_pandas()
    assert abs(out.loc[0, "x"] - 3.0) < 1e-9


def test_ordinal_and_multihot_encoders(ray_cluster):
    from ray_tpu.data.preprocessors import MultiHotEncoder, OrdinalEncoder

    ds = _ds([{"c": "red"}, {"c": "blue"}, {"c": "red"}])
    enc = OrdinalEncoder(["c"])
    out = enc.fit_transform(ds).to_pandas()
    assert list(out["c"]) == [1, 0, 1]  # sorted categories: blue=0, red=1
    # Unseen value: the ValueError surfaces wrapped by the remote map task.
    from ray_tpu.exceptions import TaskError

    with pytest.raises((ValueError, TaskError), match="unseen value"):
        enc.transform(_ds([{"c": "green"}])).to_pandas()

    ds = _ds([{"tags": ["a", "b"]}, {"tags": ["b"]}, {"tags": []}])
    out = MultiHotEncoder(["tags"]).fit_transform(ds).to_pandas()
    mat = np.stack(out["tags"].to_numpy())
    np.testing.assert_array_equal(mat, [[1, 1], [0, 1], [0, 0]])


def test_tokenizer_and_vectorizers(ray_cluster):
    from ray_tpu.data.preprocessors import (
        CountVectorizer,
        FeatureHasher,
        HashingVectorizer,
        Tokenizer,
    )

    ds = _ds([{"t": "The cat and the hat"}, {"t": "a cat"}])
    out = Tokenizer(["t"]).transform(ds).to_pandas()
    assert list(out["t"].iloc[0]) == ["the", "cat", "and", "the", "hat"]

    out = CountVectorizer(["t"], max_features=3).fit_transform(ds).to_pandas()
    # top-3 by frequency: the(2), cat(2), then tie broken alphabetically -> a or and
    assert out["t_cat"].tolist() == [1, 1]
    assert out["t_the"].tolist() == [2, 0]
    assert "t" not in out.columns

    out = HashingVectorizer(["t"], num_features=8).transform(ds).to_pandas()
    hashed_cols = [c for c in out.columns if c.startswith("t_hash_")]
    assert len(hashed_cols) == 8
    assert out[hashed_cols].to_numpy().sum() == 7  # 5 + 2 tokens total

    ds = _ds([{"u": "x", "v": 1}, {"u": "y", "v": 1}])
    out = FeatureHasher(["u", "v"], num_features=16).transform(ds).to_pandas()
    mat = np.stack(out["hashed_features"].to_numpy())
    assert mat.shape == (2, 16) and mat.sum() == 4  # 2 features per row
    # Same (col, value) pair lands in the same bucket across rows.
    assert (mat[0] != mat[1]).any()
