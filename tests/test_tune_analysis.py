"""Offline ExperimentAnalysis tests (reference
python/ray/tune/analysis/experiment_analysis.py + tests/test_experiment_analysis.py):
a finished experiment is analyzable from its directory alone — no live
controller, and even when the directory was written by another process."""

import json
import os
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ExperimentAnalysis


def _write_foreign_experiment(root):
    """Hand-write the on-disk schema (what any finished run leaves behind)."""
    os.makedirs(root, exist_ok=True)
    trials = []
    for tid, xs in (("t1", [0.3, 0.7, 0.5]), ("t2", [0.2, 0.9, 0.8])):
        tdir = os.path.join(root, tid)
        os.makedirs(tdir, exist_ok=True)
        with open(os.path.join(tdir, "params.json"), "w") as f:
            json.dump({"lr": 0.1 if tid == "t1" else 0.01}, f)
        with open(os.path.join(tdir, "result.json"), "w") as f:
            for i, x in enumerate(xs):
                f.write(json.dumps({"training_iteration": i + 1, "acc": x}) + "\n")
        trials.append(
            {
                "trial_id": tid,
                "status": "TERMINATED",
                "config": {"lr": 0.1 if tid == "t1" else 0.01},
                "last_result": {"training_iteration": len(xs), "acc": xs[-1]},
            }
        )
    with open(os.path.join(root, "experiment_state.json"), "w") as f:
        json.dump(
            {"experiment_name": "foreign", "metric": "acc", "mode": "max", "trials": trials},
            f,
        )


def test_analysis_over_foreign_directory(tmp_path):
    root = str(tmp_path / "exp")
    _write_foreign_experiment(root)
    ea = ExperimentAnalysis(root)

    # defaults come from the experiment state
    assert ea.default_metric == "acc" and ea.default_mode == "max"
    assert ea.stats["num_trials"] == 2

    # scope="last" compares final reports: t2 ends at 0.8 > t1's 0.5
    assert ea.get_best_trial().trial_id == "t2"
    assert ea.get_best_config() == {"lr": 0.01}
    assert ea.get_best_logdir().endswith("t2")
    # scope="all" compares best-ever reports: t2 peaked at 0.9
    assert ea.get_best_trial(scope="all").trial_id == "t2"
    # min mode flips it
    assert ea.get_best_trial(mode="min").trial_id == "t1"

    # per-trial dataframes carry the full history in order
    dfs = ea.trial_dataframes
    assert list(dfs["t1"]["acc"]) == [0.3, 0.7, 0.5]
    assert list(dfs["t2"]["training_iteration"]) == [1, 2, 3]

    # dataframe(): one row per trial; with metric/mode it picks each
    # trial's best report for that metric
    df = ea.dataframe()
    assert set(df["trial_id"]) == {"t1", "t2"}
    best_df = ea.dataframe(metric="acc", mode="max")
    assert sorted(best_df["acc"]) == [0.7, 0.9]

    assert ea.get_all_configs() == {"t1": {"lr": 0.1}, "t2": {"lr": 0.01}}


def test_analysis_rejects_non_experiment_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        ExperimentAnalysis(str(tmp_path))


_CHILD = r"""
import sys
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu import tune
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig

def trainable(config):
    for i in range(3):
        score = config["x"] * (i + 1)
        tune.report({{"score": score}}, checkpoint=Checkpoint.from_dict({{"score": score}}))

ray_tpu.init(num_cpus=2)
tune.Tuner(
    trainable,
    param_space={{"x": tune.grid_search([1.0, 2.0])}},
    tune_config=tune.TuneConfig(metric="score", mode="max"),
    run_config=RunConfig(storage_path={storage!r}, name="offline_exp"),
).fit()
ray_tpu.shutdown()
"""


def test_analysis_over_experiment_written_by_previous_process(tmp_path):
    """The analysis target is literally another process's output directory."""
    storage = str(tmp_path)
    script = _CHILD.format(repo="/root/repo", storage=storage)
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True, timeout=300
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    exp_dir = os.path.join(storage, "offline_exp")
    ea = ExperimentAnalysis(exp_dir)
    assert ea.stats["num_trials"] == 2
    best = ea.get_best_trial()
    assert best.config["x"] == 2.0
    assert ea.best_result["score"] == pytest.approx(6.0)
    # every trial reported 3 results, all recoverable in order
    for t in ea.trials:
        rows = t.results()
        assert [r["training_iteration"] for r in rows] == [1, 2, 3]
    # the best trial's persisted checkpoint is loadable
    ckpt = ea.get_best_checkpoint()
    assert ckpt is not None and ckpt.to_dict()["score"] == pytest.approx(6.0)
    # Tuner.restore rides the same loader over the same directory
    t = tune.Tuner.restore(exp_dir, lambda cfg: None)
    assert len(t._restore_state["trials"]) == 2
