"""Mosaic lowering gate for the Pallas kernels.

Round-1 lesson (VERDICT.md Weak #1): every kernel test ran interpret=True on
CPU, so the suite stayed green while the TPU lowering was broken (the LSE
BlockSpec violated the (8, 128) tile constraint and bench.py crashed on
hardware). This test compiles the kernels for the real TPU backend — no
interpret — so a Mosaic lowering regression fails CI whenever a TPU is
reachable.

The suite-wide conftest pins this process to CPU before jax import, so the
probe runs in a subprocess with the CPU pins stripped; it skips (not passes)
when no TPU backend comes up.
"""

import os
import subprocess
import sys

import pytest

_PROBE = r"""
import sys
import jax
if jax.default_backend() not in ("tpu", "axon"):
    print("NO_TPU_BACKEND:" + jax.default_backend())
    sys.exit(42)
import jax.numpy as jnp
from ray_tpu.ops.attention import flash_attention

B, T, H, D = 2, 512, 4, 128
q = jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16)

for causal in (False, True):
    fwd = jax.jit(lambda q, k, v, c=causal: flash_attention(q, k, v, causal=c, force_pallas=True))
    fwd.lower(q, q, q).compile()
    bwd = jax.jit(jax.grad(
        lambda q, k, v, c=causal: flash_attention(q, k, v, causal=c, force_pallas=True).astype(jnp.float32).sum(),
        argnums=(0, 1, 2)))
    bwd.lower(q, q, q).compile()
print("LOWERED_OK")
"""


def _run_tpu_probe(probe_src: str):
    """Run a probe in a subprocess with the suite's CPU pins stripped;
    returns the CompletedProcess, or None if no TPU backend came up."""
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "RAY_TPU_JAX_CONFIG_PLATFORMS", "RAY_TPU_NUM_TPUS", "XLA_FLAGS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-c", probe_src],
        env=env,
        capture_output=True,
        text=True,
        timeout=580,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode == 42:
        pytest.skip(f"no TPU backend in subprocess: {proc.stdout.strip()}")
    return proc


def _assert_lowered(proc):
    assert proc.returncode == 0, f"TPU lowering failed:\n{proc.stdout}\n{proc.stderr[-4000:]}"
    assert "LOWERED_OK" in proc.stdout


def test_flash_attention_lowers_on_tpu():
    _assert_lowered(_run_tpu_probe(_PROBE))


_RING_PROBE = r"""
import sys
import jax
if jax.default_backend() not in ("tpu", "axon"):
    print("NO_TPU_BACKEND:" + jax.default_backend())
    sys.exit(42)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh
from ray_tpu.parallel.ring_attention import ring_attention

mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
x = jax.ShapeDtypeStruct((2, 1024, 4, 128), jnp.bfloat16)
fwd = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True, impl="pallas"))
fwd.lower(x, x, x).compile()
bwd = jax.jit(jax.grad(
    lambda q, k, v: ring_attention(q, k, v, mesh, causal=True, impl="pallas").astype(jnp.float32).sum(),
    argnums=(0, 1, 2)))
bwd.lower(x, x, x).compile()
print("LOWERED_OK")
"""


def test_ring_attention_pallas_lowers_on_tpu():
    _assert_lowered(_run_tpu_probe(_RING_PROBE))
