"""Tracing + usage-stats tests (reference: python/ray/tests/test_tracing.py,
test_usage_stats.py)."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import tracing


def test_span_propagation_across_tasks(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    tracing._enabled = None  # re-read env
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def child():
            return "leaf"

        @ray_tpu.remote
        def parent():
            return ray_tpu.get(child.remote())

        assert ray_tpu.get(parent.remote(), timeout=60) == "leaf"
        # Flush events and reconstruct spans.
        deadline = time.time() + 20
        by_name = {}
        while time.time() < deadline:
            spans = tracing.export_spans()
            by_name = {s["name"]: s for s in spans}
            if "parent" in by_name and "child" in by_name:
                break
            time.sleep(0.3)
        assert "parent" in by_name and "child" in by_name, by_name.keys()
        p, c = by_name["parent"], by_name["child"]
        assert p["trace_id"] == c["trace_id"], "child must join the parent's trace"
        assert c["parent_id"] == p["span_id"], "child's parent span is the parent task"
        assert p["parent_id"] is None  # root span from the driver
    finally:
        ray_tpu.shutdown()
        tracing._enabled = None


def test_tracing_disabled_no_ctx(monkeypatch):
    monkeypatch.delenv("RAY_TPU_TRACING", raising=False)
    tracing._enabled = None
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1
        assert tracing.export_spans() == []
    finally:
        ray_tpu.shutdown()
        tracing._enabled = None


def test_usage_stats_written_on_shutdown():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    from ray_tpu._private import worker_context

    session_dir = worker_context.get_core_worker().session_dir
    ray_tpu.shutdown()
    path = os.path.join(session_dir, "usage_stats.json")
    assert os.path.exists(path)
    report = json.load(open(path))
    assert report["num_nodes"] == 1
    assert report["total_num_cpus"] == 2
    assert report["ray_tpu_version"]


def test_usage_stats_opt_out(monkeypatch):
    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    from ray_tpu._private import worker_context

    session_dir = worker_context.get_core_worker().session_dir
    ray_tpu.shutdown()
    assert not os.path.exists(os.path.join(session_dir, "usage_stats.json"))
