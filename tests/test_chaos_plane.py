"""Chaos fault-injection plane (ISSUE 13): seeded deterministic rules, the
rpc frame-seam injection for every fault kind, partition fail-fast + heal,
acall retry backoff, and the duplicate-delivery idempotency fixes the plane
exposed (P2PInbox and channel-gate reassembly).

Everything here is clusterless (loopback RpcServer/RpcClient at most); the
cluster-level chaos matrix lives in test_chaos_matrix.py.
"""

import random
import threading
import time

import pytest

from ray_tpu._private import chaos
from ray_tpu._private.chaos import CHAOS_STATS, FaultPlan
from ray_tpu._private.rpc import (
    ConnectionLost,
    RpcClient,
    RpcServer,
    retry_backoff_s,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.clear()
    yield
    chaos.clear()


@pytest.fixture(scope="module")
def echo_server():
    srv = RpcServer("chaos-test")
    calls = {"n": 0}

    async def echo(req):
        calls["n"] += 1
        return {"x": req.get("x"), "n": calls["n"]}

    srv.register("echo", echo)
    addr = srv.start()
    cli = RpcClient(addr, label="chaos-cli")
    yield srv, cli, addr, calls
    cli.close()
    srv.stop()


# ---------------------------------------------------------------------------
# Rule mechanics: deterministic, seeded
# ---------------------------------------------------------------------------


def _drive(plan, frames):
    """Feed a synthetic frame stream through the decision point; return the
    (kind or None) decision sequence."""
    out = []
    for method in frames:
        act = plan.on_send(None, "peer-x", "127.0.0.1:1", method)
        out.append(None if act is None else act.kind)
    return out


def test_same_seed_same_injection_sequence():
    """THE determinism contract: identical plan spec + seed over an
    identical frame stream produce the identical injection sequence (and
    log), including probabilistic rules — the RNG is the plan's own."""
    spec = {
        "rules": [
            {"kind": "drop", "method": "a", "p": 0.5},
            {"kind": "delay", "method": "b", "p": 0.7, "delay_ms": [1, 9]},
            {"kind": "dup", "method": "c", "every": 3},
        ]
    }
    frames = [random.Random(3).choice("abcd") for _ in range(200)]
    p1, p2 = FaultPlan(spec, seed=42), FaultPlan(spec, seed=42)
    assert _drive(p1, frames) == _drive(p2, frames)
    assert list(p1.log) == list(p2.log)
    # A different seed produces a different schedule for the p-thinned rules.
    p3 = FaultPlan(spec, seed=43)
    assert _drive(p3, frames) != _drive(p1, frames)


def test_kill_rule_same_seed_same_kill_point():
    """Crash-column determinism (ISSUE 14 acceptance): the SAME seed over
    the SAME frame stream selects the SAME kill frame — plan-level replay,
    exercised here at the decision point only (applying the Action would
    SIGKILL this test). Probability-thinned kill rules lean on the plan's
    seeded RNG exactly like the other kinds."""
    spec = {
        "rules": [
            {"kind": "kill", "method": "stream_item", "after": 2, "p": 0.6},
        ]
    }
    rng = random.Random(7)
    frames = [rng.choice(["stream_item", "other"]) for _ in range(80)]
    runs = []
    for _ in range(2):
        plan = FaultPlan(spec, seed=21, allow_kill=True)
        got = _drive(plan, frames)
        # In reality the first fire is terminal (the process dies at that
        # frame); the decision point keeps going, which is exactly what
        # lets a REPLAY walk the same stream. The kill POINT is fire #1.
        assert "kill" in got, got
        runs.append((got.index("kill"), got, list(plan.log)))
    assert runs[0] == runs[1]
    # A different seed moves the p-thinned injection schedule.
    alt = _drive(FaultPlan(spec, seed=22, allow_kill=True), frames)
    assert alt != runs[0][1]


def test_kill_rule_refused_on_direct_install():
    """Foot-gun guard: a kill rule SIGKILLs the INSTALLING process, so the
    direct in-process install path refuses it — only the remote push paths
    (chaos_set_plan RPC, env inheritance at boot) arm kill rules."""
    with pytest.raises(ValueError, match="kill"):
        chaos.install({"rules": [{"kind": "kill", "method": "x"}]})
    assert chaos.active() is None
    # Explicit opt-in works (the victim process installing its own doom).
    plan = chaos.install(
        {"rules": [{"kind": "kill", "method": "never_called"}]}, allow_kill=True
    )
    assert plan is not None
    chaos.clear()


def test_counted_rules_fire_deterministically():
    plan = FaultPlan(
        {"rules": [{"kind": "drop", "method": "m", "after": 2, "every": 2, "times": 3}]}
    )
    got = _drive(plan, ["m"] * 12)
    # Matches 1,2 skipped (after=2); then every 2nd of the remainder fires,
    # capped at 3 fires: matches 4, 6, 8.
    assert [i for i, k in enumerate(got) if k == "drop"] == [3, 5, 7]


def test_rule_matching_filters():
    plan = FaultPlan({"rules": [{"kind": "drop", "method": ["a", "b"], "peer": "raylet"}]})
    assert plan.on_send(None, "raylet-1", "x:1", "a") is not None
    assert plan.on_send(None, "worker-1", "x:1", "a") is None  # peer mismatch
    assert plan.on_send(None, "raylet-1", "x:1", "zzz") is None  # method mismatch
    # The chaos control plane is never injected.
    assert plan.on_send(None, "raylet-1", "x:1", "chaos_set_plan") is None


def test_partition_membrane_semantics():
    """Membrane: only links CROSSING the inside/outside boundary sever —
    node-local links (inside<->inside) and outside<->outside stay up."""
    plan = FaultPlan({})
    plan.add_membrane({"node:1", "w:1"}, local_inside=False)
    assert plan.blocked(None, "node:1")          # outside -> inside
    assert plan.blocked("node:1", "gcs:1")       # inside -> outside
    assert not plan.blocked("node:1", "w:1")     # inside -> inside (node-local)
    assert not plan.blocked(None, "gcs:1")       # outside -> outside
    plan.heal_all()
    assert not plan.blocked(None, "node:1")


# ---------------------------------------------------------------------------
# Frame-seam injection over a real loopback connection
# ---------------------------------------------------------------------------


def test_drop_heals_by_retry(echo_server):
    _, cli, _, _ = echo_server
    assert cli.call("echo", {"x": 0}, timeout=5)["x"] == 0  # warm connection
    plan = chaos.install({"rules": [{"kind": "drop", "method": "echo", "times": 1}]}, seed=1)
    t0 = time.monotonic()
    assert cli.call("echo", {"x": 1}, timeout=0.4, retries=2)["x"] == 1
    assert time.monotonic() - t0 < 3.0
    assert list(plan.log) == ["drop:echo:chaos-cli"]


def test_dup_delivers_twice(echo_server):
    _, cli, _, calls = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    chaos.install({"rules": [{"kind": "dup", "method": "echo", "times": 1}]})
    before = calls["n"]
    cli.call("echo", {"x": 1}, timeout=5)
    deadline = time.monotonic() + 2
    while calls["n"] - before < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    # The duplicated REQUEST frame reaches the handler twice: requests are
    # at-least-once under this plane, which is exactly what handlers must
    # tolerate (and what the dedupe fixes below are for).
    assert calls["n"] - before == 2


def test_reset_mid_frame_tears_and_recovers(echo_server):
    _, cli, _, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    resets_before = CHAOS_STATS.resets
    chaos.install(
        {"rules": [{"kind": "reset", "method": "echo", "reset_at": 3, "times": 1}]}
    )
    # The torn frame kills the connection; the retry reconnects and lands.
    assert cli.call("echo", {"x": 7}, timeout=2, retries=3)["x"] == 7
    assert CHAOS_STATS.resets == resets_before + 1


def test_delay_holds_the_frame(echo_server):
    _, cli, _, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    chaos.install(
        {"rules": [{"kind": "delay", "method": "echo", "delay_ms": [150, 200], "times": 1}]},
        seed=5,
    )
    t0 = time.monotonic()
    assert cli.call("echo", {"x": 1}, timeout=5)["x"] == 1
    assert time.monotonic() - t0 >= 0.14


def test_partition_fails_fast_and_heals(echo_server):
    _, cli, addr, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    key = f"{addr[0]}:{addr[1]}"
    chaos.partition("*", key)
    t0 = time.monotonic()
    with pytest.raises(ConnectionLost):
        cli.call("echo", {"x": 1}, timeout=2, retries=0)
    # Fail-fast: an unroutable peer must not burn the 10s connect budget.
    assert time.monotonic() - t0 < 1.0
    chaos.heal("*", key)
    assert cli.call("echo", {"x": 2}, timeout=5)["x"] == 2


def test_response_side_injection(echo_server):
    """side="resp" rules hit the server's response write, not the request:
    the client sees a timeout while the handler DID run."""
    srv, cli, _, calls = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    chaos.install(
        {"rules": [{"kind": "drop", "method": "echo", "side": "resp", "times": 1}]}
    )
    before = calls["n"]
    assert cli.call("echo", {"x": 1}, timeout=0.4, retries=2)["x"] == 1
    assert calls["n"] - before == 2  # first attempt executed, reply dropped


def test_injection_records_event_and_stats(echo_server, tmp_path):
    from ray_tpu._private import flight_recorder

    _, cli, _, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    flight_recorder.attach(str(tmp_path), role="test", ident="chaos")
    try:
        drops_before = CHAOS_STATS.drops
        chaos.install({"rules": [{"kind": "drop", "method": "echo", "times": 1}]})
        cli.call("echo", {"x": 1}, timeout=0.4, retries=2)
        assert CHAOS_STATS.drops == drops_before + 1
        dump = flight_recorder.dump()
        evs = [e for e in dump["events"] if e["type"] == "chaos_inject"]
        assert evs and evs[-1]["detail"].startswith("drop:")
    finally:
        flight_recorder._reset_for_tests()


def test_chaos_metric_collector_folds():
    from ray_tpu._private import self_metrics

    inst = self_metrics.instruments()
    assert "chaos_injected" in inst
    CHAOS_STATS.drops += 3
    self_metrics._collect_chaos_stats()
    # The flush-time collector folded the plain-int counter into the
    # instrument (delta tracking recorded the new watermark).
    assert self_metrics._folded[("chaos", "drops")] == CHAOS_STATS.drops


# ---------------------------------------------------------------------------
# acall retry backoff (satellite)
# ---------------------------------------------------------------------------


def test_backoff_schedule_is_capped_exponential_with_jitter():
    rng = random.Random(0)
    vals = [retry_backoff_s(a, 0.1, 2.0, rng) for a in range(1, 10)]
    # Each raw delay is base*2^(attempt-1) capped at max, jittered into
    # [0.5, 1.0) of that; assert the envelope per attempt.
    for attempt, v in enumerate(vals, start=1):
        raw = min(2.0, 0.1 * (2 ** (attempt - 1)))
        assert 0.5 * raw <= v < raw
    # The cap holds: attempts deep into the schedule never exceed max.
    assert max(vals) < 2.0
    # Seeded: replaying from the same rng state reproduces the schedule.
    rng2 = random.Random(0)
    assert vals == [retry_backoff_s(a, 0.1, 2.0, rng2) for a in range(1, 10)]


def test_retries_zero_unaffected_by_backoff(echo_server):
    """retries=0 callers raise immediately — no backoff sleep is inserted."""
    _, cli, _, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    chaos.install({"rules": [{"kind": "drop", "method": "echo"}]})
    t0 = time.monotonic()
    with pytest.raises(Exception):
        cli.call("echo", {"x": 1}, timeout=0.3, retries=0)
    # One attempt, one timeout, zero backoff sleeps.
    assert time.monotonic() - t0 < 0.8


def test_backoff_paces_retries_against_dead_peer(echo_server):
    """The retry schedule against a repeatedly-failing peer spaces out:
    total wall for N retries ~ sum of the capped-exponential schedule, not
    N * fixed-pause."""
    _, cli, _, _ = echo_server
    cli.call("echo", {"x": 0}, timeout=5)
    chaos.install({"rules": [{"kind": "drop", "method": "echo"}]})
    t0 = time.monotonic()
    with pytest.raises(Exception):
        cli.call("echo", {"x": 1}, timeout=0.1, retries=3)
    elapsed = time.monotonic() - t0
    # 4 attempts * 0.1s timeout + backoffs of ~[0.05-0.1, 0.1-0.2, 0.2-0.4].
    assert elapsed >= 0.4 + 0.05 + 0.1 + 0.2 - 0.05


# ---------------------------------------------------------------------------
# Duplicate/reordered one-way frames: reassembly idempotency (satellite +
# two of the recovery bugs the matrix exposed, pinned)
# ---------------------------------------------------------------------------


def test_p2p_inbox_idempotent_under_duplicated_chunks():
    from ray_tpu.util.collective.p2p import P2PInbox

    inbox = P2PInbox()
    # Reordered + duplicated 3-chunk payload.
    assert not inbox.deposit("k", 2, 3, b"C")
    assert not inbox.deposit("k", 0, 3, b"A")
    assert not inbox.deposit("k", 0, 3, b"A")  # dup mid-assembly
    assert inbox.deposit("k", 1, 3, b"B")
    # PINNED REGRESSION: a duplicate arriving AFTER completion must not
    # re-open a forever-partial reassembly (it used to leak in _parts until
    # the 180s sweep) nor resurrect the completed entry.
    assert not inbox.deposit("k", 1, 3, b"B")
    s = inbox.stats()
    assert s["partials"] == 0 and s["entries"] == 1
    assert inbox.take("k") == b"ABC"
    # PINNED REGRESSION: a duplicate after take() must not resurrect the
    # consumed payload (at-most-once take contract).
    assert not inbox.deposit("k", 1, 3, b"B")
    assert not inbox.deposit("k2", 0, 1, b"Z") is None
    assert inbox.take("k") is None
    assert inbox.stats()["partials"] == 0


def test_channel_gate_idempotent_under_duplicated_chunks():
    from ray_tpu.experimental.channel.channel import _Gate

    gate = _Gate()
    gate.add_chunk(5, 1, 2, b"B")  # reordered
    gate.add_chunk(5, 0, 2, b"A")
    assert gate.pop(5) == b"AB"
    # PINNED REGRESSION: duplicates after completion/pop used to re-open a
    # partial whose phantom depth inflated queued() — the remote-mode
    # writer's backpressure credit — throttling the producer on garbage.
    gate.add_chunk(5, 0, 2, b"A")
    gate.add_chunk(5, 1, 2, b"B")
    assert gate.queued() == 0
    assert gate.pop(5) is None  # not resurrected
    # Fresh seqs still flow.
    gate.add_chunk(6, 0, 1, b"Z")
    assert gate.pop(6) == b"Z"


def test_p2p_inbox_sweep_still_reaps_stale_partials():
    from ray_tpu.util.collective.p2p import P2PInbox

    inbox = P2PInbox()
    inbox.deposit("dead", 0, 2, b"A")  # never completes
    assert inbox.sweep(max_age_s=0.0) == 1
    assert inbox.stats()["partials"] == 0
