"""Cross-language tasks: C++ kernels on the task plane, msgpack object format.

Compiles cpp/xlang_kernels.cc into a shared library and drives it through
the FULL framework path (driver -> task submission -> worker -> ctypes ABI
-> format-"x" object store entry -> ray_tpu.get). Reference surface:
ray.cross_language + the C++ user-function execution path.
"""

import os
import subprocess

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "cpp", "xlang_kernels.cc")


@pytest.fixture(scope="module")
def kernels_so(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("xlang") / "libxlang_kernels.so")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out, SRC],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"xlang kernels failed to compile:\n{proc.stderr}")
    return out


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_xlang_serialization_roundtrip():
    """Format-'x' objects decode to plain data; pickle objects unaffected."""
    import msgpack

    from ray_tpu._private import serialization
    from ray_tpu._private.serialization import XLangBytes

    obj = {"a": [1, 2.5, "three", b"four", None, True], "n": -7}
    blob = serialization.serialize(XLangBytes(msgpack.packb(obj, use_bin_type=True)))
    assert blob.format == "x" and not blob.buffers
    assert serialization.deserialize(blob.to_bytes()) == obj
    # Default pickle path untouched.
    assert serialization.loads(serialization.dumps({"k": 1})) == {"k": 1}


def test_cpp_sum_and_wordcount(cluster, kernels_so):
    from ray_tpu.cross_language import cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    assert ray_tpu.get(sum_fn.remote([1, 2, 3])) == 6
    assert ray_tpu.get(sum_fn.remote([1, 2, 3.5])) == pytest.approx(6.5)

    wc = cpp_function("xlang_wordcount", kernels_so)
    out = ray_tpu.get(wc.remote("the cat and the hat"))
    assert out == {"the": 2, "cat": 1, "and": 1, "hat": 1}

    # Integer sums are EXACT past double precision (int64 accumulation).
    assert ray_tpu.get(sum_fn.remote([2**60, 1])) == 2**60 + 1
    with pytest.raises(Exception, match="overflow"):
        ray_tpu.get(sum_fn.remote([2**62, 2**62, 2**62]))


def test_cpp_vector_scale_binary(cluster, kernels_so):
    from ray_tpu.cross_language import cpp_function

    scale = cpp_function("xlang_vector_scale", kernels_so)
    vec = np.arange(8, dtype=np.float32)
    out = ray_tpu.get(scale.remote(vec.tobytes(), 2.5))
    np.testing.assert_allclose(np.frombuffer(out, np.float32), vec * 2.5)
    # A non-numeric scale is an error, not a silent zero-multiply.
    with pytest.raises(Exception, match="numeric"):
        ray_tpu.get(scale.remote(vec.tobytes(), "2.5"))


def test_cpp_error_surfaces_as_exception(cluster, kernels_so):
    from ray_tpu.cross_language import CrossLanguageError, cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(sum_fn.remote(["not-a-number"]))
    assert "non-numeric" in str(ei.value)

    missing = cpp_function("no_such_symbol", kernels_so)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(missing.remote(1))
    assert "no_such_symbol" in str(ei.value)
    # The invoker raises the typed error when called in-process too.
    from ray_tpu.cross_language import CppFunctionInvoker

    with pytest.raises(CrossLanguageError):
        CppFunctionInvoker(kernels_so, "no_such_symbol")(1)


def test_stored_object_is_language_agnostic(cluster, kernels_so):
    """The result object's wire form is msgpack (format 'x') — a non-Python
    runtime can decode it without pickle."""
    import msgpack

    from ray_tpu.cross_language import cpp_function

    ref = cpp_function("xlang_sum", kernels_so).remote([10, 20])
    assert ray_tpu.get(ref) == 30
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    raw = cw.get_raw_object_bytes(ref) if hasattr(cw, "get_raw_object_bytes") else None
    if raw is not None:
        header_len = int.from_bytes(raw[:4], "big")
        header = msgpack.unpackb(bytes(raw[4 : 4 + header_len]), raw=False)
        assert header.get("f") == "x"
