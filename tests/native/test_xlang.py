"""Cross-language tasks: C++ kernels on the task plane, msgpack object format.

Compiles cpp/xlang_kernels.cc into a shared library and drives it through
the FULL framework path (driver -> task submission -> worker -> ctypes ABI
-> format-"x" object store entry -> ray_tpu.get). Reference surface:
ray.cross_language + the C++ user-function execution path.
"""

import os
import subprocess

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "cpp", "xlang_kernels.cc")


@pytest.fixture(scope="module")
def kernels_so(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("xlang") / "libxlang_kernels.so")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out, SRC],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"xlang kernels failed to compile:\n{proc.stderr}")
    return out


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_xlang_serialization_roundtrip():
    """Format-'x' objects decode to plain data; pickle objects unaffected."""
    import msgpack

    from ray_tpu._private import serialization
    from ray_tpu._private.serialization import XLangBytes

    obj = {"a": [1, 2.5, "three", b"four", None, True], "n": -7}
    blob = serialization.serialize(XLangBytes(msgpack.packb(obj, use_bin_type=True)))
    assert blob.format == "x" and not blob.buffers
    assert serialization.deserialize(blob.to_bytes()) == obj
    # Default pickle path untouched.
    assert serialization.loads(serialization.dumps({"k": 1})) == {"k": 1}


def test_cpp_sum_and_wordcount(cluster, kernels_so):
    from ray_tpu.cross_language import cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    assert ray_tpu.get(sum_fn.remote([1, 2, 3])) == 6
    assert ray_tpu.get(sum_fn.remote([1, 2, 3.5])) == pytest.approx(6.5)

    wc = cpp_function("xlang_wordcount", kernels_so)
    out = ray_tpu.get(wc.remote("the cat and the hat"))
    assert out == {"the": 2, "cat": 1, "and": 1, "hat": 1}

    # Integer sums are EXACT past double precision (int64 accumulation).
    assert ray_tpu.get(sum_fn.remote([2**60, 1])) == 2**60 + 1
    with pytest.raises(Exception, match="overflow"):
        ray_tpu.get(sum_fn.remote([2**62, 2**62, 2**62]))


def test_cpp_vector_scale_binary(cluster, kernels_so):
    from ray_tpu.cross_language import cpp_function

    scale = cpp_function("xlang_vector_scale", kernels_so)
    vec = np.arange(8, dtype=np.float32)
    out = ray_tpu.get(scale.remote(vec.tobytes(), 2.5))
    np.testing.assert_allclose(np.frombuffer(out, np.float32), vec * 2.5)
    # A non-numeric scale is an error, not a silent zero-multiply.
    with pytest.raises(Exception, match="numeric"):
        ray_tpu.get(scale.remote(vec.tobytes(), "2.5"))


def test_cpp_error_surfaces_as_exception(cluster, kernels_so):
    from ray_tpu.cross_language import CrossLanguageError, cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(sum_fn.remote(["not-a-number"]))
    assert "non-numeric" in str(ei.value)

    missing = cpp_function("no_such_symbol", kernels_so)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(missing.remote(1))
    assert "no_such_symbol" in str(ei.value)
    # The invoker raises the typed error when called in-process too.
    from ray_tpu.cross_language import CppFunctionInvoker

    with pytest.raises(CrossLanguageError):
        CppFunctionInvoker(kernels_so, "no_such_symbol")(1)


def test_stored_object_is_language_agnostic(cluster, kernels_so):
    """The result object's wire form is msgpack (format 'x') — a non-Python
    runtime can decode it without pickle. Reads the raw shm bytes through
    the store's pinned-read path and checks the header tag directly."""
    import msgpack

    from ray_tpu._private import worker_context
    from ray_tpu.cross_language import cpp_function

    # Pad the args so the result object... results are small; instead store
    # an explicit large payload through the kernel's scale (bin in == bin
    # out) so the object lands in shm rather than any inline path.
    vec = np.ones(100_000, dtype=np.float32)
    ref = cpp_function("xlang_vector_scale", kernels_so).remote(vec.tobytes(), 2)
    out = ray_tpu.get(ref)
    assert np.frombuffer(out, np.float32)[0] == 2.0

    cw = worker_context.get_core_worker()
    pinned = cw.store.index.get_pinned(ref.hex())
    assert pinned is not None, "result object not in local shm"
    off, size, token = pinned
    try:
        raw = bytes(cw.store.arena.read(off, size))
    finally:
        cw.store.index.release(token)
    header_len = int.from_bytes(raw[:4], "big")
    header = msgpack.unpackb(raw[4 : 4 + header_len], raw=False)
    assert header.get("f") == "x", header
    # The payload itself is plain msgpack — decodable with zero pickle.
    payload_start = (4 + header_len + 63) & ~63
    decoded = msgpack.unpackb(
        raw[payload_start : payload_start + header["p"]], raw=False
    )
    assert decoded == out
