// In-process TSAN hammer for the shm_index reader-pin/tombstone protocol.
//
// The daemon (writer thread) cycles put/seal/remove with key reuse while
// reader threads pin/validate/release through a second attached handle —
// the exact interleavings where a protocol bug would free memory under a
// reader or let a stale release unpin someone else's object. Built with
// -fsanitize=thread by tests/test_native_races.py; any data race aborts the
// run (halt_on_error=1).
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {
int idx_create(const char*, uint64_t);
int idx_attach(const char*);
int idx_put(int, const uint8_t*, uint64_t, uint64_t);
int idx_seal(int, const uint8_t*);
int idx_remove(int, const uint8_t*);
uint32_t idx_readers(int, const uint8_t*);
int idx_get_pinned(int, const uint8_t*, uint64_t*, uint64_t*, uint32_t*, uint64_t*);
int idx_release(int, uint64_t, uint32_t);
int idx_close(int, int);
}

static void key_of(int i, uint8_t* k) {
  memset(k, 0, 28);
  k[0] = (uint8_t)i;
  k[1] = (uint8_t)(i * 37);
}

int main(int argc, char** argv) {
  int seconds = argc > 1 ? atoi(argv[1]) : 3;
  const char* name = "/tsan_idx_test";
  int daemon = idx_create(name, 64);  // small table -> probe collisions + reuse
  if (daemon < 0) { printf("create failed\n"); return 2; }
  int reader_h = idx_attach(name);
  if (reader_h < 0) { printf("attach failed\n"); return 2; }

  std::atomic<bool> stop{false};
  std::atomic<uint64_t> hits{0};
  const int NKEYS = 24;

  std::thread writer([&] {
    uint64_t gen = 0;
    while (!stop.load(std::memory_order_relaxed)) {
      for (int i = 0; i < NKEYS; ++i) {
        uint8_t k[28];
        key_of(i, k);
        // Size encodes the key so readers can detect a torn/misrouted hit.
        if (idx_put(daemon, k, gen * 4096 + i, 1000 + i) == 0) idx_seal(daemon, k);
      }
      for (int i = 0; i < NKEYS; i += 2) {
        uint8_t k[28];
        key_of(i, k);
        idx_remove(daemon, k);  // 0 or 1 (deferred free) both legal
      }
      ++gen;
    }
  });

  std::vector<std::thread> readers;
  for (int t = 0; t < 4; ++t) {
    readers.emplace_back([&] {
      uint64_t off, sz, slot;
      uint32_t ver;
      while (!stop.load(std::memory_order_relaxed)) {
        for (int i = 0; i < NKEYS; ++i) {
          uint8_t k[28];
          key_of(i, k);
          if (idx_get_pinned(reader_h, k, &off, &sz, &ver, &slot)) {
            if (sz != (uint64_t)(1000 + i)) {
              printf("BAD PAYLOAD key=%d size=%llu\n", i, (unsigned long long)sz);
              fflush(stdout);
              _exit(3);
            }
            idx_release(reader_h, slot, ver);
            hits.fetch_add(1, std::memory_order_relaxed);
          }
        }
      }
    });
  }

  std::this_thread::sleep_for(std::chrono::seconds(seconds));
  stop.store(true);
  writer.join();
  for (auto& r : readers) r.join();
  printf("HAMMER_OK hits=%llu\n", (unsigned long long)hits.load());
  idx_close(reader_h, 0);
  idx_close(daemon, 1);
  return 0;
}
