"""C++ driver API end-to-end (N22 user-facing surface).

Compiles cpp/api_example.cc (which uses the header-only ray_tpu_api.h —
the reference's `ray::Task(...).Remote()` / `ray::Get()` shape,
cpp/include/ray/api.h) and runs it against a live cluster: the native
driver submits language="cpp" tasks to the raylet, runs its own owner-side
RPC server, and receives task_done results pushed by the (C++) worker —
the reference's owner-routed direct-call result path, no KV polling and no
Python in driver or worker.
"""

import os
import subprocess
import sys

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def cluster():
    # Pre-build the C++ worker binary so the pool spawns native workers
    # from the first cpp task (the nowait path would otherwise fall back
    # to a Python worker while g++ runs in the background).
    from ray_tpu._private.cpp_worker import cpp_worker_binary

    assert cpp_worker_binary() is not None
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def kernels_so(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("apik") / "libxlang_kernels.so")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out,
         os.path.join(REPO, "cpp", "xlang_kernels.cc")],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"kernels failed to compile:\n{proc.stderr}")
    return out


@pytest.fixture(scope="module")
def example(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("apib") / "api_example")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", out,
         os.path.join(REPO, "cpp", "api_example.cc"), "-lpthread"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"api example failed to compile:\n{proc.stderr}")
    return out


def test_cpp_api_end_to_end(cluster, kernels_so, example):
    from ray_tpu._private.worker_context import get_core_worker

    raylet_host, raylet_port = get_core_worker().raylet.address
    proc = subprocess.run(
        [example, raylet_host, str(raylet_port), kernels_so],
        capture_output=True, text=True, timeout=180,
    )
    sys.stderr.write(proc.stderr)
    out = proc.stdout
    assert proc.returncode == 0, f"api example failed:\n{out}\n{proc.stderr}"
    assert "SUM 6" in out
    assert "BATCH_OK" in out
    assert "WORDCOUNT_OK" in out
    assert "ERROR_OK" in out and "xlang_sum" in out
    # Native object pipeline: plasma-sized producer result consumed BY REF
    # by the next task, plasma result streamed back to the driver.
    assert "PIPELINE_OK" in out
    # A ref arg with a FAILED producer surfaces the producer's failure fast.
    assert "FAILED_REF_OK" in out
    assert "CPP_API_PASS" in out
