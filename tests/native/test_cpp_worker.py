"""C++ worker runtime: language="cpp" tasks execute in a NATIVE worker.

cpp/ray_tpu_worker.cc is the framework's analog of the reference's C++
worker runtime (cpp/src/ray/runtime/ — native task execution loop): the
raylet's worker pool spawns it for cpp_function tasks, it registers over
the real msgpack wire, executes C-ABI kernels, and reports format-"x"
results straight to the owner — no Python in the execution path. These
tests drive that full path and verify the native worker (not a Python
fallback) actually hosted the execution.
"""

import glob
import os
import subprocess

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "cpp", "xlang_kernels.cc")


@pytest.fixture(scope="module")
def kernels_so(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("xlangw") / "libxlang_kernels.so")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out, SRC],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"xlang kernels failed to compile:\n{proc.stderr}")
    return out


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _session_logs() -> str:
    node = ray_tpu._global_node
    assert node is not None
    return os.path.join(node.session_dir, "logs")


def _native_worker_was_used() -> bool:
    for path in glob.glob(os.path.join(_session_logs(), "worker-*.out")):
        try:
            with open(path, "rb") as f:
                if b"CPP_WORKER_READY" in f.read():
                    return True
        except OSError:
            pass
    return False


def test_cpp_worker_binary_builds():
    from ray_tpu._private.cpp_worker import cpp_worker_binary

    binary = cpp_worker_binary()
    assert binary is not None and os.path.exists(binary)


def test_cpp_task_executes_in_native_worker(cluster, kernels_so):
    from ray_tpu.cross_language import cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    assert ray_tpu.get(sum_fn.remote([1, 2, 3]), timeout=60) == 6
    assert ray_tpu.get(sum_fn.remote([1.5, 2.5]), timeout=60) == 4.0
    # The proof this ran NATIVELY: the C++ worker announces itself in its
    # log on startup; a Python-fallback run would leave no such marker.
    assert _native_worker_was_used(), "cpp task did not run in the C++ worker"

    # Worker reuse: a second wave should not need new worker spawns to
    # produce correct results (same pool key).
    outs = ray_tpu.get([sum_fn.remote([i, i]) for i in range(8)], timeout=60)
    assert outs == [2 * i for i in range(8)]


def test_cpp_task_error_raises_cross_language_error(cluster, kernels_so):
    from ray_tpu.cross_language import CrossLanguageError, cpp_function
    from ray_tpu.exceptions import TaskError

    bad = cpp_function("xlang_sum", kernels_so)
    with pytest.raises((TaskError, CrossLanguageError)) as exc_info:
        # xlang_sum rejects non-array args with rc != 0.
        ray_tpu.get(bad.remote("not-an-array"), timeout=60)
    assert "xlang_sum" in str(exc_info.value)

    missing = cpp_function("no_such_symbol", kernels_so)
    with pytest.raises((TaskError, CrossLanguageError)) as exc_info:
        ray_tpu.get(missing.remote([1]), timeout=60)
    assert "no_such_symbol" in str(exc_info.value)


def test_cpp_task_ref_args_fall_back_to_python_path(cluster, kernels_so):
    """ObjectRef (and plasma-sized) args need owner-fetch machinery the
    native runtime doesn't implement yet; those calls fall back to the
    Python ctypes path with IDENTICAL results rather than failing."""
    from ray_tpu.cross_language import cpp_function

    sum_fn = cpp_function("xlang_sum", kernels_so)
    ref = ray_tpu.put([1, 2, 3])
    assert ray_tpu.get(sum_fn.remote(ref), timeout=60) == 6


def test_python_tasks_unaffected_alongside_cpp(cluster, kernels_so):
    """Language-keyed pools: python and cpp workers coexist; a python task
    never lands on a native worker (it would have no pickle runtime)."""
    from ray_tpu.cross_language import cpp_function

    @ray_tpu.remote
    def py_add(a, b):
        return a + b

    sum_fn = cpp_function("xlang_sum", kernels_so)
    py_refs = [py_add.remote(i, i) for i in range(4)]
    cpp_refs = [sum_fn.remote([i, 1]) for i in range(4)]
    assert ray_tpu.get(py_refs, timeout=60) == [2 * i for i in range(4)]
    assert ray_tpu.get(cpp_refs, timeout=60) == [i + 1 for i in range(4)]


def test_cpp_worker_native_object_data_path(cluster, kernels_so):
    """VERDICT r4 #2's done-bar: a C++ task consumes a Python-produced
    10 MiB array ObjectRef and returns a plasma-sized result consumed by
    Python — NO Python fallback anywhere in the execute path."""
    import msgpack
    import numpy as np

    from ray_tpu._private.serialization import XLangBytes
    from ray_tpu._private.worker_context import get_core_worker
    from ray_tpu.cross_language import cpp_function

    cw = get_core_worker()
    arr = np.arange(2_621_440, dtype=np.float32)  # 10 MiB
    ref = ray_tpu.put(XLangBytes(msgpack.packb(arr.tobytes(), use_bin_type=True)))
    # The object went to plasma with a provable cross-language format.
    assert cw.owned[ref.hex()].in_plasma
    assert cw.owned[ref.hex()].format == "x"

    scale = cpp_function("xlang_vector_scale", kernels_so)
    out_ref = scale.remote(ref, 2.0)
    out = ray_tpu.get(out_ref, timeout=120)
    # Routing check (lineage survives completion): NATIVE despite the ref arg.
    assert cw.lineage[out_ref.hex()[:48]].language == "cpp" 
    got = np.frombuffer(out, dtype=np.float32)
    np.testing.assert_array_equal(got, arr * 2.0)
    # The 10 MiB result came back through plasma, not inline.
    assert cw.owned[out_ref.hex()].in_plasma
    assert _native_worker_was_used(), "did not run in the C++ worker"

    # Chaining: a NATIVE task's plasma result feeds the next native task by
    # ref (format recorded from the cpp result), halving back to the input.
    back_ref = scale.remote(out_ref, 0.5)
    back = np.frombuffer(ray_tpu.get(back_ref, timeout=120), dtype=np.float32)
    assert cw.lineage[back_ref.hex()[:48]].language == "cpp" 
    np.testing.assert_array_equal(back, arr)


def test_cpp_worker_pickle_ref_still_falls_back(cluster, kernels_so):
    """A ref whose object is NOT provably format-"x" (plain Python pickle)
    keeps the Python ctypes path — identical results, no native decode of
    undecodable bytes."""
    from ray_tpu._private.worker_context import get_core_worker
    from ray_tpu.cross_language import cpp_function

    cw = get_core_worker()
    sum_fn = cpp_function("xlang_sum", kernels_so)
    ref = ray_tpu.put([4, 5, 6])  # pickle format
    out_ref = sum_fn.remote(ref)
    assert ray_tpu.get(out_ref, timeout=60) == 15
    assert cw.lineage[out_ref.hex()[:48]].language == "py" 
