"""C++ client end-to-end (N22 down-payment; reference: cpp/include/ray/api.h).

Compiles cpp/ray_tpu_client.cc and runs it against a live cluster: GCS KV
round trip, node listing, task submission by function-table key with a
KV-polled result, and a zero-copy shared-memory object read through the
_native arena/index C APIs — all without Python in the client process.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
SRC = os.path.join(REPO, "cpp", "ray_tpu_client.cc")


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def binary(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("cclient") / "ray_tpu_cclient")
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", out, SRC, "-ldl"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"C client failed to compile:\n{proc.stderr}")
    return out


def _result_task():
    import ray_tpu as rt
    from ray_tpu._private.worker_context import get_core_worker

    # Key namespaced by THIS task's id — the C client polls exactly it, so
    # stale values from earlier runs can't satisfy the poll.
    tid = rt.get_runtime_context().get_task_id()
    get_core_worker().gcs.call(
        "kv_put", {"key": f"cclient:result:{tid}", "value": b"42-from-task"}
    )


def test_c_client_end_to_end(cluster, binary):
    from ray_tpu._private.worker_context import get_core_worker

    cw = get_core_worker()
    function_key = cw._export_function(_result_task)
    gcs_host, gcs_port = cw.gcs.address
    raylet_host, raylet_port = cw.raylet.address

    # A shm-resident object for the data-plane read (large enough to skip
    # any inline path).
    payload = np.arange(300_000, dtype=np.int64)
    ref = ray_tpu.put(payload)
    oid_hex = ref.hex()
    # Raylet naming convention (raylet.py): /rtpu_<node_id[:12]>.
    arena_name = os.environ.get("RAY_TPU_ARENA_NAME") or f"/rtpu_{cw.node_id[:12]}"
    native_dir = os.path.join(REPO, "ray_tpu", "_native", "build")

    proc = subprocess.run(
        [
            binary,
            gcs_host, str(gcs_port),
            raylet_host, str(raylet_port),
            function_key, cw.job_id.hex(),
            native_dir, arena_name, arena_name + "_idx", oid_hex,
        ],
        capture_output=True, text=True, timeout=120,
    )
    sys.stderr.write(proc.stderr)
    out = proc.stdout
    assert proc.returncode == 0, out + proc.stderr
    assert "KV_OK" in out
    assert "NODES 1" in out
    assert "TASK_SUBMITTED" in out
    assert "TASK_RESULT 42-from-task" in out  # the C-submitted task ran
    shm_lines = [ln for ln in out.splitlines() if ln.startswith("SHM_READ")]
    assert shm_lines, out
    size = int(shm_lines[0].split()[1])
    c_checksum = shm_lines[0].split()[2]
    assert size >= payload.nbytes  # serialized object spans the array
    # Content check: FNV-1a over the SAME shm bytes from the Python side
    # must match what the C client computed — proves it read the right
    # region, not just a plausibly-sized one.
    pinned = cw.store.index.get_pinned(oid_hex)
    assert pinned is not None
    off, sz, token = pinned
    try:
        view = cw.store.arena.read(off, sz)
        h = 1469598103934665603
        for byte in bytes(view):
            h = ((h ^ byte) * 1099511628211) % (1 << 64)
    finally:
        cw.store.index.release(token)
    assert sz == size
    assert f"{h:016x}" == c_checksum
    assert "C_CLIENT_PASS" in out


def test_c_client_decodes_xlang_object(cluster, binary):
    """Format-'x' objects round-trip into C++ with no pickle: the client
    prints XLANG_RESULT with the natively decoded value."""
    import msgpack

    from ray_tpu._private.serialization import XLangBytes
    from ray_tpu._private.worker_context import get_core_worker

    cw = get_core_worker()
    value = {"answer": 42, "parts": [1, 2.5, "three", True, None]}
    # Pad so the object lands in shm, not any inline path.
    value["pad"] = "x" * 200_000
    ref = ray_tpu.put(XLangBytes(msgpack.packb(value, use_bin_type=True)))
    assert ray_tpu.get(ref)["answer"] == 42  # python side sees plain data

    function_key = cw._export_function(_result_task)
    gcs_host, gcs_port = cw.gcs.address
    raylet_host, raylet_port = cw.raylet.address
    arena_name = os.environ.get("RAY_TPU_ARENA_NAME") or f"/rtpu_{cw.node_id[:12]}"
    native_dir = os.path.join(REPO, "ray_tpu", "_native", "build")
    proc = subprocess.run(
        [
            binary,
            gcs_host, str(gcs_port), raylet_host, str(raylet_port),
            function_key, cw.job_id.hex(),
            native_dir, arena_name, arena_name + "_idx", ref.hex(),
        ],
        capture_output=True, text=True, timeout=120,
    )
    sys.stderr.write(proc.stderr)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    xl = [ln for ln in proc.stdout.splitlines() if ln.startswith("XLANG_RESULT")]
    assert xl, proc.stdout
    decoded = xl[0][len("XLANG_RESULT "):]
    assert '"answer":42' in decoded
    assert '[1,2.5,"three",true,null]' in decoded
