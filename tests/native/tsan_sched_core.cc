// In-process TSAN hammer for the native scheduler core's resource ledger.
//
// Many threads race try_acquire/release against heartbeat-style
// node_upsert view resets, node add/remove, and placement-group pool
// prepare/return — the interleavings the raylet + GCS drive concurrently
// in production. ThreadSanitizer proves the locking; the hammer itself
// asserts the ledger's safety invariant: availability stays within
// [0, total] at every observation (the clamp path in sc_release exists
// exactly for the release-after-view-reset interleaving). Built with
// -fsanitize=thread by tests/test_native_races.py.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int sc_create();
void sc_destroy(int);
uint32_t sc_intern(int, const char*);
void sc_node_upsert(int, const char*, int, const uint32_t*, const double*, const double*);
void sc_node_remove(int, const char*);
int sc_try_acquire(int, const char*, int, const uint32_t*, const double*);
void sc_release(int, const char*, int, const uint32_t*, const double*);
void sc_pool_upsert(int, const char*, int, const uint32_t*, const double*);
void sc_pool_remove(int, const char*);
int sc_pool_exists(int, const char*);
int sc_pool_try_acquire(int, const char*, int, const uint32_t*, const double*);
void sc_pool_release(int, const char*, int, const uint32_t*, const double*);
double sc_node_avail(int, const char*, uint32_t);
int sc_cluster_feasibility(int, int, const uint32_t*, const double*);
}

static std::atomic<bool> g_stop{false};
static std::atomic<long> g_failures{0};
static std::atomic<long> g_acquires{0};

static const int kNodes = 4;
static char g_node_names[kNodes][8];

static void acquirer(int h, uint32_t cpu_idx, unsigned seed) {
  unsigned s = seed;
  double one = 1.0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    s = s * 1664525u + 1013904223u;
    const char* node = g_node_names[s % kNodes];
    if (sc_try_acquire(h, node, 1, &cpu_idx, &one)) {
      g_acquires++;
      // Hold briefly, then release (task lifetime).
      if ((s >> 4) & 3) sc_release(h, node, 1, &cpu_idx, &one);
      // else: leak-on-purpose path exercises the upsert clamp later.
    }
    double avail = sc_node_avail(h, node, cpu_idx);
    if (avail < -1e-9 || avail > 8.0 + 1e-9) {
      fprintf(stderr, "LEDGER OUT OF RANGE: %f\n", avail);
      g_failures++;
    }
  }
}

static void heartbeat(int h, uint32_t cpu_idx) {
  // View resets + node churn (GCS restart / node death paths).
  double total = 8.0;
  int i = 0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    const char* node = g_node_names[i % kNodes];
    sc_node_upsert(h, node, 1, &cpu_idx, &total, &total);
    if (i % 7 == 6) {
      sc_node_remove(h, node);
      sc_node_upsert(h, node, 1, &cpu_idx, &total, &total);
    }
    (void)sc_cluster_feasibility(h, 1, &cpu_idx, &total);
    i++;
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

static void pool_churner(int h, uint32_t cpu_idx, unsigned seed) {
  unsigned s = seed;
  double two = 2.0, one = 1.0;
  while (!g_stop.load(std::memory_order_relaxed)) {
    s = s * 1664525u + 1013904223u;
    char key[16];
    snprintf(key, sizeof(key), "pg%u", s % 3);
    sc_pool_upsert(h, key, 1, &cpu_idx, &two);
    if (sc_pool_try_acquire(h, key, 1, &cpu_idx, &one)) {
      sc_pool_release(h, key, 1, &cpu_idx, &one);
    }
    (void)sc_pool_exists(h, key);
    if ((s >> 6) & 1) sc_pool_remove(h, key);
  }
}

int main(int argc, char** argv) {
  int seconds = argc > 1 ? atoi(argv[1]) : 3;
  int h = sc_create();
  uint32_t cpu = sc_intern(h, "CPU");
  double total = 8.0;
  for (int i = 0; i < kNodes; i++) {
    snprintf(g_node_names[i], sizeof(g_node_names[i]), "n%d", i);
    sc_node_upsert(h, g_node_names[i], 1, &cpu, &total, &total);
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; t++) threads.emplace_back(acquirer, h, cpu, 99u * (t + 1));
  threads.emplace_back(heartbeat, h, cpu);
  threads.emplace_back(pool_churner, h, cpu, 7u);
  std::this_thread::sleep_for(std::chrono::seconds(seconds));
  g_stop = true;
  for (auto& th : threads) th.join();
  sc_destroy(h);
  if (g_failures.load() != 0) {
    fprintf(stderr, "failures=%ld\n", g_failures.load());
    return 1;
  }
  printf("HAMMER_OK acquires=%ld\n", g_acquires.load());
  return 0;
}
