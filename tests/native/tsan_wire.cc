// In-process TSAN hammer for the shared native wire structs
// (cpp/ray_tpu_wire.h) under the warm-lease teardown race: the r6 fast path
// made completion delivery a synchronous frame write on a warm connection,
// so the failure mode that matters is a peer RESETTING the connection while
// a frame is mid-write. Two phases:
//
//   1. socketpair: a writer thread streams length-prefixed frames
//      (send_all+frame — the worker's completion writer) while the reader
//      validates a few frames for integrity (length + fill byte: a torn
//      write surfaces as a mismatch) and then closes its end mid-stream.
//      send_all must surface EPIPE as an exception (MSG_NOSIGNAL), never a
//      process-killing SIGPIPE.
//   2. loopback TCP: blocking RpcClients issue calls against a server that
//      acks most requests but hard-resets every third connection mid-RPC;
//      call() must either return the valid response or throw — no hangs, no
//      races on teardown.
//
// Built with -fsanitize=thread by tests/test_native_races.py; any data race
// aborts the run (halt_on_error=1). Prints HAMMER_OK on a clean pass.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>

#include "ray_tpu_wire.h"

static std::atomic<uint64_t> g_frames{0}, g_resets{0}, g_calls{0};

static bool run_stream_round(int round) {
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return false;
  const size_t payload_len = (size_t)(round % 37) * 113 + 64;
  const char fill = (char)('a' + round % 26);
  bool ok = true;

  std::thread writer([&] {
    std::string payload(payload_len, fill);
    try {
      for (;;) {
        rtpu_wire::send_all(sv[0], rtpu_wire::frame(payload));
        g_frames.fetch_add(1, std::memory_order_relaxed);
      }
    } catch (const std::exception&) {
      // Peer reset mid-stream: the contract is an exception, not SIGPIPE.
      g_resets.fetch_add(1, std::memory_order_relaxed);
    }
  });

  int want = 1 + round % 17;
  for (int k = 0; k < want; ++k) {
    char hdr[4];
    if (!rtpu_wire::read_exact(sv[1], hdr, 4)) break;
    uint32_t len = ntohl(*(const uint32_t*)hdr);
    std::string body(len, '\0');
    if (!rtpu_wire::read_exact(sv[1], &body[0], len)) break;
    if (len != payload_len || body[0] != fill || body[len - 1] != fill) {
      printf("TORN FRAME round=%d len=%u want=%zu\n", round, len, payload_len);
      ok = false;
      break;
    }
  }
  close(sv[1]);  // connection reset under the concurrent writer
  writer.join();
  close(sv[0]);
  return ok;
}

int main(int argc, char** argv) {
  int seconds = argc > 1 ? atoi(argv[1]) : 3;
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(seconds);

  // ---- phase 1: frame write vs. connection reset ----
  int round = 0;
  while (std::chrono::steady_clock::now() < deadline) {
    if (!run_stream_round(round++)) return 3;
  }

  // ---- phase 2: RpcClient vs. a resetting server ----
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(lfd, 16) != 0) {
    printf("listen failed\n");
    return 2;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, (sockaddr*)&addr, &alen);
  int port = ntohs(addr.sin_port);

  std::atomic<bool> stop{false};
  std::thread server([&] {
    int nconn = 0;  // server-thread-local: decides which connections reset
    while (!stop.load(std::memory_order_relaxed)) {
      pollfd p{lfd, POLLIN, 0};
      if (poll(&p, 1, 50) <= 0) continue;
      int c = accept(lfd, nullptr, nullptr);
      if (c < 0) continue;
      ++nconn;
      for (;;) {
        char hdr[4];
        if (!rtpu_wire::read_exact(c, hdr, 4)) break;
        uint32_t len = ntohl(*(const uint32_t*)hdr);
        std::string body(len, '\0');
        if (!rtpu_wire::read_exact(c, &body[0], len)) break;
        if (nconn % 3 == 0) break;  // hard reset mid-RPC (no reply)
        try {
          Unpacker up(body);
          Value msg = up.decode();
          Packer pk;
          pk.array_header(4);
          pk.integer(1);  // RESPONSE
          pk.integer(msg.arr.at(1).i);
          pk.str("ping");
          pk.map_header(1);
          pk.str("ok");
          pk.boolean(true);
          rtpu_wire::send_all(c, rtpu_wire::frame(pk.out));
        } catch (const std::exception&) {
          break;
        }
      }
      close(c);
    }
  });

  deadline = std::chrono::steady_clock::now() + std::chrono::seconds(seconds);
  while (std::chrono::steady_clock::now() < deadline) {
    try {
      rtpu_wire::RpcClient client("127.0.0.1", port);
      Packer payload;
      payload.map_header(0);
      for (int k = 0; k < 4; ++k) {
        Value r = client.call("ping", payload.out);
        const Value* okf = r.get("ok");
        if (!okf || !okf->truthy()) {
          printf("BAD RESPONSE\n");
          return 3;
        }
        g_calls.fetch_add(1, std::memory_order_relaxed);
      }
    } catch (const std::exception&) {
      g_resets.fetch_add(1, std::memory_order_relaxed);  // reset surfaced
    }
  }
  stop.store(true);
  server.join();
  close(lfd);

  printf("HAMMER_OK frames=%llu calls=%llu resets=%llu\n",
         (unsigned long long)g_frames.load(), (unsigned long long)g_calls.load(),
         (unsigned long long)g_resets.load());
  return 0;
}
