// In-process TSAN hammer for the shm arena allocator.
//
// The arena's mutation surface (alloc/free with first-fit coalescing,
// used/largest_free stats) is mutex'd; this hammer drives it from many
// threads with churny sizes to let ThreadSanitizer prove the locking, and
// independently asserts the allocator's own invariants: no two live
// allocations overlap, payload bytes written by the owning thread read
// back intact (a coalescing bug hands the same bytes to two threads), and
// used() returns to zero after everything is freed. Built with
// -fsanitize=thread by tests/test_native_races.py.
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
int arena_create(const char*, uint64_t);
int arena_attach(const char*);
uint64_t arena_capacity(int);
void* arena_base(int);
uint64_t arena_alloc(int, uint64_t);
int arena_free(int, uint64_t);
uint64_t arena_used(int);
uint64_t arena_largest_free(int);
int arena_close(int, int);
}

static std::mutex g_live_mu;
static std::map<uint64_t, uint64_t> g_live;  // offset -> size (overlap oracle)
static std::atomic<bool> g_stop{false};
static std::atomic<long> g_failures{0};
static std::atomic<long> g_allocs{0};

static void check_no_overlap(uint64_t off, uint64_t size) {
  std::lock_guard<std::mutex> g(g_live_mu);
  auto next = g_live.lower_bound(off);
  if (next != g_live.end() && next->first < off + size) {
    fprintf(stderr, "OVERLAP: [%lu,+%lu) vs [%lu,+%lu)\n",
            (unsigned long)off, (unsigned long)size,
            (unsigned long)next->first, (unsigned long)next->second);
    g_failures++;
  }
  if (next != g_live.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second > off) {
      fprintf(stderr, "OVERLAP with prev\n");
      g_failures++;
    }
  }
  g_live[off] = size;
}

static void drop_live(uint64_t off) {
  std::lock_guard<std::mutex> g(g_live_mu);
  g_live.erase(off);
}

static void worker(int handle, int tid, unsigned seed) {
  uint8_t* base = (uint8_t*)arena_base(handle);
  unsigned s = seed;
  std::vector<std::pair<uint64_t, uint64_t>> mine;  // (offset, size)
  while (!g_stop.load(std::memory_order_relaxed)) {
    s = s * 1664525u + 1013904223u;
    uint64_t size = 64 + (s % 4096);
    uint64_t off = arena_alloc(handle, size);
    if (off != UINT64_MAX) {
      check_no_overlap(off, size);
      memset(base + off, (uint8_t)tid, size);
      mine.push_back({off, size});
      g_allocs++;
    }
    // Free roughly half the time (pressure + coalescing churn), always
    // verifying the payload still carries OUR byte first.
    if (!mine.empty() && ((s >> 8) & 1)) {
      auto [foff, fsize] = mine.back();
      mine.pop_back();
      for (uint64_t i = 0; i < fsize; i += 517) {
        if (base[foff + i] != (uint8_t)tid) {
          fprintf(stderr, "TORN PAYLOAD at %lu\n", (unsigned long)(foff + i));
          g_failures++;
          break;
        }
      }
      drop_live(foff);
      if (arena_free(handle, foff) != 0) {
        fprintf(stderr, "free failed\n");
        g_failures++;
      }
    }
  }
  for (auto [off, size] : mine) {
    drop_live(off);
    arena_free(handle, off);
  }
}

static void stats_reader(int handle) {
  while (!g_stop.load(std::memory_order_relaxed)) {
    (void)arena_used(handle);
    (void)arena_largest_free(handle);
  }
}

int main(int argc, char** argv) {
  int seconds = argc > 1 ? atoi(argv[1]) : 3;
  const char* name = "/tsan_arena_test";
  int h = arena_create(name, 32ull * 1024 * 1024);
  if (h < 0) {
    fprintf(stderr, "arena_create failed\n");
    return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < 6; t++) threads.emplace_back(worker, h, t + 1, 1234u * (t + 1));
  threads.emplace_back(stats_reader, h);
  std::this_thread::sleep_for(std::chrono::seconds(seconds));
  g_stop = true;
  for (auto& th : threads) th.join();
  if (arena_used(h) != 0) {
    fprintf(stderr, "LEAK: used=%lu after full free\n", (unsigned long)arena_used(h));
    g_failures++;
  }
  arena_close(h, 1);
  if (g_failures.load() != 0) {
    fprintf(stderr, "failures=%ld\n", g_failures.load());
    return 1;
  }
  printf("HAMMER_OK allocs=%ld\n", g_allocs.load());
  return 0;
}
