"""Test fixtures.

Analog of the reference's python/ray/tests/conftest.py: `ray_start_regular`
boots a real one-process-tree cluster per test; `ray_start_cluster` yields a
multi-raylet single-host Cluster (the reference's multi-node-without-a-cluster
trick, cluster_utils.py:99).

JAX is forced onto a virtual 8-device CPU mesh BEFORE first import so sharding
tests exercise real multi-device paths without TPU hardware.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAY_TPU_NUM_TPUS", "0")
# Worker subprocesses read this and re-apply it via jax.config.update — an
# environment sitecustomize may force jax_platforms to a TPU plugin, and a
# config update is the only override that wins (env vars are read before it).
os.environ["RAY_TPU_JAX_CONFIG_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pin this (test-runner) process to CPU before any test imports jax.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    try:
        yield cluster
    finally:
        cluster.shutdown()
