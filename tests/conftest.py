"""Test fixtures.

Analog of the reference's python/ray/tests/conftest.py: `ray_start_regular`
boots a real one-process-tree cluster per test; `ray_start_cluster` yields a
multi-raylet single-host Cluster (the reference's multi-node-without-a-cluster
trick, cluster_utils.py:99).

JAX is forced onto a virtual 8-device CPU mesh BEFORE first import so sharding
tests exercise real multi-device paths without TPU hardware.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAY_TPU_NUM_TPUS", "0")
# Worker subprocesses read this and re-apply it via jax.config.update — an
# environment sitecustomize may force jax_platforms to a TPU plugin, and a
# config update is the only override that wins (env vars are read before it).
os.environ["RAY_TPU_JAX_CONFIG_PLATFORMS"] = "cpu"
# Dynamic backup for the graftlint static affinity checks: @loop_only /
# @blocking markers (ray_tpu/_private/concurrency.py) install cheap runtime
# asserts when this is set BEFORE first import. Driven by the lease/worker
# test modules (test_leases, test_basic, test_actors, test_cancel, ...);
# enabled process-wide because marker behavior binds at import and the suite
# shares one interpreter — worker subprocesses inherit it, so the asserts
# also run inside every spawned worker's IO loop and exec thread.
os.environ.setdefault("RAY_TPU_DEBUG_AFFINITY", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pin this (test-runner) process to CPU before any test imports jax.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (`-m 'not slow'`); wide sweeps and "
        "long soak tests",
    )


def cpu_backend_lacks_multiprocess_collectives() -> bool:
    """True when multi-PROCESS XLA collectives cannot run in this
    environment: jax <= 0.4.x does not wire CPU cross-process collectives
    (gloo) into jax.distributed, so compiling a multiprocess computation on
    the CPU backend raises XlaRuntimeError "Multiprocess computations aren't
    implemented on the CPU backend". The identical code path bootstraps ICI
    worlds on real TPU (and GPU) backends, where it is exercised for real."""
    import jax

    if jax.default_backend() != "cpu":
        return False
    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return False
    return (major, minor) < (0, 5)


# Skip-with-reason guard for the known env-limited multiprocess-collective
# tests (3 in test_collective.py, 1 in test_train.py) so tier-1 output is
# clean instead of red on CPU-only images.
skip_without_multiprocess_collectives = pytest.mark.skipif(
    cpu_backend_lacks_multiprocess_collectives(),
    reason="env-limited: this jax/jaxlib's XLA CPU backend cannot run "
    "multiprocess collectives (raises 'Multiprocess computations aren't "
    "implemented on the CPU backend'); the same code path runs on real "
    "TPU/GPU backends",
)


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    try:
        yield cluster
    finally:
        cluster.shutdown()
