"""ray_tpu.dag tests (analog of the reference's python/ray/dag/tests/)."""

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


def test_function_dag(ray_start_regular):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x):
        return x * 2

    @ray_tpu.remote
    def combine(x, y):
        return x + y

    with InputNode() as inp:
        dag = combine.bind(a.bind(inp), b.bind(inp))

    # (5+1) + (5*2) = 16
    assert ray_tpu.get(dag.execute(5)) == 16
    # DAG is reusable
    assert ray_tpu.get(dag.execute(1)) == 4


def test_shared_upstream_node_runs_once(ray_start_regular):
    @ray_tpu.remote
    def source():
        import os
        import time

        return (os.getpid(), time.time_ns())

    @ray_tpu.remote
    def ident(x):
        return x

    src = source.bind()
    dag = MultiOutputNode([ident.bind(src), ident.bind(src)])
    left, right = ray_tpu.get(dag.execute())
    assert left == right  # one submission, shared ref


def test_dag_input_attributes(ray_start_regular):
    @ray_tpu.remote
    def add(x, y):
        return x + y

    with InputNode() as inp:
        dag = add.bind(inp["a"], inp["b"])

    assert ray_tpu.get(dag.execute({"a": 3, "b": 4})) == 7


def test_class_node_dag(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    with InputNode() as inp:
        counter = Counter.bind(10)
        dag = counter.add.bind(inp)

    assert ray_tpu.get(dag.execute(5)) == 15


def test_multi_output(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * 10

    with InputNode() as inp:
        dag = MultiOutputNode([f.bind(inp), f.bind(2)])

    refs = dag.execute(1)
    assert ray_tpu.get(refs) == [10, 20]


def test_options_on_node(ray_start_regular):
    @ray_tpu.remote
    def f():
        return "ok"

    dag = f.bind().options(name="dag-step")
    assert ray_tpu.get(dag.execute()) == "ok"
