"""Raw-frame wire path (rpc.py RAW_*, ISSUE 10): bit-exact round trips at
chunk boundaries, msgpack fallback negotiation, raw responses (RawResult),
and torn-connection mid-raw-frame recovery — the stream must reset cleanly,
never desynchronize.

Pure rpc-layer tests: one RpcServer + clients on the shared IO loop, no
cluster, so the whole module costs well under a second of tier-1 budget.
"""

import os
import socket
import time

import pytest

from ray_tpu._private.rpc import (
    RAW_CHUNK,
    EventLoopThread,
    RawResult,
    RpcClient,
    RpcServer,
    _pack_raw_header,
)

CHUNK = 64 * 1024  # stand-in chunk size; boundary math is what matters


@pytest.fixture()
def raw_server():
    """Server whose raw handler scatters chunks into a per-object bytearray
    (the arena stand-in) and whose fetch handler can answer raw."""
    server = RpcServer("raw-test")
    store: dict[str, bytearray] = {}

    def on_raw(frame):
        buf = store.setdefault(frame.oid, bytearray())
        end = frame.start + len(frame.payload)
        if len(buf) < end:
            buf.extend(b"\0" * (end - len(buf)))
        buf[frame.start : end] = frame.payload
        return {"ok": True, "got": len(frame.payload)}

    server.set_raw_handler(on_raw)

    async def rpc_fetch(req):
        data = bytes(store[req["object_id"]])
        start = req["start"]
        end = min(start + req["length"], len(data))
        if req.get("raw"):
            return RawResult(req["object_id"], start, memoryview(data)[start:end])
        return {"data": data[start:end]}

    async def rpc_ping(req):
        return {"pong": req.get("n", 0)}

    server.register("fetch", rpc_fetch)
    server.register("ping", rpc_ping)
    server.start("127.0.0.1", 0)
    try:
        yield server, store
    finally:
        server.stop()


def _push_raw(client, oid, payload, chunk=CHUNK):
    io = EventLoopThread.get()

    async def _run():
        acks = []
        for start in range(0, len(payload), chunk):
            fut = await client.astart_raw(
                RAW_CHUNK, oid, start, memoryview(payload)[start : start + chunk]
            )
            acks.append(await fut)
        return acks

    return io.run(_run(), timeout=30)


@pytest.mark.parametrize("size", [1, CHUNK - 1, CHUNK, CHUNK + 1, 3 * CHUNK + 7])
def test_raw_push_bit_exact_at_chunk_boundaries(raw_server, size):
    server, store = raw_server
    payload = os.urandom(size)
    client = RpcClient(server.address, label="raw-c")
    try:
        acks = _push_raw(client, f"obj-{size}", payload)
        assert all(a["ok"] for a in acks)
        assert bytes(store[f"obj-{size}"]) == payload
    finally:
        client.close()


@pytest.mark.parametrize("size", [1, CHUNK - 1, CHUNK, CHUNK + 1])
def test_raw_fetch_response_bit_exact(raw_server, size):
    """Server answers with a RawResult frame; the client-side sink receives
    the payload while the buffer view is valid and scatters it."""
    server, store = raw_server
    payload = os.urandom(size)
    store["src"] = bytearray(payload)
    client = RpcClient(server.address, label="raw-f")
    out = bytearray(size)
    io = EventLoopThread.get()

    async def _fetch(start, length):
        def sink(frame):
            out[frame.start : frame.start + len(frame.payload)] = frame.payload
            return {"len": len(frame.payload), "raw": True}

        return await client.acall(
            "fetch",
            {"object_id": "src", "start": start, "length": length, "raw": True},
            raw_sink=sink,
            retries=0,
        )

    try:
        got = 0
        for start in range(0, size, CHUNK):
            resp = io.run(_fetch(start, CHUNK), timeout=30)
            assert resp["raw"]
            got += resp["len"]
        assert got == size
        assert bytes(out) == payload
    finally:
        client.close()


def test_msgpack_fallback_when_sink_requested(raw_server):
    """A peer that answers a raw-capable request in msgpack (mixed-version /
    raw disabled) resolves the same future with the msgpack payload — the
    sink is simply never called."""
    server, store = raw_server
    store["src"] = bytearray(b"x" * 1000)
    client = RpcClient(server.address, label="raw-fb")
    io = EventLoopThread.get()
    called = []

    async def _fetch():
        # No "raw" key -> the handler takes the msgpack branch.
        return await client.acall(
            "fetch",
            {"object_id": "src", "start": 0, "length": 1000},
            raw_sink=lambda frame: called.append(frame),
            retries=0,
        )

    try:
        resp = io.run(_fetch(), timeout=30)
        assert resp["data"] == b"x" * 1000
        assert not called
    finally:
        client.close()


def test_raw_and_msgpack_interleave_on_one_connection(raw_server):
    """Raw frames and msgpack requests share the stream; ordering and seq
    bookkeeping must survive interleaving."""
    server, store = raw_server
    client = RpcClient(server.address, label="raw-mix")
    io = EventLoopThread.get()

    async def _mixed():
        results = []
        for i in range(10):
            fut = await client.astart_raw(
                RAW_CHUNK, "mix", i * 100, bytes([i]) * 100
            )
            ping = await client.astart_call("ping", {"n": i})
            results.append((await fut, await ping))
        return results

    try:
        results = io.run(_mixed(), timeout=30)
        assert all(ack["ok"] and pong["pong"] == i for i, (ack, pong) in enumerate(results))
        assert bytes(store["mix"]) == b"".join(bytes([i]) * 100 for i in range(10))
    finally:
        client.close()


def test_torn_connection_mid_raw_frame_resets_cleanly(raw_server):
    """Kill a connection halfway through a raw frame's payload: the server
    must tear the connection down (the length prefix scopes the frame) and
    keep serving fresh connections — no desynced stream, no poisoned state."""
    server, store = raw_server
    host, port = server.address
    sock = socket.create_connection((host, port))
    # A raw frame claiming 64 KiB of payload, but deliver only half of it.
    header = _pack_raw_header(RAW_CHUNK, 1, b"torn", 0, CHUNK)
    sock.sendall(header)
    sock.sendall(b"A" * (CHUNK // 2))
    time.sleep(0.1)
    sock.close()  # torn mid-frame

    # The partial frame must not have reached the handler...
    assert "torn" not in store
    # ...and the server still serves new connections and full transfers.
    client = RpcClient(server.address, label="raw-after-tear")
    try:
        payload = os.urandom(2 * CHUNK + 5)
        acks = _push_raw(client, "after", payload)
        assert all(a["ok"] for a in acks)
        assert bytes(store["after"]) == payload
        assert client.call("ping", {"n": 7})["pong"] == 7
    finally:
        client.close()


def test_oversize_raw_header_resets_connection(raw_server):
    """A raw header whose oid length overruns the frame is a protocol error:
    the server drops the connection instead of guessing at payload bounds."""
    server, store = raw_server
    host, port = server.address
    sock = socket.create_connection((host, port))
    # oid_len (1000) > frame length (20): header overruns.
    import struct

    bogus = (0x80000000 | 20).to_bytes(4, "big") + struct.pack(
        "<BBHIQ", RAW_CHUNK, 0, 1000, 1, 0
    ) + b"abcd"
    sock.sendall(bogus)
    sock.settimeout(5)
    # Server closes on the protocol error.
    assert sock.recv(1024) == b""
    sock.close()
    # Healthy clients unaffected.
    client = RpcClient(server.address, label="raw-after-bogus")
    try:
        assert client.call("ping", {"n": 1})["pong"] == 1
    finally:
        client.close()
