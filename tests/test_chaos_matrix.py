"""Cluster-level chaos matrix (ISSUE 13): workloads x seeded fault cells
over a real multi-raylet cluster, the partition_node/heal_node network
tear, and the pinning regression tests for the recovery bugs the matrix
exposed.

Layout (tier-1 budget): ONE module-scoped 3-node cluster hosts the matrix
cells; the full 7x5 sweep is marked `slow` and a 3-cell deterministic
subset (<30s) runs in tier-1. The partition/rejoin test builds its own
tiny cluster (it deliberately drives a node through declared-dead, which
must not pollute the shared cluster's GCS state).
"""

import os
import threading
import time

import pytest

import ray_tpu
from chaos_matrix import FAULTS, WORKLOAD_NAMES, assert_cell, run_cell
from ray_tpu._private import chaos
from ray_tpu._private.rpc import EventLoopThread

# Worker processes read config through RAY_TPU_* env only, so the knobs
# that bound recovery budgets must be env-set BEFORE the cluster spawns
# workers (the driver side gets them through _system_config as well).
_ENV_KNOBS = {
    "RAY_TPU_TASK_DONE_ACK_TIMEOUT_S": "2.0",
    "RAY_TPU_RPC_RETRY_BACKOFF_MAX_MS": "500",
    "RAY_TPU_LOST_TASK_SWEEP_INTERVAL_S": "4.0",
    "RAY_TPU_LOST_TASK_AGE_S": "6.0",
}


@pytest.fixture(scope="module")
def chaos_cluster():
    from ray_tpu.cluster_utils import Cluster

    saved = {k: os.environ.get(k) for k in _ENV_KNOBS}
    os.environ.update(_ENV_KNOBS)
    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=1, object_store_memory=96 * 1024 * 1024)
            for _ in range(3)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        ctx = {
            "cluster": cluster,
            "nodes": nodes,
            "io": EventLoopThread.get(),
        }
        # Warm the task path once so matrix cells measure recovery, not
        # first-worker spawn.
        @ray_tpu.remote
        def warm():
            return 1

        assert ray_tpu.get([warm.remote() for _ in range(3)], timeout=60) == [1, 1, 1]
        yield ctx
    finally:
        chaos.clear()
        cluster.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# tier-1 deterministic subset (<45s): four cells, four fault kinds —
# including ONE crash cell (LLM stream x kill: a seeded plan makes the
# streaming worker SIGKILL itself mid-stream; retry completes the stream).
# ---------------------------------------------------------------------------

_SUBSET = [
    ("pull", "reset"),
    ("broadcast", "dup"),
    ("actors", "delay"),
    ("llm", "kill"),
]


@pytest.mark.parametrize("workload,fault", _SUBSET, ids=[f"{w}x{f}" for w, f in _SUBSET])
def test_matrix_subset(chaos_cluster, workload, fault):
    # Kill cells pay for worker respawn + jax re-import per crash (up to
    # one per armed worker when retries land on armed peers), which is
    # load-sensitive on this 1-CPU box — wider budget, same contract.
    budget = 60.0 if fault == "kill" else 30.0
    res = run_cell(chaos_cluster, workload, fault, seed=13, budget_s=budget)
    assert_cell(res, budget_s=budget)
    if fault != "partition":
        assert res.injected > 0, "cell ran but nothing was injected"


# ---------------------------------------------------------------------------
# the full sweep (slow): every workload x every fault kind
# ---------------------------------------------------------------------------

_FULL = [
    (w, f)
    for w in WORKLOAD_NAMES
    for f in FAULTS
    if (w, f) not in _SUBSET  # already covered in tier-1
]


@pytest.mark.slow
@pytest.mark.parametrize("workload,fault", _FULL, ids=[f"{w}x{f}" for w, f in _FULL])
def test_matrix_full(chaos_cluster, workload, fault):
    res = run_cell(chaos_cluster, workload, fault, seed=13, budget_s=60.0)
    assert_cell(res, budget_s=60.0)


# ---------------------------------------------------------------------------
# partition_node / heal_node (satellite) + rejoin-after-dead (pinned bug)
# ---------------------------------------------------------------------------


def test_partition_node_short_tear_and_heal(chaos_cluster):
    """A short tear (under node_death_timeout_s): the severed node's links
    fail fast with ConnectionLost, node-local links stay up, and after
    heal_node the cluster is exactly as before (node never left ALIVE)."""
    cluster, nodes, io = (
        chaos_cluster["cluster"], chaos_cluster["nodes"], chaos_cluster["io"],
    )
    victim = nodes[1]
    cluster.partition_node(victim)
    try:
        # Severed: a peer's RPC to the victim fails fast (no 10s connect spin).
        t0 = time.monotonic()
        with pytest.raises(Exception):
            io.run(
                nodes[0]._peer(victim.node_id, victim.address).acall(
                    "get_state", {}, timeout=3, retries=0
                ),
                timeout=5,
            )
        assert time.monotonic() - t0 < 2.0
    finally:
        cluster.heal_node(victim)
    # Healed: the same call lands.
    st = io.run(
        nodes[0]._peer(victim.node_id, victim.address).acall(
            "get_state", {}, timeout=10
        ),
        timeout=15,
    )
    assert st["node_id"] == victim.node_id
    # And the GCS still lists every node ALIVE (tear was under the death
    # timeout).
    alive = sum(1 for n in cluster.gcs.nodes.values() if n["state"] == "ALIVE")
    assert alive == len(nodes)


def test_partition_outlives_death_timeout_then_rejoins():
    """PINNED RECOVERY BUG: a partition that outlives node_death_timeout_s
    gets the node declared DEAD; on heal the raylet's next heartbeat is
    answered with dead=True, and an IN-PROCESS raylet used to os._exit(1)
    — killing the whole host process (driver, GCS, and every sibling node
    with it). Now it REJOINS: re-registers under its node id, republishes
    its object locations, and serves traffic again."""
    from ray_tpu._private import config as config_mod
    from ray_tpu._private import worker_context
    from ray_tpu.cluster_utils import Cluster

    # This test builds its own cluster (declared-dead must not pollute the
    # shared module cluster's GCS); snapshot the module cluster's driver
    # context + config so they survive this cluster's init/shutdown.
    prev_cw = worker_context.get_core_worker_if_initialized()
    prev_cfg = config_mod._config
    cluster = Cluster(
        _system_config={"node_death_timeout_s": 1.2, "heartbeat_interval_s": 0.3}
    )
    try:
        nodes = [cluster.add_node(num_cpus=1) for _ in range(2)]
        cluster.connect()
        cluster.wait_for_nodes()
        victim = nodes[1]
        cluster.partition_node(victim)
        try:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if cluster.gcs.nodes[victim.node_id]["state"] == "DEAD":
                    break
                time.sleep(0.1)
            assert cluster.gcs.nodes[victim.node_id]["state"] == "DEAD"
        finally:
            cluster.heal_node(victim)
        # The raylet heartbeats into the dead verdict and rejoins (before
        # the fix: os._exit(1) here killed this very test process).
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if cluster.gcs.nodes[victim.node_id]["state"] == "ALIVE":
                break
            time.sleep(0.1)
        assert cluster.gcs.nodes[victim.node_id]["state"] == "ALIVE", (
            "severed node did not rejoin after heal"
        )
        # The rejoined cluster schedules work end to end.
        @ray_tpu.remote(max_retries=4)
        def ping():
            return os.getpid()

        assert ray_tpu.get([ping.remote() for _ in range(4)], timeout=60)
    finally:
        cluster.shutdown()
        with config_mod._config_lock:
            config_mod._config = prev_cfg
        if prev_cw is not None:
            worker_context.set_core_worker(prev_cw)


# ---------------------------------------------------------------------------
# runtime plan control (satellite): chaos_set_plan RPC + worker fan-out
# ---------------------------------------------------------------------------


def test_chaos_set_plan_broadcast_reaches_workers(chaos_cluster):
    """The raylet's chaos_set_plan RPC with broadcast=True installs the
    plan in its WORKER processes (verified from inside a task) and clears
    it the same way — faults are flippable mid-workload."""
    nodes, io = chaos_cluster["nodes"], chaos_cluster["io"]

    @ray_tpu.remote
    def plan_active():
        from ray_tpu._private import chaos as _c

        return _c.active() is not None

    # Ensure at least one worker is up, then fan the plan out on every node.
    assert ray_tpu.get(plan_active.remote(), timeout=30) is False
    reached = 0
    plan = {"rules": [{"kind": "delay", "method": "no_such_method", "times": 1}]}
    for n in nodes:
        resp = io.run(
            n.rpc_chaos_set_plan({"plan": plan, "seed": 5, "broadcast": True})
        )
        assert resp["ok"]
        reached += resp["workers_reached"]
    try:
        assert reached >= 1
        assert ray_tpu.get(plan_active.remote(), timeout=30) is True
    finally:
        for n in nodes:
            io.run(n.rpc_chaos_set_plan({"plan": None, "broadcast": True}))
        chaos.clear()  # the in-process raylet handler also set the driver plan
    assert ray_tpu.get(plan_active.remote(), timeout=30) is False


# ---------------------------------------------------------------------------
# pinned recovery bugs (found by the matrix, fixed in this PR)
# ---------------------------------------------------------------------------


def test_silently_dropped_task_done_heals_within_ack_budget(chaos_cluster):
    """PINNED RECOVERY BUG: a task_done/tasks_done one-way frame lost
    WITHOUT a connection reset (receiver drop; chaos drop models it) used
    to hang the owner's get() forever on the lease path — the worker's
    send_nowait future never resolves, nothing re-delivered, and the
    owner's lease probe pings the WORKER, which is alive. The ack watchdog
    (task_done_ack_timeout_s) now re-delivers through the acked retrying
    path; the owner drops the duplicate by cid."""
    nodes, io = chaos_cluster["nodes"], chaos_cluster["io"]

    @ray_tpu.remote
    def work():
        return "done"

    # Warm a worker, then make every worker drop its next completion frame.
    assert ray_tpu.get(work.remote(), timeout=30) == "done"
    worker_plan = {
        "rules": [
            {"kind": "drop", "method": ["tasks_done", "task_done"], "times": 1}
        ]
    }
    pushed = 0
    for n in nodes:
        for w in n.workers.values():
            if w.client is not None and w.state not in ("starting", "dead"):
                try:
                    io.run(w.client.acall(
                        "chaos_set_plan", {"plan": worker_plan}, timeout=5, retries=0
                    ), timeout=6)
                    pushed += 1
                except Exception:
                    pass
    assert pushed >= 1
    try:
        t0 = time.monotonic()
        # Ack timeout is 2s (module env): the dropped frame re-delivers in
        # ~2s — far under the 15s lease failover / lost-task sweep, and not
        # the forever-hang it used to be.
        assert ray_tpu.get(work.remote(), timeout=30) == "done"
        assert time.monotonic() - t0 < 12.0
    finally:
        for n in nodes:
            for w in n.workers.values():
                if w.client is not None and w.state not in ("starting", "dead"):
                    try:
                        io.run(w.client.acall(
                            "chaos_set_plan", {"plan": None}, timeout=5, retries=0
                        ), timeout=6)
                    except Exception:
                        pass


def test_duplicated_actor_call_executes_once(chaos_cluster):
    """PINNED RECOVERY BUG: a duplicated actor_call frame (at-least-once
    wire; chaos dup models it) used to EXECUTE THE METHOD TWICE — actor
    state mutated twice per call. The worker now tombstones received task
    ids and answers duplicates from its result cache."""
    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    a = Acc.remote()
    try:
        assert ray_tpu.get(a.bump.remote(), timeout=30) == 1  # warm
        chaos.install(
            {"rules": [{"kind": "dup", "method": "actor_call", "times": 2}]},
            seed=3,
        )
        try:
            assert ray_tpu.get(a.bump.remote(), timeout=30) == 2
            assert ray_tpu.get(a.bump.remote(), timeout=30) == 3
        finally:
            chaos.clear()
        # State advanced exactly once per call despite duplicated frames.
        assert ray_tpu.get(a.bump.remote(), timeout=30) == 4
    finally:
        ray_tpu.kill(a)


def test_dropped_actor_call_heals_by_probe_resend(chaos_cluster):
    """PINNED RECOVERY BUG: an actor_call frame silently lost (connection
    up, no reset) used to park the call FOREVER — no timeout, no sweep
    covers actor calls. The owner now probes the worker over the same FIFO
    connection after each unacked interval; 'never received' proves loss
    and triggers a deduped resend."""
    @ray_tpu.remote
    class Echo:
        def ping(self, x):
            return x

    a = Echo.remote()
    try:
        assert ray_tpu.get(a.ping.remote(1), timeout=30) == 1  # warm
        chaos.install(
            {"rules": [{"kind": "drop", "method": "actor_call", "times": 1}]},
            seed=4,
        )
        try:
            t0 = time.monotonic()
            # Ack interval is 2s (module env): loss heals in ~2-4s, not never.
            assert ray_tpu.get(a.ping.remote(2), timeout=30) == 2
            assert time.monotonic() - t0 < 15.0
        finally:
            chaos.clear()
    finally:
        ray_tpu.kill(a)


def test_lost_register_actor_reply_is_idempotent(chaos_cluster):
    """PINNED RECOVERY BUG: actor registration had no ack bound — a lost
    register_actor reply parked .remote() forever — and the naive retry
    would have scheduled a SECOND creation (the GCS handler re-ran its
    body). Now the retry is served the remembered outcome and exactly one
    actor serves calls."""
    @ray_tpu.remote
    class One:
        def who(self):
            return os.getpid()

    chaos.install(
        {"rules": [{"kind": "drop", "method": "register_actor", "side": "resp",
                    "times": 1}]},
        seed=6,
    )
    try:
        t0 = time.monotonic()
        a = One.remote()  # first reply dropped; bounded retry lands
        pids = {ray_tpu.get(a.who.remote(), timeout=30) for _ in range(3)}
        assert len(pids) == 1
        assert time.monotonic() - t0 < 40.0
    finally:
        chaos.clear()
        ray_tpu.kill(a)


def test_push_commit_reply_lost_retry_serves_remembered_outcome(chaos_cluster):
    """Partition/reset during push_commit: the first commit reply is
    dropped (side=resp), the sender's bounded retry must be served the
    REMEMBERED outcome (raylet._commit_results) — the push completes and
    the replica is intact, instead of a guessed verdict or a hang."""
    import numpy as np

    from chaos_matrix import _free_all, _oid, _seal_raw

    nodes, io = chaos_cluster["nodes"], chaos_cluster["io"]
    data = np.random.default_rng(99).integers(0, 255, 2 * 1024 * 1024,
                                              dtype=np.uint8).tobytes()
    oid = _oid("commitretry")
    chaos.install(
        {"rules": [{"kind": "drop", "method": "push_commit", "side": "resp",
                    "times": 1}]},
        seed=2,
    )
    try:
        _seal_raw(io, nodes[0], oid, data)
        resp = io.run(
            nodes[0].push_manager.push(
                oid, nodes[1].node_id, nodes[1].address, timeout=8.0
            ),
            timeout=30,
        )
        assert resp["ok"], resp
        offset, size = io.run(nodes[1].store.get(oid))
        try:
            assert bytes(nodes[1].arena.read(offset, size)) == data
        finally:
            nodes[1].store.release(oid)
    finally:
        chaos.clear()
        _free_all(nodes, oid)
