"""Tests for LeelaChessZero (two-player zero-sum AlphaZero, Lc0 heads).

Mirrors the reference's leela_chess_zero tests in spirit on the in-tree
TicTacToe board: the zero-sum search must be sound (sign-flipped backups
find the tactical move), the value/policy/moves-left heads must train, and
search+net must dominate a random player.
"""

import numpy as np
import pytest

from ray_tpu.rllib.env.board_env import TicTacToeEnv


def test_tictactoe_env_protocol():
    env = TicTacToeEnv()
    obs = env.reset()
    assert obs.shape == (9,) and not obs.any()
    assert env.legal_actions().all()
    # X plays 0, O plays 3, X plays 1, O plays 4, X plays 2 -> X wins row 0.
    for a, expect_done in ((0, False), (3, False), (1, False), (4, False)):
        obs, r, done = env.step(a)
        assert r == 0.0 and done is expect_done
    obs, r, done = env.step(2)
    assert done and r == 1.0  # reward to the mover (X)
    # State cloning round-trips.
    env2 = TicTacToeEnv()
    env2.reset()
    env2.set_state(env.get_state())
    assert np.array_equal(env2.observe(), env.observe())


def test_zero_sum_mcts_finds_winning_move():
    """With a uniform prior and no training, sign-flipped PUCT must still
    find an immediate winning move (pure search soundness)."""
    from ray_tpu.rllib.algorithms.leela_chess_zero.leela_chess_zero import ZeroSumMCTS

    env = TicTacToeEnv()
    env.reset()
    # X: 0, O: 3, X: 1, O: 4 -> X to move, 2 wins immediately.
    for a in (0, 3, 1, 4):
        env.step(a)

    def uniform_predict(obs, legal):
        p = legal.astype(np.float32)
        return p / p.sum(), 0.0

    mcts = ZeroSumMCTS(env, uniform_predict, num_sims=200,
                       dirichlet_eps=0.0, rng=np.random.default_rng(0))
    pi, _ = mcts.search(temperature=1e-7)
    assert pi.argmax() == 2, f"search missed the winning move: {pi}"


def test_lc0_self_play_trains_and_beats_random():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import LeelaChessZeroConfig

    cfg = (
        LeelaChessZeroConfig()
        .environment(TicTacToeEnv)
        .training(
            lr=2e-3, num_sims=25, games_per_iter=8, sgd_iters=6,
            train_batch_size=128, model_hiddens=(64, 64),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        v_losses = []
        for _ in range(6):
            r = algo.step()
            if "value_loss" in r:
                v_losses.append(r["value_loss"])
        assert v_losses, "network never trained (replay too small?)"
        assert v_losses[-1] < v_losses[0], f"value head not learning: {v_losses}"
        assert np.isfinite(r["moves_left_loss"])

        # Search + trained net vs a random player: never lose across 20
        # games (tic-tac-toe is a draw under correct play; random blunders).
        rng = np.random.default_rng(1)
        losses = 0
        for g in range(20):
            env = algo.env
            env.reset()
            agent_first = g % 2 == 0
            agent_turn = agent_first
            while True:
                if agent_turn:
                    a = algo.compute_single_action()
                else:
                    legal = np.flatnonzero(env.legal_actions())
                    a = int(rng.choice(legal))
                _, reward, done = env.step(a)
                if done:
                    if reward > 0 and not agent_turn:
                        losses += 1
                    break
                agent_turn = not agent_turn
        assert losses == 0, f"trained lc0 lost {losses}/20 games to random"
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()
