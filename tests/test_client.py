"""Thin-client (Ray Client analog) tests.

Modeled on the reference's python/ray/tests/test_client.py: tasks, objects,
actors, named actors, errors — all through the client proxy, with no local
node in the client process.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def client_connection():
    """In-process head + client server; the test then swaps the real driver
    out of worker_context and connects a thin client in its place."""
    from ray_tpu._private import worker_context
    from ray_tpu.util.client import ClientServer, connect

    real_cw = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    server = ClientServer(real_cw, host="127.0.0.1", port=0)
    worker_context.set_core_worker(None)  # simulate a fresh client process
    ctx = connect("ray_tpu://%s:%d" % server.address)
    yield ctx
    ctx.disconnect()
    server.stop()
    worker_context.set_core_worker(real_cw)
    ray_tpu.shutdown()


def test_client_tasks_and_objects(client_connection):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3
    # refs as args across the proxy
    r1 = add.remote(10, 20)
    assert ray_tpu.get(add.remote(r1, 5)) == 35
    # put/get numpy payload
    arr = np.arange(1000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    assert np.array_equal(out, arr)
    # wait
    ready, not_ready = ray_tpu.wait([add.remote(1, 1)], num_returns=1, timeout=30)
    assert len(ready) == 1 and not not_ready


def test_client_actors(client_connection):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(100)
    assert ray_tpu.get(c.inc.remote()) == 101
    assert ray_tpu.get(c.inc.remote(9)) == 110
    ray_tpu.kill(c)


def test_client_named_actor_and_nodes(client_connection):
    @ray_tpu.remote(name="client-named")
    class A:
        def ping(self):
            return "pong"

    A.remote()
    h = ray_tpu.get_actor("client-named")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    assert len(ray_tpu.nodes()) == 1
    assert ray_tpu.cluster_resources()["CPU"] == 4


def test_client_nested_refs(client_connection):
    """ObjectRefs nested inside returned values are fetchable client-side,
    and releasing a deserialized copy never unpins a live original."""

    @ray_tpu.remote
    def make_refs():
        return [ray_tpu.put(41), ray_tpu.put(43)]

    inner = ray_tpu.get(make_refs.remote())
    assert [ray_tpu.get(r) for r in inner] == [41, 43]
    # Copy + drop: the original must stay fetchable.
    import copy

    dup = copy.copy(inner[0])
    del dup
    import gc

    gc.collect()
    assert ray_tpu.get(inner[0]) == 41


def test_client_task_error_propagates(client_connection):
    @ray_tpu.remote
    def boom():
        raise ValueError("client boom")

    with pytest.raises(Exception, match="client boom"):
        ray_tpu.get(boom.remote())
