"""Thin-client (Ray Client analog) tests.

Modeled on the reference's python/ray/tests/test_client.py: tasks, objects,
actors, named actors, errors — all through the client proxy, with no local
node in the client process.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def client_connection():
    """In-process head + client server; the test then swaps the real driver
    out of worker_context and connects a thin client in its place."""
    from ray_tpu._private import worker_context
    from ray_tpu.util.client import ClientServer, connect

    real_cw = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    server = ClientServer(real_cw, host="127.0.0.1", port=0)
    worker_context.set_core_worker(None)  # simulate a fresh client process
    ctx = connect("ray_tpu://%s:%d" % server.address)
    yield ctx
    ctx.disconnect()
    server.stop()
    worker_context.set_core_worker(real_cw)
    ray_tpu.shutdown()


def test_client_tasks_and_objects(client_connection):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3
    # refs as args across the proxy
    r1 = add.remote(10, 20)
    assert ray_tpu.get(add.remote(r1, 5)) == 35
    # put/get numpy payload
    arr = np.arange(1000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    assert np.array_equal(out, arr)
    # wait
    ready, not_ready = ray_tpu.wait([add.remote(1, 1)], num_returns=1, timeout=30)
    assert len(ready) == 1 and not not_ready


def test_client_actors(client_connection):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(100)
    assert ray_tpu.get(c.inc.remote()) == 101
    assert ray_tpu.get(c.inc.remote(9)) == 110
    ray_tpu.kill(c)


def test_client_named_actor_and_nodes(client_connection):
    @ray_tpu.remote(name="client-named")
    class A:
        def ping(self):
            return "pong"

    A.remote()
    h = ray_tpu.get_actor("client-named")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    assert len(ray_tpu.nodes()) == 1
    assert ray_tpu.cluster_resources()["CPU"] == 4


def test_client_nested_refs(client_connection):
    """ObjectRefs nested inside returned values are fetchable client-side,
    and releasing a deserialized copy never unpins a live original."""

    @ray_tpu.remote
    def make_refs():
        return [ray_tpu.put(41), ray_tpu.put(43)]

    inner = ray_tpu.get(make_refs.remote())
    assert [ray_tpu.get(r) for r in inner] == [41, 43]
    # Copy + drop: the original must stay fetchable.
    import copy

    dup = copy.copy(inner[0])
    del dup
    import gc

    gc.collect()
    assert ray_tpu.get(inner[0]) == 41


def test_client_task_error_propagates(client_connection):
    @ray_tpu.remote
    def boom():
        raise ValueError("client boom")

    with pytest.raises(Exception, match="client boom"):
        ray_tpu.get(boom.remote())


def test_client_large_object_streams_both_ways(client_connection):
    """Values above the data-channel threshold transfer as bounded chunks
    (reference: dataservicer chunking), transparently to the caller."""
    big = np.arange(400_000, dtype=np.float64)  # ~3.2 MB serialized
    ref = ray_tpu.put(big)
    back = ray_tpu.get(ref)
    assert np.array_equal(back, big)

    @ray_tpu.remote
    def double(x):
        return x * 2

    out = ray_tpu.get(double.remote(ref))
    assert np.array_equal(out, big * 2)


def test_client_reconnects_transparently(client_connection):
    """A mid-flight connection loss is retried on a fresh connection and
    the request replayed; the session (pinned refs) survives on the
    server. (A clean socket close heals inside the transport; a LOST
    in-flight call surfaces ConnectionLost and exercises this layer.)"""
    from ray_tpu._private import worker_context
    from ray_tpu._private.rpc import ConnectionLost

    cw = worker_context.get_core_worker_if_initialized()
    ref = ray_tpu.put({"k": 1})
    failed = {"n": 0}
    orig_call = cw._rpc.call

    def dies_mid_flight(method, payload, timeout=None):
        failed["n"] += 1
        raise ConnectionLost("injected: connection lost mid-call")

    cw._rpc.call = dies_mid_flight  # replaced wholesale on reconnect
    assert ray_tpu.get(ref) == {"k": 1}
    # >= 1: a queued ref-release piggyback may hit the injected failure
    # first (it is caught and re-queued, also through this path).
    assert failed["n"] >= 1
    assert cw._reconnects >= 1
    assert cw._rpc.call is not dies_mid_flight
    del orig_call


def test_client_replayed_mutation_is_at_most_once(client_connection):
    """The same req_id re-sent after a reconnect must NOT re-run the side
    effect: the server's session response cache replays the original
    answer (at-most-once semantics for mutating calls)."""
    from ray_tpu._private import serialization, worker_context

    cw = worker_context.get_core_worker_if_initialized()
    payload = {
        "client_id": cw._client_id,
        "req_id": cw._next_req_id(),
        "value": serialization.dumps("only-once"),
    }
    r1 = cw._rpc.call("client_put", dict(payload))
    r2 = cw._rpc.call("client_put", dict(payload))
    assert r1["id"] == r2["id"], "replay created a second object"


def test_client_streaming_generator(client_connection):
    """num_returns="streaming" through the proxy (reference:
    util/client/worker.py:81 streaming generators): iteration overlaps the
    remote producer, refs resolve via get, errors and ends propagate."""
    import time

    @ray_tpu.remote
    def gen(n):
        import time as _t

        for i in range(n):
            _t.sleep(0.05)
            yield i * i

    g = gen.options(num_returns="streaming").remote(5)
    seen = []
    for ref in g:
        seen.append(ray_tpu.get(ref))
    assert seen == [0, 1, 4, 9, 16]

    # mid-stream consumption overlaps production: the first item arrives
    # long before the producer (1s of sleeps) could have finished
    @ray_tpu.remote
    def slow_gen():
        import time as _t

        for i in range(10):
            _t.sleep(0.1)
            yield i

    g2 = slow_gen.options(num_returns="streaming").remote()
    t0 = time.time()
    first = ray_tpu.get(next(iter(g2)))
    assert first == 0 and time.time() - t0 < 0.9
    rest = [ray_tpu.get(r) for r in g2]
    assert rest == list(range(1, 10))

    # producer errors surface from the generator
    @ray_tpu.remote
    def bad_gen():
        yield 1
        raise RuntimeError("producer boom")

    g3 = bad_gen.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g3)) == 1
    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(next(g3))


def test_client_data_channel_backpressure(client_connection):
    """A consumer that opens download streams faster than it drains them is
    BLOCKED by the per-session buffer cap instead of growing server memory
    (then proceeds once the backlog drains)."""
    import threading
    import time

    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    # Drive the chunk protocol by hand under a second client id so the
    # fixture client's own session stays clean.
    blob_src = np.random.RandomState(0).bytes(3 * 1024 * 1024)
    r1 = cw.put(np.frombuffer(blob_src, dtype=np.uint8))
    r2 = cw.put(np.frombuffer(blob_src, dtype=np.uint8))

    rpc = cw._rpc
    cid = "bp-test-client"
    resp1 = rpc.call("client_get", {"client_id": cid, "ids": [r1.hex()],
                                    "owners": [None], "req_id": cid + ":1"})
    assert "stream" in resp1, resp1.keys()

    # Artificially shrink the cap AFTER stream 1 is buffered.
    # (the server object lives in the fixture module scope; fetch via gc)
    import gc

    from ray_tpu.util.client.server import ClientServer

    servers = [o for o in gc.get_objects() if isinstance(o, ClientServer)]
    assert servers, "client server not found"
    server = servers[0]
    old_cap = server.max_stream_bytes
    server.max_stream_bytes = 4 * 1024 * 1024  # stream1 (~3MiB) + stream2 won't fit
    try:
        got2 = {}

        def second_get():
            got2["resp"] = rpc.call(
                "client_get",
                {"client_id": cid, "ids": [r2.hex()], "owners": [None],
                 "req_id": cid + ":2"},
                timeout=120,
            )

        t = threading.Thread(target=second_get)
        t.start()
        time.sleep(1.0)
        assert t.is_alive(), "second get should be blocked on the cap"
        #

        # drain stream 1 fully and ack; the blocked get should now proceed
        off = 0
        while True:
            c = rpc.call("client_get_chunk", {"client_id": cid, "stream": resp1["stream"], "offset": off})
            off += len(c["data"])
            if c["done"]:
                break
        rpc.call("client_stream_done", {"client_id": cid, "stream": resp1["stream"]})
        t.join(timeout=60)
        assert not t.is_alive(), "second get never unblocked after drain"
        assert "stream" in got2["resp"]
        rpc.call("client_stream_done", {"client_id": cid, "stream": got2["resp"]["stream"]})
    finally:
        server.max_stream_bytes = old_cap
