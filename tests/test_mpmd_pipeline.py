"""MPMD pipeline over compiled graphs (ISSUE 12).

Covers the descriptor channel plane (KIND_DEVICE envelopes through channel
slots, payloads streamed out of band — experimental/channel/
device_envelope.py) and the MPMD pipeline built on it (parallel/
mpmd_pipeline.py): zero host-store copies of activations, bit-exact parity
vs the single-controller ``pipeline_apply``, device-resident driver inputs
routed as descriptor slots instead of silently msgpack-serialized through
the ring, the doorbell short-circuiting the configurable re-poll backoff,
and the chaos path — SIGKILL of one stage surfaces a typed error naming it
and every channel slot / device buffer / pinned payload is reclaimed.
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.exceptions import ActorDiedError


def _drain_resident(stats_fn, target: int, timeout: float = 30.0) -> dict:
    """Pin releases and loop-exit reclaims are asynchronous (one-way frames,
    thread joins): poll the counters down instead of sleeping blind."""
    deadline = time.monotonic() + timeout
    st = stats_fn()
    while time.monotonic() < deadline:
        st = stats_fn()
        if st["resident_count"] <= target:
            return st
        time.sleep(0.1)
    return st


def test_doorbell_wakes_backed_off_reader():
    """Satellite: channel_poll_interval_ms is a RayConfig knob and the
    doorbell path never waits a full poll interval. With the fallback
    re-poll cap cranked to 2 s, an idle resident loop's reader is deep in
    its exponential backoff — yet a fresh execute() completes in far less
    than one poll interval, because the producer's doorbell (or the device
    payload's deposit) sets the reader's gate event immediately."""
    os.environ["RAY_TPU_CHANNEL_POLL_INTERVAL_MS"] = "2000"
    try:
        ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        assert cw.cfg.channel_poll_interval_ms == 2000

        @ray_tpu.remote
        class Inc:
            def work(self, x):
                return x + 1

        with InputNode() as inp:
            dag = Inc.bind().work.bind(Inc.bind().work.bind(inp))
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(0).get() == 2  # warm the loops
            # Let every blocked reader back off to the 2 s cap...
            time.sleep(1.2)
            # ...then a full round trip must be doorbell-paced, not
            # poll-paced: 2 stages x 2 s would be >= 4 s on poll alone.
            t0 = time.monotonic()
            assert compiled.execute(5).get(timeout=30) == 7
            assert time.monotonic() - t0 < 1.5
        finally:
            compiled.teardown()
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_CHANNEL_POLL_INTERVAL_MS", None)


@pytest.fixture(scope="module")
def pipeline_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=192 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


@ray_tpu.remote(tensor_transport="collective")
class DeviceStage:
    def work(self, x):
        import jax.numpy as jnp

        return jnp.tanh(x) + 1.0

    def devobj_stats(self):
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()

    def pid(self):
        return os.getpid()


def test_device_descriptor_stream_zero_host_copy(pipeline_cluster):
    """Tentpole core: a tensor_transport actor's jax.Array result crosses a
    compiled-graph edge as a ~300 B descriptor slot while the payload rides
    the p2p direct mailbox — the host object store sees ZERO activation
    objects, the producer's pin watermark trails the ring by <= 2 slots,
    and teardown reclaims every payload (no leaked device buffers)."""
    import jax.numpy as jnp

    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    s1, s2 = DeviceStage.bind(), DeviceStage.bind()
    with InputNode() as inp:
        dag = s2.work.bind(s1.work.bind(inp))
    compiled = dag.experimental_compile()
    h1, h2 = s1.resolve_actor_handle(), s2.resolve_actor_handle()
    try:
        store0 = cw.raylet.call("get_state")["store"]["num_objects"]
        x = jnp.arange(8.0, dtype=jnp.float32)
        expected = np.tanh(np.tanh(np.arange(8.0)) + 1.0) + 1.0
        iters = 6
        for _ in range(iters):
            out = compiled.execute(x).get(timeout=60)
            np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)
        assert cw.raylet.call("get_state")["store"]["num_objects"] == store0

        st = ray_tpu.get(h1.devobj_stats.remote(), timeout=30)
        # Every iteration eager-pushed stage1's activation out of band...
        assert st["chan_sends"] >= iters, st
        # ...no resolution fell back to a host-store copy...
        assert st["transfers_host"] == 0, st
        # ...and ring-advance reaping keeps the pin watermark at <= 2
        # in-flight payloads (read_count - 2 is provably-done).
        assert st["resident_count"] <= 2, st
    finally:
        compiled.teardown()
    st = _drain_resident(
        lambda: ray_tpu.get(h1.devobj_stats.remote(), timeout=30), target=0
    )
    assert st["resident_count"] == 0, st
    # Free the module cluster's CPUs for the pipeline builds below.
    ray_tpu.kill(h1)
    ray_tpu.kill(h2)


def test_driver_device_input_routed_as_descriptor(pipeline_cluster):
    """Satellite: execute() fed a device-resident jax.Array no longer
    msgpack-serializes it silently through the host ring — the driver is
    the holder and the input crosses as a descriptor slot (chan_sends
    counts it; the store object count stays flat), and teardown reclaims
    the driver's payload scope."""
    import jax.numpy as jnp

    from ray_tpu._private import worker_context
    from ray_tpu.experimental.device_object import device_object_stats

    cw = worker_context.get_core_worker()

    @ray_tpu.remote
    class SumStage:
        def total(self, x):
            return float(x.sum())

    node = SumStage.bind()
    with InputNode() as inp:
        dag = node.total.bind(inp)
    compiled = dag.experimental_compile()
    base = device_object_stats()
    try:
        store0 = cw.raylet.call("get_state")["store"]["num_objects"]
        x = jnp.ones((16,), dtype=jnp.float32)
        for _ in range(4):
            assert compiled.execute(x).get(timeout=60) == 16.0
        st = device_object_stats()
        assert st["chan_sends"] - base["chan_sends"] >= 4, (base, st)
        assert cw.raylet.call("get_state")["store"]["num_objects"] == store0
    finally:
        compiled.teardown()
    # The driver's payload scope reclaims at teardown (resident counts are
    # vs the pre-test base — this pytest process may hold other device
    # objects from earlier modules).
    st = _drain_resident(device_object_stats, target=base["resident_count"])
    assert st["resident_count"] <= base["resident_count"], (base, st)
    ray_tpu.kill(node.resolve_actor_handle())


def test_unserializable_result_is_per_iteration_error(pipeline_cluster):
    """A stage return value the serializer rejects becomes THAT iteration's
    TaskError (the DAG keeps serving) — not a resident-loop crash that
    wedges every subsequent get()."""
    import threading

    from ray_tpu.exceptions import TaskError

    @ray_tpu.remote
    class Sometimes:
        def work(self, x):
            if x == 1:
                return threading.Lock()  # pickle refuses
            return x

    node = Sometimes.bind()
    with InputNode() as inp:
        dag = node.work.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get(timeout=30) == 0
        with pytest.raises(TaskError):
            compiled.execute(1).get(timeout=30)
        assert compiled.execute(2).get(timeout=30) == 2  # loop survived
    finally:
        compiled.teardown()
    ray_tpu.kill(node.resolve_actor_handle())


def _stage_fn(w, h):
    import jax.numpy as jnp

    return jnp.tanh(h @ w)


def test_mpmd_parity_bitexact_vs_pipeline_apply(pipeline_cluster):
    """Acceptance oracle: the MPMD pipeline's outputs are BIT-EXACT vs the
    single-controller pipeline_apply on identical stacked params/inputs —
    at M == S and at M > S — and the per-stage loop stats expose the
    measured bubble."""
    import jax

    from ray_tpu.parallel.mesh import MeshConfig, create_mesh
    from ray_tpu.parallel.mpmd_pipeline import mpmd_pipeline
    from ray_tpu.parallel.pipeline import pipeline_apply

    n_stages, d = 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(1), (n_stages, d, d)) * 0.3
    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    pipe = mpmd_pipeline(_stage_fn, ws, num_microbatches=4)
    try:
        for M in (4, 8):  # M == S and M > S
            x = jax.random.normal(jax.random.PRNGKey(M), (M * 2, d))
            ref = np.asarray(
                pipeline_apply(_stage_fn, ws, x, mesh, num_microbatches=M)
            )
            out = np.asarray(pipe.apply(x, num_microbatches=M))
            assert np.array_equal(out, ref), f"M={M}: MPMD != pipeline_apply"
        # Non-divisible batches fail loudly, like pipeline_apply.
        bad = jax.random.normal(jax.random.PRNGKey(9), (10, d))
        with pytest.raises(AssertionError, match="not divisible"):
            pipe.apply(bad, num_microbatches=4)

        pipe.reset_stage_stats()
        x = jax.random.normal(jax.random.PRNGKey(3), (16, d))
        pipe.apply(x, num_microbatches=8)
        rows = pipe.stage_stats()
        assert len(rows) == n_stages
        assert all(r["iters"] >= 8 for r in rows), rows
        assert 0.0 <= pipe.bubble_fraction() < 1.0
    finally:
        pipe.teardown()


def test_mpmd_chaos_sigkill_stage_reclaims_everything(pipeline_cluster):
    """Acceptance: SIGKILL one stage mid-schedule. The in-flight and
    subsequent microbatches surface a typed ActorDiedError naming the dead
    stage (descriptor waits abort on the poison, they don't hang out the
    grace window), and teardown reclaims the full data plane: channel
    slots back to the arena, driver payload scope freed, surviving stages'
    pinned payloads freed — counters return to baseline."""
    import jax

    from ray_tpu._private import worker_context
    from ray_tpu.experimental.device_object import device_object_stats
    from ray_tpu.parallel.mpmd_pipeline import mpmd_pipeline

    cw = worker_context.get_core_worker()
    n_stages, d = 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(1), (n_stages, d, d)) * 0.3
    driver_base = device_object_stats()["resident_count"]
    chan0 = cw.raylet.call("get_state")["store"]["num_channels"]
    pipe = mpmd_pipeline(_stage_fn, ws, num_microbatches=4)
    survivors = [s for i, s in enumerate(pipe.stages) if i != 1]
    victim_pid = ray_tpu.get(pipe.stages[1].pid.remote(), timeout=30)
    try:
        x = jax.random.normal(jax.random.PRNGKey(2), (8, d))
        assert pipe.apply(x, num_microbatches=4).shape == (8, d)

        # Mid-schedule: several microbatches in flight when stage 1 dies.
        x_mb = jax.random.normal(jax.random.PRNGKey(3), (2, d))
        refs = [pipe.compiled.execute(x_mb) for _ in range(3)]
        os.kill(victim_pid, signal.SIGKILL)
        with pytest.raises(ActorDiedError, match="run"):
            for r in refs:
                r.get(timeout=60)
            # Even if every in-flight microbatch drained before the signal
            # landed, the next iterations must surface the typed death
            # (bounded: the driver monitor plants poison within seconds).
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                pipe.compiled.execute(x_mb).get(timeout=60)
    finally:
        pipe.teardown(kill_actors=False)

    # Full reclamation: channels back to the arena, driver scope freed,
    # surviving stages' pinned payloads freed.
    assert cw.raylet.call("get_state")["store"]["num_channels"] == chan0
    st = _drain_resident(device_object_stats, target=driver_base)
    assert st["resident_count"] <= driver_base, st
    for s in survivors:
        st = _drain_resident(
            lambda s=s: ray_tpu.get(s.devobj_stats.remote(), timeout=30), target=0
        )
        assert st["resident_count"] == 0, st
    for s in survivors:
        ray_tpu.kill(s)
