"""Every example in examples/ must actually run (subprocess, CPU, small).

The reference ships runnable example galleries; these are the equivalent
user-facing entry points, so breakage is a release blocker, not a docs
nit."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ("train_transformer.py", ["2"], "final loss:"),
    ("serve_llm.py", [], "generated:"),
    ("tune_hyperparams.py", [], "best config:"),
    ("data_pipeline.py", [], "jax batches ok"),
    ("rllib_ppo.py", ["1"], "iter 0:"),
    ("cross_language_task.py", [], "wordcount:"),
    ("serve_composed.py", [], "math:"),
    ("rllib_offline.py", [], "expert agreement:"),
    ("speculative_decode.py", [], "exact-output speculative decoding ok"),
    ("cpp_native_driver.py", [], "CPP_API_PASS"),
]


@pytest.mark.parametrize("script,args,expect", EXAMPLES, ids=[e[0] for e in EXAMPLES])
def test_example_runs(script, args, expect):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        RAY_TPU_JAX_CONFIG_PLATFORMS="cpu",
        RAY_TPU_NUM_TPUS="0",
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples", script), *args],
            capture_output=True,
            text=True,
            timeout=560,
            env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        pytest.fail(f"{script} timed out; partial stdout:\n{out}\nstderr:\n{err}")
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
    assert expect in proc.stdout, f"{script} output missing {expect!r}:\n{proc.stdout}"
