"""ARS + CRR (VERDICT r2 Missing #1: RLlib algorithm breadth).

Learning-gated like the other algorithm tests:
- ARS improves CartPole purely by top-k filtered random search with the
  observation filter (reference rllib/algorithms/ars/).
- CRR recovers a good CartPole policy OFFLINE from mixed expert/random
  data — the advantage filter must reject the random fraction
  (reference rllib/algorithms/crr/).
"""

import numpy as np
import pytest

import gymnasium as gym

import ray_tpu


@pytest.fixture
def ray_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


def test_ars_learns_cartpole(ray_cluster):
    from ray_tpu.rllib import ARSConfig

    cfg = (
        ARSConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2)
        .training(
            episodes_per_batch=16,
            num_top_directions=8,
            noise_stdev=0.05,
            stepsize=0.05,
            episode_horizon=500,
            eval_episodes=3,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(30):
            r = algo.step()
            reward = r.get("episode_reward_mean")
            if reward == reward:  # not NaN
                best = max(best, reward)
            if best >= 150:
                break
        assert best >= 150, f"ARS failed to learn CartPole (best={best})"
        assert algo.compute_single_action([0.0, 0.1, 0.0, -0.1]) in (0, 1)
    finally:
        algo.cleanup()


def _expert_action(obs) -> int:
    """Decent scripted CartPole controller (pole angle + velocity)."""
    return int(obs[2] + 0.3 * obs[3] > 0)


def test_crr_learns_cartpole_offline(ray_cluster, tmp_path):
    from ray_tpu.rllib import CRRConfig
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.policy.sample_batch import (
        ACTIONS,
        DONES,
        NEXT_OBS,
        OBS,
        REWARDS,
        SampleBatch,
    )

    # Mixed dataset: 60% scripted expert, 40% random. Plain behavior
    # cloning of this data caps well below the expert; CRR's advantage
    # filter recovers the expert component.
    env = gym.make("CartPole-v1")
    writer = JsonWriter(str(tmp_path / "crr_data"))
    rng = np.random.default_rng(0)
    rows = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)}
    obs, _ = env.reset(seed=0)
    for _ in range(6000):
        a = _expert_action(obs) if rng.random() < 0.6 else int(rng.integers(2))
        nobs, r, term, trunc, _ = env.step(a)
        rows[OBS].append(np.asarray(obs, np.float32))
        rows[ACTIONS].append(np.int64(a))
        rows[REWARDS].append(np.float32(r))
        rows[DONES].append(np.float32(term or trunc))
        rows[NEXT_OBS].append(np.asarray(nobs, np.float32))
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
    writer.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    writer.close()

    cfg = (
        CRRConfig()
        .environment("CartPole-v1")
        .offline_data(input_=str(tmp_path / "crr_data"))
        .training(lr=1e-3, train_batch_size=256, updates_per_iter=300,
                  weight_type="exp", temperature=1.0)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(10):
            r = algo.step()
        assert np.isfinite(r["total_loss"])
        # Evaluate the learned policy in the real env.
        rewards = []
        for ep in range(5):
            obs, _ = env.reset(seed=100 + ep)
            total = 0.0
            for _ in range(500):
                obs, rr, term, trunc, _ = env.step(algo.compute_single_action(obs))
                total += rr
                if term or trunc:
                    break
            rewards.append(total)
        mean_r = float(np.mean(rewards))
        assert mean_r >= 120, f"CRR failed to recover the expert (reward={mean_r})"
    finally:
        env.close()
        algo.cleanup()


def test_crr_binary_weights_smoke(ray_cluster, tmp_path):
    from ray_tpu.rllib import CRRConfig
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.policy.sample_batch import (
        ACTIONS,
        DONES,
        NEXT_OBS,
        OBS,
        REWARDS,
        SampleBatch,
    )

    env = gym.make("CartPole-v1")
    writer = JsonWriter(str(tmp_path / "crr_bin"))
    rng = np.random.default_rng(1)
    rows = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)}
    obs, _ = env.reset(seed=1)
    for _ in range(1000):
        a = int(rng.integers(2))
        nobs, r, term, trunc, _ = env.step(a)
        rows[OBS].append(np.asarray(obs, np.float32))
        rows[ACTIONS].append(np.int64(a))
        rows[REWARDS].append(np.float32(r))
        rows[DONES].append(np.float32(term or trunc))
        rows[NEXT_OBS].append(np.asarray(nobs, np.float32))
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
    writer.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    writer.close()
    env.close()

    cfg = (
        CRRConfig()
        .environment("CartPole-v1")
        .offline_data(input_=str(tmp_path / "crr_bin"))
        .training(updates_per_iter=50, weight_type="binary")
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    r = algo.step()
    assert np.isfinite(r["total_loss"])
    assert 0.0 <= r["mean_weight"] <= 1.0  # binary weights are indicators
    ckpt = algo.save_checkpoint()
    algo2 = cfg.build()
    algo2.setup(cfg.to_dict())
    algo2.load_checkpoint(ckpt)
    assert algo2._timesteps_total == algo._timesteps_total
