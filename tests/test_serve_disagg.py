"""Prefill/decode disaggregation + cluster KV prefix tier (ISSUE 20).

Two planes under test, sharing ONE module-scoped cluster (tier-1 budget):

- **Handoff oracles, end to end over a REAL serve instance** (controller +
  proxy + 1 prefill replica + 2 decode replicas): a prompt prefilled on
  pool A and decoded on pool B must yield BYTE-IDENTICAL tokens vs a
  single-replica (monolithic engine) run — greedy and seeded sampling —
  and must stay byte-identical when a seeded plan SIGKILLs the serving
  decode replica mid-stream (the PR 14 migration path re-prefills and
  teacher-forces on the surviving decode replica).

- **Cluster prefix tier lifecycle, on driver-attached engines** (the
  driver's core worker is the holder/importer — same sealing, registry
  rows, typed-miss and retraction code paths the replicas run; the
  cross-PROCESS import leg is exercised by the serve handoff oracles above
  and the --serve-disagg bench smoke): publish→import bit-exactness,
  sealed-copy immunity to holder pool churn (import-while-evicting can
  serve but never hand a torn block), typed miss + stale-row retraction
  when the payload died under the row (the holder-death story: importers
  garbage-collect rows for corpses), LRU-cap retraction, and GCS KV back
  to baseline after engine shutdown.
"""

import json
import threading
import time
import urllib.request
import zlib

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve._private.common import CONTROLLER_NAME, PREFIX_HINT_HEADER

MODEL = dict(
    vocab_size=64,
    d_model=32,
    n_layers=1,
    n_heads=2,
    n_kv_heads=2,
    d_ff=48,
    max_seq_len=64,
    dtype="float32",
    remat=False,
)
ENGINE = dict(num_slots=4, block_size=4, max_model_len=64, prefill_chunk=4)
SYSTEM = list(range(3, 3 + 16))  # 4 full blocks shared across prompts


@pytest.fixture(scope="module")
def disagg_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=6, object_store_memory=96 * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        serve.start()
        yield cluster
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


@pytest.fixture(scope="module")
def disagg_app(disagg_cluster):
    from ray_tpu.serve.llm import disaggregated_llm_app

    serve.run(
        disaggregated_llm_app(
            MODEL,
            dict(ENGINE),
            name="llm",
            prefill_replicas=1,
            decode_replicas=2,
            cluster_prefix=True,
        )
    )
    return disagg_cluster


def _oracle(prompt, n, **sampling):
    """Uninterrupted single-engine (monolithic) reference run with the same
    seed-deterministic params the replicas build (init_seed=0)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, init_params
    from ray_tpu.serve.llm import LLMEngine

    kw = dict(MODEL)
    kw["dtype"] = jnp.dtype(kw["dtype"]).type
    cfg = TransformerConfig(**kw)
    eng = LLMEngine(init_params(jax.random.PRNGKey(0), cfg), cfg, **ENGINE)
    try:
        return eng.submit(prompt, max_new_tokens=n, **sampling).result(120)
    finally:
        eng.shutdown()


def _replicas(dep):
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    table = ray_tpu.get(controller.get_routing_table.remote(-2, 0.1))["table"]
    return [r["actor_name"] for r in table.get(dep, {}).get("replicas", [])]


def _replica_stats(dep):
    out = []
    for name in _replicas(dep):
        try:
            out.append(
                ray_tpu.get(
                    ray_tpu.get_actor(name).handle_request.remote(
                        "get_stats", (), {}
                    ),
                    timeout=15,
                )
            )
        except Exception:
            pass
    return out


def _stream_sse(url, body, headers=None, timeout=240):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), headers=headers or {}
    )
    resp = urllib.request.urlopen(req, timeout=timeout)
    toks, buf = [], b""
    while True:
        chunk = resp.read(64)
        if not chunk:
            return toks, False
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            if not event.startswith(b"data: "):
                continue
            payload = event[6:]
            if payload == b"[DONE]":
                return toks, True
            toks.append(json.loads(payload)["token"])


def _flight_events(cluster, kind, since_wall):
    from ray_tpu._private.rpc import EventLoopThread

    resp = EventLoopThread.get().run(cluster.nodes[0].rpc_debug_dump({}), timeout=15)
    return [
        ev
        for proc in resp.get("processes", [])
        for ev in proc.get("events", [])
        if ev.get("type") == kind and ev.get("ts", 0) >= since_wall - 2.0
    ]


def _wait_kv_restored(deps=("llm", "llm--prefill")):
    """Leak oracle: every live replica's KV pool back to full once idle."""
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        stats = [s for dep in deps for s in _replica_stats(dep)]
        if stats and all(
            s["free_blocks"] + s["cached_blocks"] == s["num_blocks"] for s in stats
        ):
            return
        time.sleep(0.25)
    pytest.fail(f"replicas leaked KV blocks: {stats}")


def _run_handoff_oracle(cluster, prompt, n, sampling, kill=False):
    """POST one stream through the disaggregated app; the client's tokens
    must be byte-identical to the monolithic oracle, and the output must
    provably have ridden a prefill→decode handoff (counter delta, flight
    event) — with an optional seeded mid-stream SIGKILL of the serving
    decode replica."""
    from ray_tpu.serve.llm import prefix_route_hint

    expect = _oracle(prompt, n, **sampling)
    host, port = serve.http_address()
    url = f"http://{host}:{port}/llm"
    t_wall0 = time.time()
    handoffs0 = sum(s.get("handoffs", 0) for s in _replica_stats("llm"))
    exports0 = sum(s.get("handoff_exports", 0) for s in _replica_stats("llm--prefill"))
    hint = prefix_route_hint(prompt, ENGINE["block_size"])
    assert hint
    if kill:
        # A previous kill's replacement may still be booting.
        deadline = time.monotonic() + 180
        actors = _replicas("llm")
        while len(actors) < 2 and time.monotonic() < deadline:
            time.sleep(0.25)
            actors = _replicas("llm")
        assert len(actors) == 2, actors
        # The prefix hint pins the decode-pool pick, so the victim is known
        # BEFORE the request and the kill point (2nd actor-call response:
        # the accept + first stream-chunk pump) is seeded and replayable.
        victim = actors[zlib.crc32(hint.encode()) % len(actors)]
        assert cluster.install_plan_in_actor(
            victim,
            {"rules": [{"kind": "kill", "method": ["actor_call"],
                        "side": "resp", "after": 2, "times": 1}]},
            seed=13,
        )
    toks, done = _stream_sse(
        url,
        dict(tokens=prompt, max_new_tokens=n, **sampling),
        headers={PREFIX_HINT_HEADER: hint},
    )
    assert done, "stream ended without [DONE]"
    assert toks == expect, (toks, expect)
    # The tokens came through the pools, not a monolithic fallback: the
    # prefill pool sealed+exported and a decode replica imported.
    assert (
        sum(s.get("handoff_exports", 0) for s in _replica_stats("llm--prefill"))
        > exports0
    )
    if not kill:
        assert sum(s.get("handoffs", 0) for s in _replica_stats("llm")) > handoffs0
    assert _flight_events(cluster, "llm_kv_handoff", t_wall0), "no handoff recorded"
    if kill:
        assert _flight_events(cluster, "llm_migrate", t_wall0), "no migration"
        assert _flight_events(cluster, "chaos_kill", t_wall0), "no kill recorded"
    _wait_kv_restored()


def test_handoff_byte_identical_greedy(disagg_app):
    """THE tentpole oracle: prefilled on pool A, decoded on pool B, tokens
    byte-identical to a single-replica run (greedy)."""
    _run_handoff_oracle(
        disagg_app, prompt=[3, 1, 4, 1, 5, 9, 2, 6], n=24, sampling={}
    )


def test_handoff_byte_identical_seeded_sampling(disagg_app):
    """Sampled arm: the counter-based per-request RNG makes the handed-off
    continuation bit-identical too (tok0 drawn at the prefill pool, the
    rest at the decode pool, same stream as one engine drawing all 24)."""
    _run_handoff_oracle(
        disagg_app,
        prompt=[2, 7, 1, 8, 2, 8, 1, 8],
        n=24,
        sampling=dict(temperature=0.9, top_k=16, seed=11),
    )


def test_handoff_decode_kill_midstream_greedy(disagg_app):
    """A seeded plan SIGKILLs the serving DECODE replica mid-stream: the
    proxy migrates to the surviving decode replica (re-prefill + teacher-
    forced resume — the sealed import died with the victim) and the client
    still sees the byte-exact uninterrupted sequence."""
    _run_handoff_oracle(
        disagg_app, prompt=[1, 6, 1, 8, 0, 3, 3, 9], n=24, sampling={}, kill=True
    )


@pytest.mark.slow
def test_handoff_decode_kill_midstream_seeded_sampling(disagg_app):
    """Kill arm under seeded sampling: migration + handoff + RNG counters
    compose — still byte-identical."""
    _run_handoff_oracle(
        disagg_app,
        prompt=[2, 2, 5, 3, 0, 6, 1, 7],
        n=24,
        sampling=dict(temperature=0.8, top_k=8, seed=5),
        kill=True,
    )


# ---------------------------------------------------------------------------
# cluster prefix tier lifecycle (driver-attached engines)
# ---------------------------------------------------------------------------


def _mk_engine(**overrides):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, init_params
    from ray_tpu.serve.llm import LLMEngine

    kw = dict(MODEL)
    kw["dtype"] = jnp.dtype(kw["dtype"]).type
    cfg = TransformerConfig(**kw)
    return LLMEngine(
        init_params(jax.random.PRNGKey(0), cfg), cfg, **dict(ENGINE, **overrides)
    )


def _cw():
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker()


def _row(h):
    from ray_tpu.serve.llm import kv_transfer

    return kv_transfer.lookup_prefix_row(_cw(), h)


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    pytest.fail(f"timed out waiting for {msg}")


def test_prefix_import_bit_identical_and_local_seed(disagg_cluster):
    """Engine A publishes the shared prefix; engine B's registry probe
    imports it and B's output is byte-identical to an engine that computed
    everything itself. The import also seeds B's LOCAL prefix cache, so
    B's next same-prefix prompt never probes the registry again."""
    from ray_tpu.serve.llm.engine import block_hashes

    a = _mk_engine(cluster_prefix=True)
    b = _mk_engine(cluster_prefix=True)
    try:
        expect = _oracle(SYSTEM + [33, 35, 37, 39, 41, 43, 45, 47], 6)
        a.submit(SYSTEM + [20, 22, 24, 26, 28, 30, 32, 34], max_new_tokens=4).result(
            120
        )
        # Rows are fire-and-forget: wait for the shared depth-4 row to land.
        shared = block_hashes(SYSTEM, ENGINE["block_size"])[-1]
        _wait(lambda: _row(shared) is not None, msg="published prefix row")
        out = b.submit(
            SYSTEM + [33, 35, 37, 39, 41, 43, 45, 47], max_new_tokens=6
        ).result(120)
        assert out == expect, (out, expect)
        st = b.stats()
        assert st["prefix_import_hits"] == 1, st
        assert st["prefix_import_errors"] == 0, st
        # Second same-prefix prompt: the import registered the blocks in
        # B's local cache, so the probe short-circuits (hits stay at 1)
        # and the output is still oracle-exact.
        expect2 = _oracle(SYSTEM + [49, 51, 53, 55], 4)
        out2 = b.submit(SYSTEM + [49, 51, 53, 55], max_new_tokens=4).result(120)
        assert out2 == expect2
        assert b.stats()["prefix_import_hits"] == 1, b.stats()
    finally:
        a.shutdown()
        b.shutdown()


def test_sealed_copy_survives_holder_pool_churn(disagg_cluster):
    """Import-while-evicting, the serve side: the published payload is a
    SEALED COPY, so the holder recycling every pool block it was built
    from (12 distinct prompts churning a 64-block pool) cannot tear a
    later import — B still gets byte-exact tokens."""
    from ray_tpu.serve.llm.engine import block_hashes

    a = _mk_engine(cluster_prefix=True)
    b = _mk_engine(cluster_prefix=True)
    try:
        a.submit(SYSTEM + [2, 4, 6, 8], max_new_tokens=2).result(120)
        shared = block_hashes(SYSTEM, ENGINE["block_size"])[-1]
        _wait(lambda: _row(shared) is not None, msg="published prefix row")
        # Churn: distinct UNSHARED prompts overwrite the holder's pool.
        rng = np.random.default_rng(9)
        for _ in range(12):
            p = rng.integers(32, 64, 32).tolist()
            a.submit(p, max_new_tokens=2).result(120)
        expect = _oracle(SYSTEM + [11, 13, 15, 17], 6)
        out = b.submit(SYSTEM + [11, 13, 15, 17], max_new_tokens=6).result(120)
        assert out == expect, (out, expect)
        assert b.stats()["prefix_import_hits"] == 1, b.stats()
    finally:
        a.shutdown()
        b.shutdown()


def test_freed_payload_is_typed_miss_and_importer_retracts(disagg_cluster):
    """Import racing eviction/holder death, the miss side: the payload
    died under a still-present row. The importer gets the TYPED miss
    (DeviceObjectLostError, never a torn block), falls back to recompute
    (output still byte-exact), and retracts the stale row so the next
    prober skips the corpse — the holder-death garbage-collection story."""
    from ray_tpu.serve.llm.engine import block_hashes

    a = _mk_engine(cluster_prefix=True)
    b = _mk_engine(cluster_prefix=True)
    try:
        prompt_a = SYSTEM + [20, 22, 24, 26, 28, 30, 32, 34]
        a.submit(prompt_a, max_new_tokens=2).result(120)
        deep = block_hashes(prompt_a, ENGINE["block_size"])[4]
        shared = block_hashes(SYSTEM, ENGINE["block_size"])[-1]
        _wait(lambda: _row(deep) is not None, msg="published prefix row")
        # Kill the payload OUT FROM UNDER the rows (what eviction racing a
        # lookup, or a dead holder, looks like to an importer).
        oid = _row(shared)["oid"]
        _cw()._device_manager().free(oid)
        expect = _oracle(SYSTEM + [33, 35, 37, 39], 6)
        out = b.submit(SYSTEM + [33, 35, 37, 39], max_new_tokens=6).result(120)
        assert out == expect, (out, expect)
        st = b.stats()
        assert st["prefix_import_errors"] == 1, st
        assert st["prefix_import_hits"] == 0, st
        # The stale row B probed is gone. B republishes the prefix it just
        # recomputed (it is a cluster_prefix holder too), so the key may be
        # occupied again — the invariant is that no row points at the
        # corpse, not that the key is empty (read-check-delete semantics).
        _wait(
            lambda: (_row(shared) or {}).get("oid") != oid,
            msg="stale row retraction",
        )
    finally:
        a.shutdown()
        b.shutdown()


def test_lru_cap_retracts_evicted_rows(disagg_cluster):
    """cluster_prefix_max=1: publishing a second prefix evicts the first
    sealed payload AND retracts its registry rows; the survivor's rows
    stay."""
    from ray_tpu.serve.llm.engine import block_hashes

    a = _mk_engine(cluster_prefix=True, cluster_prefix_max=1)
    try:
        p1 = [10] * 4 + list(range(36, 48))
        p2 = [11] * 4 + list(range(36, 48))
        a.submit(p1, max_new_tokens=2).result(120)
        h1 = block_hashes(p1, ENGINE["block_size"])[-2]
        _wait(lambda: _row(h1) is not None, msg="first prefix row")
        a.submit(p2, max_new_tokens=2).result(120)
        h2 = block_hashes(p2, ENGINE["block_size"])[-2]
        _wait(lambda: _row(h2) is not None, msg="second prefix row")
        _wait(lambda: _row(h1) is None, msg="evicted prefix row retraction")
        assert a.stats()["published_prefixes"] == 1, a.stats()
    finally:
        a.shutdown()


def test_gcs_rows_return_to_baseline_after_shutdown(disagg_cluster):
    """Engine shutdown retracts every row it published and frees the
    sealed payloads: the GCS KV's llmprefix/ keyspace returns to its
    pre-engine baseline (no abandoned rows for importers to chase)."""
    from ray_tpu.serve.llm.kv_transfer import PREFIX_ROW

    def row_count():
        got = _cw().gcs.call("kv_keys", {"prefix": PREFIX_ROW}, timeout=10)
        return len(got.get("keys", []))

    baseline = row_count()
    a = _mk_engine(cluster_prefix=True)
    b = _mk_engine(cluster_prefix=True)
    try:
        rng = np.random.default_rng(3)
        for eng in (a, b):
            for _ in range(2):
                eng.submit(
                    rng.integers(0, 64, 24).tolist(), max_new_tokens=2
                ).result(120)
        _wait(lambda: row_count() > baseline, msg="published rows")
    finally:
        a.shutdown()
        b.shutdown()
    _wait(
        lambda: row_count() <= baseline,
        msg="rows retracted on shutdown",
    )
