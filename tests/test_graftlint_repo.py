"""Tier-1 gate: graftlint over the real ``ray_tpu/`` tree.

Runs the analyzer against the committed ``graftlint_baseline.json`` — any
NEW concurrency violation (loop-affinity leak, blocking call in async,
lock-order cycle) fails CI. Pure AST: must finish well under 10s and must
never import jax (the analyzer parses the tree, it does not execute it)."""

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_graftlint_repo_is_clean_and_fast():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.tools.graftlint", "ray_tpu", "--stats"],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, (
        f"graftlint found NEW violations:\n{proc.stdout}\n{proc.stderr}"
    )
    assert elapsed < 10.0, f"graftlint took {elapsed:.1f}s (budget 10s)"
    assert "graftlint:" in proc.stdout  # --stats footer rendered


def test_graftlint_never_imports_jax():
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from ray_tpu.tools.graftlint.cli import main\n"
            "rc = main(['ray_tpu'])\n"
            "assert 'jax' not in sys.modules, 'graftlint must not import jax'\n"
            "raise SystemExit(rc)",
        ],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_baseline_has_no_hot_path_suppressions():
    """Acceptance: the warm-lease hot path is CLEAN, not suppressed — the
    baseline must hold zero entries for rpc.py / lease_manager.py /
    worker_main.py. The device-object plane (experimental/device_object/)
    sits on the training/inference hot path the same way: its loop/blocking
    boundaries must stay annotated, never baselined."""
    with open(os.path.join(_REPO, "graftlint_baseline.json")) as f:
        data = json.load(f)
    hot = (
        "_private/rpc.py",
        "_private/lease_manager.py",
        "_private/worker_main.py",
        "experimental/device_object/",
    )
    offenders = [
        e["key"]
        for e in data.get("entries", [])
        if any(h in e["key"] for h in hot)
    ]
    assert not offenders, offenders
