"""Tune tests (modeled on reference python/ray/tune/tests — controller loop,
search/scheduler behavior, checkpoint/restore, trainer integration)."""

import random

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import FailureConfig, RunConfig
from ray_tpu.tune import sample as s
from ray_tpu.tune.schedulers import ASHAScheduler, MedianStoppingRule, PopulationBasedTraining
from ray_tpu.tune.search import BasicVariantGenerator, ConcurrencyLimiter, HyperOptLikeSearch


# ---------- sampling (no cluster needed) ----------

def test_grid_cross_product_times_samples():
    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search(["x", "y"])}
    g = BasicVariantGenerator(space, num_samples=2)
    assert g.total_samples == 12
    configs = [g.suggest(str(i)) for i in range(12)]
    assert all(c is not None for c in configs)
    assert g.suggest("extra") is None
    assert {(c["a"], c["b"]) for c in configs} == {(a, b) for a in (1, 2, 3) for b in ("x", "y")}


def test_domains_sample_within_bounds():
    rng = random.Random(0)
    for _ in range(100):
        assert 1e-4 <= s.loguniform(1e-4, 1e-1).sample(rng) <= 1e-1
        assert s.randint(2, 8).sample(rng) in range(2, 8)
        assert s.choice(["a", "b"]).sample(rng) in ("a", "b")
        q = s.quniform(0, 1, 0.25).sample(rng)
        assert abs(q / 0.25 - round(q / 0.25)) < 1e-9


def test_sample_from_sees_resolved_config():
    space = {"a": tune.choice([4]), "b": tune.sample_from(lambda spec: spec.config["a"] * 2)}
    cfg = s.resolve(space, random.Random(0))
    assert cfg == {"a": 4, "b": 8}


def test_nested_spaces():
    space = {"opt": {"lr": tune.loguniform(1e-4, 1e-2), "name": "adam"}, "n": tune.grid_search([1, 2])}
    g = BasicVariantGenerator(space)
    c = g.suggest("t")
    assert c["opt"]["name"] == "adam" and 1e-4 <= c["opt"]["lr"] <= 1e-2 and c["n"] in (1, 2)


def test_concurrency_limiter():
    g = ConcurrencyLimiter(BasicVariantGenerator({"x": tune.uniform(0, 1)}, num_samples=5), 2)
    a, b = g.suggest("t1"), g.suggest("t2")
    assert a is not None and b is not None
    assert g.suggest("t3") is None
    g.on_trial_complete("t1", {"m": 1})
    assert g.suggest("t3") is not None


# ---------- experiments on a live cluster ----------

def _quadratic(config):
    # max of -(x-3)^2 at x=3
    for i in range(5):
        tune.report({"score": -((config["x"] - 3.0) ** 2) - 0.01 * (5 - i)})


def test_tuner_random_search(ray_start_regular):
    results = tune.Tuner(
        _quadratic,
        param_space={"x": tune.uniform(0, 6)},
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=6,
                                    max_concurrent_trials=3),
    ).fit()
    assert len(results) == 6
    best = results.get_best_result("score", "max")
    assert best.metrics["score"] == max(r.metrics["score"] for r in results)


def test_tune_run_grid(ray_start_regular):
    results = tune.run(
        _quadratic,
        config={"x": tune.grid_search([1.0, 3.0, 5.0])},
        metric="score",
        mode="max",
    )
    assert len(results) == 3
    assert abs(results.get_best_result("score", "max").metrics["score"] + 0.01) < 1e-6


class _Counter(tune.Trainable):
    def setup(self, config):
        self.gain = config.get("gain", 1)
        self.total = 0

    def step(self):
        self.total += self.gain
        return {"total": self.total}

    def save_checkpoint(self):
        return Checkpoint.from_dict({"total": self.total})

    def load_checkpoint(self, ckpt):
        self.total = ckpt.to_dict()["total"]


def test_class_trainable_stop_criteria(ray_start_regular):
    results = tune.run(_Counter, config={"gain": 2}, stop={"training_iteration": 4})
    assert results[0].metrics["training_iteration"] == 4
    assert results[0].metrics["total"] == 8


def test_class_trainable_checkpoints_kept(ray_start_regular):
    results = tune.run(_Counter, config={"gain": 1}, stop={"training_iteration": 3})
    ckpt = results[0].checkpoint
    assert ckpt is not None and ckpt.to_dict()["total"] == 3


def _report_iters(config):
    for i in range(1, config.get("iters", 20) + 1):
        tune.report({"acc": config["lr"] * i})


def test_asha_stops_bad_trials_early(ray_start_regular):
    scheduler = ASHAScheduler(metric="acc", mode="max", max_t=20, grace_period=2,
                              reduction_factor=2)
    # good trials first + limited concurrency => later bad trials hit rungs
    # that already have recorded competitors and get cut (async ASHA only
    # stops trials arriving after the quantile is established)
    results = tune.Tuner(
        _report_iters,
        param_space={"lr": tune.grid_search([10.0, 1.0, 0.1, 0.01])},
        tune_config=tune.TuneConfig(scheduler=scheduler, metric="acc", mode="max",
                                    max_concurrent_trials=2),
    ).fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in results)
    assert iters[-1] >= 19  # best trial ran (nearly) to completion
    assert iters[0] < 20  # at least one trial was cut early


def test_median_stopping(ray_start_regular):
    scheduler = MedianStoppingRule(metric="acc", mode="max", grace_period=2,
                                   min_samples_required=2)
    results = tune.Tuner(
        _report_iters,
        param_space={"lr": tune.grid_search([0.001, 0.001, 5.0, 5.0])},
        tune_config=tune.TuneConfig(scheduler=scheduler, metric="acc", mode="max",
                                    max_concurrent_trials=4),
    ).fit()
    assert len(results) == 4


class _PBTTrainable(tune.Trainable):
    """Score grows by `rate`; good rates dominate — exploited trials should
    adopt winning rates + checkpoints."""

    def setup(self, config):
        self.score = 0.0

    def step(self):
        self.score += self.config["rate"]
        return {"score": self.score}

    def save_checkpoint(self):
        return Checkpoint.from_dict({"score": self.score})

    def load_checkpoint(self, ckpt):
        self.score = ckpt.to_dict()["score"]

    def reset_config(self, new_config):
        self.config = new_config
        return True


def test_pbt_exploits(ray_start_regular):
    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"rate": tune.uniform(0.1, 10.0)}, seed=0,
    )
    results = tune.Tuner(
        _PBTTrainable,
        param_space={"rate": tune.grid_search([0.1, 0.1, 8.0, 8.0])},
        tune_config=tune.TuneConfig(scheduler=pbt, metric="score", mode="max",
                                    max_concurrent_trials=4),
        run_config=RunConfig(stop={"training_iteration": 12}),
    ).fit()
    best = results.get_best_result("score", "max").metrics["score"]
    assert best >= 8.0 * 10  # top performer kept running


def _flaky(config, checkpoint=None):
    start = 0
    if checkpoint is not None:
        start = checkpoint.to_dict()["i"] + 1
    for i in range(start, 6):
        if i == 3 and start == 0:
            raise RuntimeError("boom")
        tune.report({"i": i}, checkpoint=Checkpoint.from_dict({"i": i}))


def test_trial_retry_from_checkpoint(ray_start_regular):
    results = tune.run(_flaky, config={}, max_failures=2)
    assert not results.errors
    assert results[0].metrics["i"] == 5


def test_trial_error_surfaces(ray_start_regular):
    def bad(config):
        raise ValueError("nope")

    results = tune.run(bad, config={})
    assert len(results.errors) == 1


def test_hyperopt_like_beats_random_on_easy_quadratic(ray_start_regular):
    searcher = HyperOptLikeSearch(
        {"x": tune.uniform(0, 6)}, metric="score", mode="max",
        n_initial_points=3, seed=0,
    )
    results = tune.Tuner(
        _quadratic,
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=12,
                                    search_alg=searcher, max_concurrent_trials=1),
    ).fit()
    best = results.get_best_result("score", "max")
    assert best.metrics["score"] > -1.5  # found the region around x=3


def test_tuner_restore(ray_start_regular, tmp_path):
    run_config = RunConfig(name="restore_exp", storage_path=str(tmp_path),
                           stop={"training_iteration": 4})
    results = tune.Tuner(
        _Counter,
        param_space={"gain": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="total", mode="max"),
        run_config=run_config,
    ).fit()
    assert len(results) == 2
    exp_dir = str(tmp_path / "restore_exp")
    restored = tune.Tuner.restore(
        exp_dir, _Counter,
        tune_config=tune.TuneConfig(metric="total", mode="max"),
        run_config=RunConfig(stop={"training_iteration": 6}),
    ).fit()
    assert len(restored) == 2
    # restored trials resume from checkpoint (iteration 4) and run to 6
    for r in restored:
        assert r.metrics["training_iteration"] >= 4


def test_trainer_via_tuner(ray_start_regular):
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    def loop(config):
        from ray_tpu.air import session

        for i in range(3):
            session.report({"loss": 1.0 / (config.get("lr", 1.0) * (i + 1))})

    trainer = JaxTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=1),
    )
    results = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {"lr": tune.grid_search([1.0, 2.0])}},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    ).fit()
    assert len(results) == 2
    assert results.get_best_result("loss", "min").metrics["loss"] == pytest.approx(1.0 / 6.0)
