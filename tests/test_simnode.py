"""Control-plane scale harness tests (ISSUE 19).

Tier-1 coverage for the sim-mode shells (_private/simnode), the GCS fan-in
hardening they exist to exercise (versioned delta heartbeat sync, per-node
location index, drop-oldest task-event ring), the jittered rejoin backoff,
and locality-aware placement on the REAL raylet path. The 1k-node sweep and
chaos-at-scale cells are marked `slow` (tier-2); tier-1 keeps a 128-shell
smoke that boots, converges, and pushes 10k stub tasks in well under 30s.
"""

import asyncio
import random
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import init_config
from ray_tpu._private.raylet import apply_heartbeat_view, rejoin_backoff_delay
from ray_tpu._private.sched_core import create_sched_core
from ray_tpu._private.simnode import SimCluster, SimTraffic


# ---------------------------------------------------------------------------
# Sim smoke (tier-1): module-scoped cluster — boot once, share across tests.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sim_cluster():
    c = SimCluster(
        128,
        resources_per_node={"CPU": 8},
        num_entry_nodes=16,
        _system_config={
            "heartbeat_interval_s": 0.25,
            "node_death_timeout_s": 2.0,
            "rejoin_backoff_base_s": 0.02,
            "rejoin_backoff_max_s": 0.5,
        },
    )
    c.start()
    c.wait_for_view(timeout=60)
    try:
        yield c
    finally:
        c.shutdown()


def test_sim_smoke_128_shells_10k_tasks(sim_cluster):
    """128 shells over the real GCS wire push 10k stub tasks inside the
    tier-1 budget. Every shell's delta-synced view converged (fixture), and
    placement throughput holds four digits even on a 1-core box."""
    c = sim_cluster
    base = c.done_count
    n = 10_000

    async def _burst():
        step = 500
        for i in range(0, n, step):
            await asyncio.gather(
                *[c.asubmit(c.make_spec(sim_ms=1.0)) for _ in range(step)]
            )

    t0 = time.monotonic()
    c._io.run(_burst(), timeout=120)
    assert c.wait_done(base + n, timeout=60)
    wall = time.monotonic() - t0
    assert wall < 30.0, f"10k stub tasks took {wall:.1f}s (budget 30s)"
    assert all(len(node.cluster_view) == 128 for node in c.nodes[:8])


def test_sim_heartbeats_are_delta_synced(sim_cluster):
    """Steady state: idle heartbeats carry ZERO view rows — the O(N^2)
    bytes/interval hot spot is gone. A fresh shell's first contact is the
    only full-view reply in the window."""
    c = sim_cluster
    time.sleep(0.6)  # let any task-burst availability churn settle
    c.gcs.hb_stats = {"replies": 0, "rows": 0, "full_replies": 0, "view_bytes": 0}
    c.gcs.hb_account = True
    time.sleep(1.0)
    c.gcs.hb_account = False
    hb = c.gcs.hb_stats
    assert hb["replies"] >= 128, hb  # everyone beat at least once
    assert hb["full_replies"] == 0, hb
    assert hb["rows"] == 0, hb  # idle deltas are EMPTY
    assert hb["view_bytes"] == 0, hb


def test_sim_closed_loop_traffic_no_untyped_failures(sim_cluster):
    stats = SimTraffic(
        sim_cluster, users=8, pattern="diurnal", think_s=0.01,
        sim_ms=2.0, task_timeout_s=5.0, seed=5,
    ).run(1.5)
    assert stats["completed"] > 50
    assert stats["failures"] == {}, stats


# ---------------------------------------------------------------------------
# Delta-sync protocol edges (satellite 3)
# ---------------------------------------------------------------------------


def test_delta_resync_after_missed_generations():
    """A client whose view version predates the pruned tombstone floor must
    get a FULL view resync — deltas would silently skip removals it never
    saw. Driven against a live GCS over the wire via one sim shell."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.rpc import RpcClient

    init_config({"heartbeat_interval_s": 30.0, "node_death_timeout_s": 120.0})
    gcs = GcsServer()
    cli = RpcClient(gcs.address, label="t-resync")
    try:
        for i in range(3):
            cli.call(
                "register_node",
                {
                    "node_id": f"n{i}",
                    "address": ["127.0.0.1", 10000 + i],
                    "resources": {"CPU": 1},
                },
                timeout=10,
            )
        # First contact: version 0 is always a full resync.
        r = cli.call("heartbeat", {"node_id": "n0", "view_version": 0}, timeout=10)
        assert r["view_full"] is True
        ver = r["view_version"]
        assert set(r["view"]) == {"n0", "n1", "n2"}

        # Still-current client gets an EMPTY delta.
        r = cli.call("heartbeat", {"node_id": "n0", "view_version": ver}, timeout=10)
        assert r["view_full"] is False and r["view"] == {} and r["view_removed"] == []

        # Age the tombstone history past its bound: the old version now
        # predates the pruned floor.
        for j in range(1100):
            gcs._bump_view(f"ghost{j}", removed=True)
        assert gcs._removals_floor > ver
        r = cli.call("heartbeat", {"node_id": "n0", "view_version": ver}, timeout=10)
        assert r["view_full"] is True, "pruned-floor client must full-resync"

        # A client "from the future" (GCS restarted, versions reset) also
        # falls back to a full view instead of a bogus delta.
        r = cli.call(
            "heartbeat",
            {"node_id": "n0", "view_version": r["view_version"] + 999},
            timeout=10,
        )
        assert r["view_full"] is True
    finally:
        cli.close()
        gcs.stop()


def test_stale_view_echo_never_clobbers_local_ledger():
    """The never-self guard: a heartbeat delta carrying a STALE row for this
    node (pre-acquire availability echoed back) must not overwrite the local
    ledger — in-flight acquires are authoritative."""

    class Shell:
        pass

    node = Shell()
    node.node_id = "me"
    node.cluster_view = {}
    node._synced_peers = set()
    node._view_version = 0
    node._sched = create_sched_core()
    node._sched.node_upsert("me", {"CPU": 4}, {"CPU": 4})
    assert node._sched.try_acquire("me", {"CPU": 3})  # in-flight work

    stale_echo = {
        "view": {
            "me": {
                "address": ["127.0.0.1", 1],
                "resources_total": {"CPU": 4},
                "resources_available": {"CPU": 4},  # pre-acquire lie
                "labels": {},
                "state": "ALIVE",
            },
            "peer": {
                "address": ["127.0.0.1", 2],
                "resources_total": {"CPU": 2},
                "resources_available": {"CPU": 2},
                "labels": {},
                "state": "ALIVE",
            },
        },
        "view_removed": [],
        "view_full": True,
        "view_version": 7,
    }
    apply_heartbeat_view(stale_echo, node)
    assert node._view_version == 7
    # Self: untouched — the acquire survives the echo.
    assert node._sched.node_avail("me", "CPU") == pytest.approx(1.0)
    # Peer: mirrored.
    assert node._sched.node_avail("peer", "CPU") == pytest.approx(2.0)

    # Removal tombstones drop peers from the mirror — but never self.
    apply_heartbeat_view(
        {"view": {}, "view_removed": ["peer"], "view_full": False,
         "view_version": 8},
        node,
    )
    assert "peer" not in node.cluster_view
    assert node._sched.node_avail("me", "CPU") == pytest.approx(1.0)
    node._sched.close()


def test_optimistic_debit_expires_when_no_delta_arrives():
    """The scale harness caught this: under delta sync a forward-time mirror
    debit is only overwritten when the peer's row CHANGES at the GCS. A peer
    that acquires and releases between its own heartbeats never changes its
    row, no delta arrives, and the debit would stick forever — the forwarder
    permanently under-estimates an idle peer. The ledger must credit it back
    after its deadline; an authoritative row must cancel it instead."""
    from ray_tpu._private.raylet import OptimisticDebitLedger

    sched = create_sched_core()
    sched.node_upsert("peer", {"CPU": 2}, {"CPU": 2})

    # Expiry path: debit, no delta ever arrives, deadline passes → credited.
    ledger = OptimisticDebitLedger()
    assert sched.try_acquire("peer", {"CPU": 1})
    ledger.note("peer", {"CPU": 1}, interval_s=0.02)
    assert sched.node_avail("peer", "CPU") == pytest.approx(1.0)
    time.sleep(0.15)  # past the 2.5x-interval deadline (interval floor 0.05)
    ledger.expire(sched)
    assert sched.node_avail("peer", "CPU") == pytest.approx(2.0)

    # Authoritative-row path: a delta for the peer supersedes the debit —
    # expire() afterwards must NOT double-credit on top of the fresh row.
    assert sched.try_acquire("peer", {"CPU": 1})
    ledger.note("peer", {"CPU": 1}, interval_s=0.02)
    ledger.on_authoritative_rows({"peer"})
    sched.node_upsert("peer", {"CPU": 2}, {"CPU": 0.5})  # the real row
    time.sleep(0.15)
    ledger.expire(sched)
    assert sched.node_avail("peer", "CPU") == pytest.approx(0.5)

    # A late credit for a tombstoned node is harmless (release no-ops).
    ledger.note("ghost", {"CPU": 1}, interval_s=0.02)
    time.sleep(0.15)
    ledger.expire(sched)
    sched.close()


# ---------------------------------------------------------------------------
# Rejoin backoff (satellite 1)
# ---------------------------------------------------------------------------


def test_rejoin_backoff_delay_jitters_and_caps():
    cfg = init_config({"rejoin_backoff_base_s": 0.05, "rejoin_backoff_max_s": 2.0})
    rng = random.Random(42)
    # Full jitter: attempt k draws uniform [0, min(max, base*2^k)].
    for attempt, ceiling in [(0, 0.05), (1, 0.1), (3, 0.4), (10, 2.0)]:
        draws = [rejoin_backoff_delay(attempt, cfg, rng) for _ in range(200)]
        assert all(0 <= d <= ceiling + 1e-9 for d in draws), (attempt, max(draws))
        assert max(draws) > ceiling * 0.8  # actually spans the range
    # Distinct node seeds de-correlate: two raylets don't retry in lockstep.
    a = [rejoin_backoff_delay(2, cfg, random.Random("node-a")) for _ in range(8)]
    b = [rejoin_backoff_delay(2, cfg, random.Random("node-b")) for _ in range(8)]
    assert a != b


def test_gcs_restart_rejoin_storm_no_duplicate_rows():
    """Restart the GCS under 3 REAL raylets: every raylet hits `unknown` on
    its next heartbeat and rejoins with jittered backoff. Afterwards: same
    node ids, no duplicate rows, and sealed-object locations republished."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        _system_config={
            "heartbeat_interval_s": 0.2,
            "node_death_timeout_s": 5.0,
            "rejoin_backoff_base_s": 0.02,
            "rejoin_backoff_max_s": 0.3,
        }
    )
    try:
        for _ in range(3):
            cluster.add_node(num_cpus=1)
        cluster.connect()
        cluster.wait_for_nodes()
        ids_before = {n.node_id for n in cluster.nodes}

        ref = ray_tpu.put(np.zeros(300 * 1024, dtype=np.uint8))  # plasma-sized
        oid = ref.hex()

        cluster.restart_gcs()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            alive = {
                nid
                for nid, n in cluster.gcs.nodes.items()
                if n["state"] == "ALIVE"
            }
            if alive == ids_before:
                break
            time.sleep(0.1)
        assert set(cluster.gcs.nodes) == ids_before, "duplicate/lost node rows"
        assert all(n["state"] == "ALIVE" for n in cluster.gcs.nodes.values())

        # Location rows for the sealed object came back via the republish.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if cluster.gcs.object_locations.get(oid):
                break
            time.sleep(0.1)
        assert cluster.gcs.object_locations.get(oid), "locations not republished"
        # And the object is still fetchable end to end.
        assert ray_tpu.get(ref, timeout=60).nbytes == 300 * 1024
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# Locality-aware scheduling on the REAL raylet path (tentpole acceptance)
# ---------------------------------------------------------------------------


def test_locality_task_lands_on_holder_and_spills_when_saturated():
    """A task whose plasma-sized arg lives on node B runs ON node B
    (flight-evidenced via locality_hit), and when B is saturated the same
    shape spills to another node instead of queueing behind B."""
    from ray_tpu._private import flight_recorder
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        _system_config={
            "heartbeat_interval_s": 0.2,
            "locality_cache_ttl_s": 0.2,
        }
    )
    try:
        cluster.add_node(num_cpus=1)
        n2 = cluster.add_node(num_cpus=1)
        cluster.add_node(num_cpus=1)
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote
        def produce():
            return np.ones(300 * 1024, dtype=np.uint8)  # > inline cutoff

        @ray_tpu.remote
        def consume(x):
            import os

            return (int(x[0]), os.environ.get("RAY_TPU_NODE_ID"))

        @ray_tpu.remote
        def hog():
            time.sleep(4.0)
            return 1

        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        big = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=n2.node_id)
        ).remote()
        ray_tpu.wait([big], timeout=60)
        # Deterministic settle: the head's MIRROR of the holder must show a
        # free CPU again (produce released it; the delta takes ~2 heartbeat
        # intervals to propagate) or locality would correctly refuse a
        # saturated holder and the assertion below would test the race, not
        # the policy.
        head = cluster.nodes[0]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if head._sched.node_avail(n2.node_id, "CPU") >= 1.0:
                break
            time.sleep(0.05)
        time.sleep(0.2)  # location row publish

        val, ran_on = ray_tpu.get(consume.remote(big), timeout=60)
        assert val == 1
        assert ran_on == n2.node_id, "large-arg task must land on the holder"
        evs = (flight_recorder.dump() or {}).get("events", [])
        assert any(e["type"] == "locality_hit" for e in evs), (
            "locality placement must leave flight evidence"
        )

        # The first consume leased a worker ON the holder; a cached idle
        # lease would satisfy the next submit without consulting placement
        # at all (and still hold the holder's CPU). Wait for the idle-lease
        # release so the spill phase exercises the scheduler, not the cache.
        from ray_tpu._private import worker_context

        lm = worker_context.get_core_worker_if_initialized()._lease_mgr
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if lm is None or not any(s.leases for s in lm._shapes.values()):
                break
            time.sleep(0.1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:  # holder idle again, mirror caught up
            if head._sched.node_avail(n2.node_id, "CPU") >= 1.0:
                break
            time.sleep(0.05)

        # Saturate the holder, resubmit the same shape: it must SPILL to a
        # different node, not camp on B's queue.
        blocker = hog.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=n2.node_id)
        ).remote()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:  # holder saturation visible at head
            if head._sched.node_avail(n2.node_id, "CPU") < 1.0:
                break
            time.sleep(0.05)
        t0 = time.monotonic()
        val, ran_on = ray_tpu.get(consume.remote(big), timeout=60)
        spill_wall = time.monotonic() - t0
        assert val == 1
        assert ran_on != n2.node_id, "saturated holder: task must spill"
        assert spill_wall < 3.5, (
            f"spill took {spill_wall:.1f}s — it queued behind the hog instead"
        )
        assert ray_tpu.get(blocker, timeout=60) == 1
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# Task-event drop-oldest ring (satellite 2)
# ---------------------------------------------------------------------------


def test_task_event_ring_drops_oldest_counts_and_flares():
    from ray_tpu._private import flight_recorder
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.rpc import RpcClient

    init_config(
        {
            "heartbeat_interval_s": 30.0,
            "node_death_timeout_s": 120.0,
            "task_events_buffer_size": 100,
        }
    )
    gcs = GcsServer()
    cli = RpcClient(gcs.address, label="t-events")
    try:
        r = cli.call(
            "record_task_events",
            {"events": [{"task_id": f"a{i}", "state": "FINISHED"} for i in range(60)]},
            timeout=10,
        )
        assert r["dropped"] == 0 and gcs.events_dropped_total == 0

        r = cli.call(
            "record_task_events",
            {"events": [{"task_id": f"b{i}", "state": "FINISHED"} for i in range(80)]},
            timeout=10,
        )
        assert r["dropped"] == 40  # 60 + 80 - 100
        assert gcs.events_dropped_total == 40
        assert len(gcs.task_events) == 100
        # Drop-OLDEST: the survivors are the newest 100 (a40..a59 + b0..b79).
        ids = [e["task_id"] for e in gcs.task_events]
        assert ids[0] == "a40" and ids[-1] == "b79"

        # get_task_events serves the ring, bounded by limit.
        got = cli.call("get_task_events", {"limit": 10}, timeout=10)
        assert len(got["events"]) == 10

        evs = (flight_recorder.dump() or {}).get("events", [])
        assert any(e["type"] == "gcs_overload" for e in evs), (
            "overflow must flare a gcs_overload flight event"
        )
    finally:
        cli.close()
        gcs.stop()


def test_gcs_location_index_tracks_add_remove_death():
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.rpc import RpcClient

    init_config({"heartbeat_interval_s": 30.0, "node_death_timeout_s": 120.0})
    gcs = GcsServer()
    cli = RpcClient(gcs.address, label="t-locidx")
    try:
        cli.call(
            "register_node",
            {"node_id": "nx", "address": ["127.0.0.1", 1], "resources": {"CPU": 1}},
            timeout=10,
        )
        for i in range(5):
            cli.call(
                "add_object_location",
                {"object_id": f"o{i}", "node_id": "nx"},
                timeout=10,
            )
        assert gcs._locations_by_node["nx"] == {f"o{i}" for i in range(5)}
        cli.call(
            "remove_object_location", {"object_id": "o0", "node_id": "nx"}, timeout=10
        )
        assert "o0" not in gcs._locations_by_node["nx"]

        # Node death via the index drops exactly this node's rows.
        gcs._io.run(gcs._on_node_death("nx"), timeout=10)
        assert "nx" not in gcs._locations_by_node
        assert all("nx" not in holders for holders in gcs.object_locations.values())
    finally:
        cli.close()
        gcs.stop()


# ---------------------------------------------------------------------------
# Tier-2 (slow): the 1k sweep and chaos at scale
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sim_1k_shells_boot_and_schedule():
    c = SimCluster(
        1000,
        resources_per_node={"CPU": 8},
        num_entry_nodes=32,
        _system_config={
            "heartbeat_interval_s": 0.5,
            "node_death_timeout_s": 5.0,
        },
    )
    try:
        t0 = time.monotonic()
        c.start()
        c.wait_for_view(timeout=300)
        boot = time.monotonic() - t0

        n = 5000
        async def _burst():
            for i in range(0, n, 500):
                await asyncio.gather(
                    *[c.asubmit(c.make_spec(sim_ms=1.0)) for _ in range(500)]
                )

        c._io.run(_burst(), timeout=300)
        assert c.wait_done(n, timeout=180)
        assert boot < 180, f"1k boot+converge took {boot:.0f}s"
        # Delta sync holds at 1k: idle steady-state rows are zero.
        time.sleep(1.0)
        c.gcs.hb_stats = {"replies": 0, "rows": 0, "full_replies": 0, "view_bytes": 0}
        time.sleep(2.0)
        assert c.gcs.hb_stats["full_replies"] == 0
        assert c.gcs.hb_stats["rows"] == 0
    finally:
        c.shutdown()


@pytest.mark.slow
def test_sim_chaos_matrix_at_scale():
    from chaos_matrix import run_sim_matrix

    cells = run_sim_matrix(num_nodes=256, seed=7, quick=False)
    bad = [r.summary() for r in cells if not r.ok]
    assert not bad, f"sim SLO cells failed: {bad}"
