"""KV-cache decode correctness: generate() must match the no-cache forward.

The decisive oracle: greedy generation with prefill+cached decode steps must
produce exactly the tokens obtained by re-running the full (cache-free)
``forward`` at every step and taking argmax — teacher-forcing equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.generate import decode_step, generate, init_cache, prefill
from ray_tpu.models.transformer import TransformerConfig, forward, init_params


def _cfg(**kw):
    base = dict(
        vocab_size=128,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=96,
        max_seq_len=64,
        dtype=jnp.float32,
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _greedy_reference(params, prompt, cfg, n_new):
    """Teacher-forced loop: full forward each step, argmax of last logits."""
    toks = prompt
    out = []
    for _ in range(n_new):
        logits, _ = forward(params, toks, cfg)
        nxt = np.asarray(logits[:, -1].argmax(axis=-1), np.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, jnp.asarray(nxt)[:, None]], axis=1)
    return np.stack(out, axis=1)  # [B, n_new]


@pytest.mark.parametrize("kv_heads,tie", [(4, False), (2, False), (4, True)])
def test_greedy_generate_matches_forward(kv_heads, tie):
    cfg = _cfg(n_kv_heads=kv_heads, tie_embeddings=tie)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, cfg.vocab_size)
    want = _greedy_reference(params, prompt, cfg, n_new=8)
    got = np.asarray(generate(params, prompt, cfg, max_new_tokens=8, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_prefill_logits_match_forward():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 9), 0, cfg.vocab_size)
    cache = init_cache(cfg, 3, 16)
    logits, cache, pos = prefill(params, prompt, cache, cfg)
    full, _ = forward(params, prompt, cfg)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-5
    )
    assert np.asarray(pos).tolist() == [9, 9, 9]
    # Cache beyond the prompt is untouched zeros.
    assert float(jnp.abs(cache["k"][:, :, 9:]).sum()) == 0.0


def test_decode_step_extends_prefill():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2, 12)
    logits, cache, pos = prefill(params, prompt, cache, cfg)
    nxt = logits.argmax(axis=-1).astype(jnp.int32)
    step_logits, _ = decode_step(params, nxt, cache, pos, cfg)
    ext = jnp.concatenate([prompt, nxt[:, None]], axis=1)
    full, _ = forward(params, ext, cfg)
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(full[:, -1]), rtol=2e-4, atol=2e-5
    )


def test_decode_chunk_matches_forward():
    """Multi-token cached decode: feeding q tokens at once produces the
    same per-position logits as the cache-free forward."""
    from ray_tpu.models.generate import decode_chunk

    cfg = _cfg(n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, cfg.vocab_size)
    extra = jax.random.randint(jax.random.PRNGKey(2), (2, 3), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2, 16)
    _, cache, pos = prefill(params, prompt, cache, cfg)
    chunk_logits, _ = decode_chunk(params, extra, cache, pos, cfg)
    full, _ = forward(params, jnp.concatenate([prompt, extra], axis=1), cfg)
    # chunk_logits[j] is the next-token distribution after consuming
    # extra[j] at absolute position 4+j -> full-forward logits[4+j].
    np.testing.assert_allclose(
        np.asarray(chunk_logits), np.asarray(full[:, 4:7]), rtol=2e-4, atol=2e-5
    )


def test_prefill_chunked_matches_prefill():
    """Chunked prefill (bounded-memory long-prompt path) ends in the same
    cache state and last-token logits as one-shot prefill."""
    from ray_tpu.models.generate import prefill_chunked

    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
    one_logits, one_cache, one_pos = prefill(
        params, prompt, init_cache(cfg, 2, 16), cfg
    )
    ch_logits, ch_cache, ch_pos = prefill_chunked(
        params, prompt, init_cache(cfg, 2, 16), cfg, chunk=4
    )
    np.testing.assert_allclose(
        np.asarray(ch_logits), np.asarray(one_logits), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_array_equal(np.asarray(ch_pos), np.asarray(one_pos))
    np.testing.assert_allclose(
        np.asarray(ch_cache["k"][:, :, :12]),
        np.asarray(one_cache["k"][:, :, :12]),
        rtol=2e-4,
        atol=2e-5,
    )
    with pytest.raises(ValueError, match="divisible"):
        prefill_chunked(params, prompt, init_cache(cfg, 2, 16), cfg, chunk=5)


def test_speculative_generate_exact_and_fewer_passes():
    """Speculative decoding is EXACT for greedy (accept iff draft token ==
    target argmax) — same tokens as generate() — and when the draft IS the
    target every proposal is accepted, so target passes collapse to
    ~max_new/(k+1)."""
    from ray_tpu.models.generate import speculative_generate

    cfg = _cfg(n_kv_heads=2)
    draft_cfg = _cfg(n_layers=1, d_ff=64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_params(jax.random.PRNGKey(9), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)

    want = np.asarray(generate(params, prompt, cfg, max_new_tokens=12, temperature=0.0))
    got, rounds = speculative_generate(
        params, draft_params, prompt, cfg, draft_cfg, max_new_tokens=12, k=3
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert 1 <= int(rounds) <= 12  # never worse than one pass per token

    # Perfect draft (the target itself): every round accepts all k, so
    # rounds ~= ceil((max_new - 1) / (k + 1)).
    got2, rounds2 = speculative_generate(
        params, params, prompt, cfg, cfg, max_new_tokens=12, k=3
    )
    np.testing.assert_array_equal(np.asarray(got2), want)
    assert int(rounds2) <= 4, f"perfect draft should collapse passes, got {int(rounds2)}"


def test_sliding_window_generate_matches_forward():
    """Windowed config: cached decode (position-mask window) must agree
    with the cache-free forward (kernel/XLA-mask window) token for token."""
    cfg = _cfg(sliding_window=6, n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, cfg.vocab_size)
    want = _greedy_reference(params, prompt, cfg, n_new=8)
    got = np.asarray(generate(params, prompt, cfg, max_new_tokens=8, temperature=0.0))
    np.testing.assert_array_equal(got, want)
    # The window matters: a full-attention config diverges from it.
    full = np.asarray(
        generate(params, prompt, _cfg(n_kv_heads=2), max_new_tokens=8, temperature=0.0)
    )
    assert not np.array_equal(got, full), "window had no effect"


def test_sampling_modes():
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, cfg.vocab_size)
    a = generate(params, prompt, cfg, max_new_tokens=6, temperature=0.8, top_k=16,
                 key=jax.random.PRNGKey(7))
    b = generate(params, prompt, cfg, max_new_tokens=6, temperature=0.8, top_k=16,
                 key=jax.random.PRNGKey(7))
    c = generate(params, prompt, cfg, max_new_tokens=6, temperature=0.8, top_k=16,
                 key=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same key -> same draw
    assert np.asarray(a).shape == (2, 6)
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < cfg.vocab_size)).all()
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # overwhelmingly likely


def test_ragged_prompt_batch_matches_per_row():
    """Ragged batch (prompt_lens) must produce exactly what each row
    produces generated alone — padding must be invisible."""
    cfg = _cfg(n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rows = [
        jax.random.randint(jax.random.PRNGKey(i), (n,), 0, cfg.vocab_size)
        for i, n in enumerate((3, 7, 5))
    ]
    T = max(len(r) for r in rows)
    padded = jnp.stack([
        jnp.pad(r, (0, T - len(r)), constant_values=99) for r in rows
    ])
    lens = jnp.asarray([len(r) for r in rows], jnp.int32)
    got = np.asarray(
        generate(params, padded, cfg, max_new_tokens=6, temperature=0.0,
                 prompt_lens=lens)
    )
    for i, r in enumerate(rows):
        solo = np.asarray(
            generate(params, r[None], cfg, max_new_tokens=6, temperature=0.0)
        )[0]
        np.testing.assert_array_equal(got[i], solo, err_msg=f"row {i}")


def test_zero_length_prompt_row_is_clamped():
    """A stray len-0 row behaves as len-1 (defined, finite) instead of
    poisoning the batch with NaN."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    got = np.asarray(
        generate(params, prompt, cfg, max_new_tokens=4, temperature=0.0,
                 prompt_lens=jnp.asarray([0, 5], jnp.int32))
    )
    as_one = np.asarray(
        generate(params, prompt, cfg, max_new_tokens=4, temperature=0.0,
                 prompt_lens=jnp.asarray([1, 5], jnp.int32))
    )
    np.testing.assert_array_equal(got, as_one)
    assert (got >= 0).all() and (got < cfg.vocab_size).all()


def test_generate_with_tp_sharded_params():
    """Multi-chip serving: prefill + decode over tensor-parallel-sharded
    params (tp=4 x dp=2 on the virtual 8-device mesh) matches the
    single-device logits to float tolerance — XLA inserts the collectives,
    the decode loop stays one compiled program. (Logits, not argmax
    chains: the tp all-reduce changes summation order, so near-tied tokens
    could legitimately flip.)"""
    from ray_tpu.models.transformer import param_logical_axes
    from ray_tpu.parallel.mesh import (
        MeshConfig,
        create_mesh,
        logical_to_spec,
        shard_pytree,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    cfg = _cfg(d_ff=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)

    cache = init_cache(cfg, 2, 12)
    ref_logits, ref_cache, pos = prefill(params, prompt, cache, cfg)
    nxt = ref_logits.argmax(axis=-1).astype(jnp.int32)
    ref_step, _ = decode_step(params, nxt, ref_cache, pos, cfg)

    mesh = create_mesh(MeshConfig(tp=4, dp=2))
    axes = param_logical_axes(cfg)

    def spec_for(path):
        node = axes
        for p in path:
            node = node[p.key]
        return logical_to_spec(node)

    sharded = shard_pytree(params, mesh, lambda path, _leaf: spec_for(path))
    sh_logits, sh_cache, sh_pos = prefill(sharded, prompt, init_cache(cfg, 2, 12), cfg)
    np.testing.assert_allclose(
        np.asarray(sh_logits), np.asarray(ref_logits), rtol=1e-5, atol=1e-6
    )
    sh_step, _ = decode_step(sharded, nxt, sh_cache, sh_pos, cfg)
    np.testing.assert_allclose(
        np.asarray(sh_step), np.asarray(ref_step), rtol=1e-5, atol=1e-6
    )
    # The full generation loop also runs end-to-end under the sharding.
    out = np.asarray(generate(sharded, prompt, cfg, max_new_tokens=5))
    assert out.shape == (2, 5) and (out < cfg.vocab_size).all()


def test_moe_greedy_generate_matches_lossless_forward():
    """MoE inference is LOSSLESS by design (every token gets an expert
    slot), deliberately not replicating training's capacity drops — so
    generate under the DEFAULT capacity factor must match a forward whose
    capacity is raised to never drop."""
    import dataclasses

    cfg = _cfg(num_experts=4)  # default expert_capacity_factor (1.25)
    lossless = dataclasses.replace(cfg, expert_capacity_factor=float(cfg.num_experts))
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    want = _greedy_reference(params, prompt, lossless, n_new=6)
    got = np.asarray(generate(params, prompt, cfg, max_new_tokens=6, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_moe_ragged_prompts_match_solo():
    """Padding must stay invisible under MoE too: lossless dispatch makes
    routing per-token, so capacity never couples rows or padding."""
    cfg = _cfg(num_experts=4, n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rows = [
        jax.random.randint(jax.random.PRNGKey(i), (n,), 0, cfg.vocab_size)
        for i, n in enumerate((2, 6))
    ]
    T = max(len(r) for r in rows)
    padded = jnp.stack([jnp.pad(r, (0, T - len(r)), constant_values=3) for r in rows])
    lens = jnp.asarray([len(r) for r in rows], jnp.int32)
    got = np.asarray(
        generate(params, padded, cfg, max_new_tokens=5, temperature=0.0,
                 prompt_lens=lens)
    )
    for i, r in enumerate(rows):
        solo = np.asarray(
            generate(params, r[None], cfg, max_new_tokens=5, temperature=0.0)
        )[0]
        np.testing.assert_array_equal(got[i], solo, err_msg=f"row {i}")


def test_speculative_sampling_matches_target_distribution():
    """Sampling-mode spec decode (temperature/top-p accept-reject with
    leftover resample) is exact IN DISTRIBUTION: marginals of the first two
    generated positions match vanilla temperature sampling of the target
    within Monte-Carlo noise, and a perfect draft (q == p) accepts every
    proposal."""
    from ray_tpu.models.generate import speculative_generate

    cfg = _cfg(vocab_size=12, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2, d_ff=48)
    draft_cfg = _cfg(vocab_size=12, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2, d_ff=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_params(jax.random.PRNGKey(9), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, cfg.vocab_size)
    V, TEMP, N = cfg.vocab_size, 0.9, 1500

    # Perfect draft: q == p at every position -> acceptance prob 1 -> round
    # count collapses like the greedy case.
    _, rounds_perfect = speculative_generate(
        params, params, prompt, cfg, cfg, max_new_tokens=9, k=2,
        temperature=TEMP, key=jax.random.PRNGKey(7),
    )
    assert int(rounds_perfect) <= 4, int(rounds_perfect)

    # Distributional equality vs vanilla sampling (batched over keys via
    # vmap-free loop batching: B=N rows of the same prompt in ONE call each
    # path — cheap at these shapes).
    prompts = jnp.broadcast_to(prompt, (N, prompt.shape[1]))
    spec_toks, _ = speculative_generate(
        params, draft_params, prompts, cfg, draft_cfg, max_new_tokens=2, k=2,
        temperature=TEMP, key=jax.random.PRNGKey(3),
    )
    ref_toks = generate(
        params, prompts, cfg, max_new_tokens=2, temperature=TEMP,
        key=jax.random.PRNGKey(11),
    )
    spec_toks, ref_toks = np.asarray(spec_toks), np.asarray(ref_toks)
    for pos in range(2):
        h_spec = np.bincount(spec_toks[:, pos], minlength=V) / N
        h_ref = np.bincount(ref_toks[:, pos], minlength=V) / N
        tv = 0.5 * np.abs(h_spec - h_ref).sum()
        # TV between two N-sample empiricals of the same law concentrates
        # around ~sqrt(V/(pi*N)); 0.08 is ~3x that for V=12, N=1500.
        assert tv < 0.08, f"position {pos}: TV {tv:.3f} (spec {h_spec}, ref {h_ref})"


def test_speculative_sampling_acceptance_matches_theory():
    """Empirical first-draft acceptance rate matches sum_x min(p(x), q(x))
    computed from the two models' actual (temperature-processed)
    distributions at that position."""
    from ray_tpu.models.generate import _processed_probs, speculative_generate

    cfg = _cfg(vocab_size=10, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2, d_ff=48)
    draft_cfg = _cfg(vocab_size=10, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2, d_ff=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    draft_params = init_params(jax.random.PRNGKey(9), draft_cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, cfg.vocab_size)
    V, TEMP, N = cfg.vocab_size, 1.0, 1200

    # Theory: acceptance prob of draft 1 = E_{t0~p0}[ sum_x min(p1(x|t0),
    # q1(x|t0)) ], over every possible first token t0 (teacher-forced
    # no-cache forwards give p1/q1 exactly).
    logits0, _ = forward(params, prompt, cfg)
    p0 = np.asarray(_processed_probs(logits0[:, -1], TEMP, 1.0))[0]
    theory = 0.0
    for t0 in range(V):
        ext = jnp.concatenate([prompt, jnp.full((1, 1), t0, jnp.int32)], axis=1)
        lt, _ = forward(params, ext, cfg)
        ld, _ = forward(draft_params, ext, draft_cfg)
        p1 = np.asarray(_processed_probs(lt[:, -1], TEMP, 1.0))[0]
        q1 = np.asarray(_processed_probs(ld[:, -1], TEMP, 1.0))[0]
        theory += p0[t0] * np.minimum(p1, q1).sum()

    # Empirical: with max_new_tokens=3, k=1, the first round emits
    # 1 + accepted tokens on top of the prefill token: acceptance finishes
    # in ONE round (1+2=3), rejection leaves n=2 and forces a second.
    # rounds is a global counter, so run B=1 trials sequentially (tiny
    # model; the jit is cached after the first call).
    accepted = 0
    trials = 150
    for i in range(trials):
        _, rounds = speculative_generate(
            params, draft_params, prompt, cfg, draft_cfg, max_new_tokens=3,
            k=1, temperature=TEMP, key=jax.random.PRNGKey(100 + i),
        )
        if int(rounds) == 1:
            accepted += 1
    emp = accepted / trials
    se = (theory * (1 - theory) / trials) ** 0.5
    assert abs(emp - float(theory)) < 4 * se + 0.02, (
        f"acceptance {emp:.3f} vs theory {float(theory):.3f} (se {se:.3f})"
    )
