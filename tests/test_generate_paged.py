"""Paged KV-cache decode correctness (ISSUE 11 tentpole, model layer).

The serving oracle: paged attention over a block table must produce the SAME
tokens as the dense-cache path for any schedule the engine can produce —
fragmented/out-of-order physical blocks, inactive slots sharing the batch,
write-masked padded prefill chunks. Dense decode_step/decode_chunk are the
reference; tokens (argmax chains) must match exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.generate import (
    decode_chunk,
    init_cache,
    init_paged_cache,
    paged_decode_chunk,
    paged_decode_step,
    prefill,
)
from ray_tpu.models.transformer import TransformerConfig, init_params


def _cfg(**kw):
    base = dict(
        vocab_size=128,
        d_model=48,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=64,
        max_seq_len=64,
        dtype=jnp.float32,
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _paged_prefill(params, toks, cache, table, cfg, chunk=4):
    """Chunked prefill of a single sequence through paged_decode_chunk
    (exactly what the serving engine does): fixed [1, chunk] shape, padded
    final chunk write-masked via valid_to."""
    T = len(toks)
    logits = None
    p = 0
    while p < T:
        piece = toks[p : p + chunk]
        fed = piece + [0] * (chunk - len(piece))
        logits, cache = paged_decode_chunk(
            params,
            jnp.asarray([fed], jnp.int32),
            cache,
            jnp.asarray([table], jnp.int32),
            jnp.asarray([p], jnp.int32),
            cfg,
            valid_to=jnp.asarray([T], jnp.int32),
        )
        p += len(piece)
    last_row = (T - 1) % chunk if T % chunk else chunk - 1
    return logits[:, last_row], cache


def test_paged_decode_matches_dense():
    """Greedy continuation over a paged cache with a FRAGMENTED, out-of-order
    block table matches dense prefill+decode token for token (GQA config —
    the KV==H attention branch is covered by the valid_to test below)."""
    cfg = _cfg(n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 7), 0, cfg.vocab_size)
    toks = np.asarray(prompt)[0].tolist()
    bs, n_new = 4, 6

    # Dense reference: prefill + greedy decode steps.
    dcache = init_cache(cfg, 1, 32)
    dlogits, dcache, dpos = prefill(params, prompt, dcache, cfg)
    want = []
    cur = int(np.asarray(dlogits).argmax())
    from ray_tpu.models.generate import decode_step

    for _ in range(n_new):
        want.append(cur)
        dlogits, dcache = decode_step(
            params, jnp.asarray([cur], jnp.int32), dcache, dpos, cfg
        )
        dpos = dpos + 1
        cur = int(np.asarray(dlogits).argmax())

    # Paged: deliberately fragmented physical blocks (never 0 — reserved).
    table = [5, 2, 7, 1]  # covers 16 positions at block_size 4
    pcache = init_paged_cache(cfg, num_blocks=9, block_size=bs)
    plogits, pcache = _paged_prefill(params, toks, pcache, table, cfg, chunk=4)
    got = []
    cur = int(np.asarray(plogits)[0].argmax())
    pos = len(toks)
    for _ in range(n_new):
        got.append(cur)
        step_logits, pcache = paged_decode_step(
            params,
            jnp.asarray([cur], jnp.int32),
            pcache,
            jnp.asarray([table], jnp.int32),
            jnp.asarray([pos], jnp.int32),
            cfg,
        )
        pos += 1
        cur = int(np.asarray(step_logits)[0].argmax())
    assert got == want


def test_paged_multi_slot_batch_matches_solo_and_inactive_slots_are_inert():
    """A multi-slot decode batch (different positions per slot, one slot
    INACTIVE) produces per-slot logits matching each sequence decoded alone
    — slots must not couple, and the inactive slot must stay finite."""
    cfg = _cfg(n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    bs = 4
    seqs = [
        np.asarray(
            jax.random.randint(jax.random.PRNGKey(i + 1), (n,), 0, cfg.vocab_size)
        ).tolist()
        for i, n in enumerate((5, 9))
    ]
    # Shared pool: slot 0 owns blocks [1,2,3], slot 1 owns [4,5,6], slot 2
    # inactive (all-zero table).
    tables = [[1, 2, 3], [4, 5, 6], [0, 0, 0]]
    cache = init_paged_cache(cfg, num_blocks=8, block_size=bs)
    last = {}
    for slot, toks in enumerate(seqs):
        logits, cache = _paged_prefill(params, toks, cache, tables[slot], cfg)
        last[slot] = int(np.asarray(logits)[0].argmax())

    # One batched step across all three slots.
    step_tok = jnp.asarray([last[0], last[1], 0], jnp.int32)
    step_pos = jnp.asarray([len(seqs[0]), len(seqs[1]), 0], jnp.int32)
    logits_b, _ = paged_decode_step(
        params, step_tok, cache, jnp.asarray(tables, jnp.int32), step_pos, cfg
    )
    logits_b = np.asarray(logits_b)
    assert np.isfinite(logits_b).all(), "inactive slot leaked non-finite values"

    # Solo reference per sequence via the DENSE path.
    for slot, toks in enumerate(seqs):
        dcache = init_cache(cfg, 1, 32)
        dlogits, dcache, dpos = prefill(
            params, jnp.asarray([toks], jnp.int32), dcache, cfg
        )
        assert int(np.asarray(dlogits).argmax()) == last[slot]
        from ray_tpu.models.generate import decode_step

        ref, _ = decode_step(
            params, jnp.asarray([last[slot]], jnp.int32), dcache, dpos, cfg
        )
        assert int(logits_b[slot].argmax()) == int(np.asarray(ref)[0].argmax())


def test_paged_valid_to_masks_padded_writes():
    """A padded prefill chunk must not write beyond valid_to: the blocks
    covering the padding stay bit-identical to their pre-call state, and
    the null block absorbs the masked rows."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    bs = 4
    cache = init_paged_cache(cfg, num_blocks=6, block_size=bs)
    table = [1, 2, 3]
    toks = [7, 3, 9, 1, 5]  # 5 real tokens, chunk 8 -> 3 padded rows
    before_b3 = np.asarray(cache["k"][:, 3])
    fed = toks + [0] * 3
    _, cache = paged_decode_chunk(
        params,
        jnp.asarray([fed], jnp.int32),
        cache,
        jnp.asarray([table], jnp.int32),
        jnp.asarray([0], jnp.int32),
        cfg,
        valid_to=jnp.asarray([5], jnp.int32),
    )
    # Positions 5..7 live in blocks 2 (rows 1..3): those rows must be
    # untouched zeros; block 3 (positions 8..11) entirely untouched.
    np.testing.assert_array_equal(np.asarray(cache["k"][:, 3]), before_b3)
    assert float(jnp.abs(cache["k"][:, 2, 1:]).sum()) == 0.0
    # Real rows WERE written (block 1 rows 0..3, block 2 row 0).
    assert float(jnp.abs(cache["k"][:, 1]).sum()) > 0.0
    assert float(jnp.abs(cache["k"][:, 2, 0]).sum()) > 0.0


def test_paged_chunk_matches_dense_chunk_with_window():
    """Sliding-window config: multi-token paged decode_chunk logits match
    the dense decode_chunk on the same continuation."""
    cfg = _cfg(sliding_window=6, n_kv_heads=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size)
    extra = jax.random.randint(jax.random.PRNGKey(2), (1, 3), 0, cfg.vocab_size)
    toks = np.asarray(prompt)[0].tolist()

    dcache = init_cache(cfg, 1, 16)
    _, dcache, dpos = prefill(params, prompt, dcache, cfg)
    dense, _ = decode_chunk(params, extra, dcache, dpos, cfg)

    bs = 4
    table = [3, 1, 2, 4]
    pcache = init_paged_cache(cfg, num_blocks=5, block_size=bs)
    _, pcache = _paged_prefill(params, toks, pcache, table, cfg, chunk=3)
    paged, _ = paged_decode_chunk(
        params,
        extra,
        pcache,
        jnp.asarray([table], jnp.int32),
        jnp.asarray([6], jnp.int32),
        cfg,
    )
    assert (
        np.asarray(paged).argmax(-1) == np.asarray(dense).argmax(-1)
    ).all()
    np.testing.assert_allclose(
        np.asarray(paged), np.asarray(dense), rtol=2e-4, atol=2e-5
    )
