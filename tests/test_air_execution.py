"""AIR execution layer tests (reference: python/ray/air/execution — the
RayActorManager + resource manager substrate adopted by Tune and Train).

Covers the failure paths the layer exists for: pooled actor killed mid-task
(on_actor_failure fires, restart counter increments, the replacement is
rescheduled), restart budget exhaustion, clean cancellation of in-flight
tasks on removal, and — the leak audit — placement-group release on every
exit path (no reserved bundle survives in GlobalState)."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.air.execution import (
    ActorManager,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceRequest,
)


class _Worker:
    def __init__(self, tag="w"):
        self.tag = tag

    def pid(self):
        return os.getpid()

    def work(self, x):
        return x * 2

    def slow(self):
        time.sleep(30)
        return "done"

    def boom(self):
        raise ValueError("app-level")


def _drive(mgr, pred, timeout=60.0, step=0.25):
    """Pump manager events until pred() or timeout; returns pred()."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and not pred():
        mgr.next(timeout=step)
    return pred()


def _no_reserved_pgs():
    from ray_tpu._private.state import GlobalState

    state = GlobalState()
    return not any(
        pg["state"] in ("CREATED", "PENDING") for pg in state.placement_groups()
    )


def _cluster_cpus_free(timeout=30.0):
    """True once every CPU is back in the availability ledger (release is
    asynchronous: the raylet reaps the worker, then reports to the GCS)."""
    deadline = time.monotonic() + timeout
    while True:
        total = ray_tpu.cluster_resources().get("CPU", 0)
        avail = ray_tpu.available_resources().get("CPU", 0)
        if avail >= total:
            return True
        if time.monotonic() > deadline:
            return False
        time.sleep(0.1)


# ---------- resource managers ----------


def test_fixed_resource_manager_budget(ray_start_regular):
    rm = FixedResourceManager(total_resources={"CPU": 2})
    req1 = ResourceRequest([{"CPU": 1}])
    req2 = ResourceRequest([{"CPU": 1}])
    req3 = ResourceRequest([{"CPU": 1}])
    for r in (req1, req2, req3):
        rm.request_resources(r)
    a1 = rm.acquire_resources(req1)
    a2 = rm.acquire_resources(req2)
    assert a1 is not None and a2 is not None
    assert not rm.has_resources_ready(req3)
    assert rm.acquire_resources(req3) is None
    rm.free_resources(a1)
    assert rm.has_resources_ready(req3)
    # double-free is a no-op, not a budget corruption
    rm.free_resources(a1)
    a3 = rm.acquire_resources(req3)
    assert a3 is not None
    assert not rm.has_resources_ready(ResourceRequest([{"CPU": 1}]))
    rm.clear()
    assert rm.has_resources_ready(ResourceRequest([{"CPU": 2}]))


def test_fixed_manager_actor_options_mapping(ray_start_regular):
    rm = FixedResourceManager(total_resources={"CPU": 4, "TPU": 2, "custom": 1})
    req = ResourceRequest([{"CPU": 2, "TPU": 1, "custom": 1}])
    rm.request_resources(req)
    acq = rm.acquire_resources(req)
    opts = acq.actor_options(0)
    assert opts["num_cpus"] == 2
    assert opts["num_tpus"] == 1
    assert opts["resources"] == {"custom": 1}
    with pytest.raises(IndexError):
        acq.actor_options(1)
    rm.free_resources(acq)


def test_pg_manager_acquire_and_guaranteed_release(ray_start_regular):
    rm = PlacementGroupResourceManager()
    req = ResourceRequest([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    rm.request_resources(req)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not rm.has_resources_ready(req):
        time.sleep(0.1)
    assert rm.has_resources_ready(req)
    acq = rm.acquire_resources(req)
    assert acq is not None and acq.placement_group is not None
    opts = acq.actor_options(1)
    assert opts["scheduling_strategy"].placement_group_bundle_index == 1
    assert not _no_reserved_pgs()  # the PG is live
    rm.free_resources(acq)
    assert _no_reserved_pgs()
    # cancel of a never-acquired request also releases its pending PG
    req2 = ResourceRequest([{"CPU": 1}])
    rm.request_resources(req2)
    rm.cancel_resource_request(req2)
    assert _no_reserved_pgs()
    rm.clear()


# ---------- actor manager: tasks + app errors ----------


def test_actor_task_callbacks_and_app_error(ray_start_regular):
    mgr = ActorManager(FixedResourceManager())
    results, errors, started = [], [], []
    t = mgr.add_actor(
        _Worker,
        {"tag": "a"},
        resource_request=ResourceRequest([{"CPU": 1}]),
        on_start=lambda tr: started.append(tr.tracked_id),
    )
    # Scheduled before the actor is up: queued, then submitted on start.
    mgr.schedule_actor_task(t, "work", (21,), on_result=results.append)
    assert _drive(mgr, lambda: results == [42])
    assert started and t.state == "ALIVE"
    # An application exception is a TASK error: actor stays alive.
    mgr.schedule_actor_task(t, "boom", on_error=lambda e: errors.append(e))
    assert _drive(mgr, lambda: len(errors) == 1)
    assert t.state == "ALIVE" and t.restart_count == 0
    mgr.schedule_actor_task(t, "work", (5,), on_result=results.append)
    assert _drive(mgr, lambda: 10 in results)
    mgr.clear()
    assert _cluster_cpus_free()


def test_remove_actor_cancels_inflight_cleanly(ray_start_regular):
    mgr = ActorManager(FixedResourceManager())
    fired = []
    t = mgr.add_actor(_Worker, resource_request=ResourceRequest([{"CPU": 1}]))
    assert _drive(mgr, lambda: t.state == "ALIVE")
    mgr.schedule_actor_task(
        t, "slow", on_result=fired.append, on_error=fired.append
    )
    mgr.next(timeout=0.5)
    mgr.remove_actor(t)
    assert t.state == "STOPPED"
    # the cancelled in-flight task's callbacks never fire
    for _ in range(8):
        mgr.next(timeout=0.25)
    assert fired == []
    with pytest.raises(ValueError):
        mgr.schedule_actor_task(t, "work", (1,))
    mgr.clear()


# ---------- chaos: SIGKILL a managed actor ----------


def test_chaos_sigkill_restarts_and_releases_pg(ray_start_regular):
    """The acceptance-criteria chaos test: SIGKILL a pooled PG-backed actor
    mid-task; on_actor_failure fires, the restart counter increments, the
    replacement actor serves rescheduled work, and removal releases the
    placement group — no reserved bundles remain in GlobalState."""
    mgr = ActorManager(PlacementGroupResourceManager())
    failures, results = [], []
    t = mgr.add_actor(
        _Worker,
        {"tag": "chaos"},
        resource_request=ResourceRequest([{"CPU": 1}]),
        max_restarts=2,
        restart_backoff_s=0.1,
        on_failure=lambda tr, err, will_restart: failures.append(
            (type(err).__name__, will_restart)
        ),
    )
    assert _drive(mgr, lambda: t.state == "ALIVE")
    pids = []
    mgr.schedule_actor_task(t, "pid", on_result=pids.append)
    assert _drive(mgr, lambda: pids)

    # Kill the actor process while a task is in flight.
    mgr.schedule_actor_task(t, "slow", on_result=results.append)
    mgr.next(timeout=0.5)
    os.kill(pids[0], signal.SIGKILL)

    assert _drive(mgr, lambda: t.restart_count == 1 and t.state == "ALIVE", timeout=90)
    assert failures and failures[0][1] is True  # will_restart
    assert results == []  # the doomed task's callback was swallowed, not faked

    # The replacement is schedulable and is a NEW process.
    mgr.schedule_actor_task(t, "pid", on_result=pids.append)
    mgr.schedule_actor_task(t, "work", (100,), on_result=results.append)
    assert _drive(mgr, lambda: 200 in results and len(pids) == 2)
    assert pids[1] != pids[0]

    mgr.remove_actor(t)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not _no_reserved_pgs():
        time.sleep(0.1)
    assert _no_reserved_pgs()
    mgr.clear()


def test_restart_budget_exhausted_fails_and_releases(ray_start_regular):
    mgr = ActorManager(PlacementGroupResourceManager())
    failures = []
    t = mgr.add_actor(
        _Worker,
        resource_request=ResourceRequest([{"CPU": 1}]),
        max_restarts=0,
        on_failure=lambda tr, err, will_restart: failures.append(will_restart),
    )
    assert _drive(mgr, lambda: t.state == "ALIVE")
    pids = []
    mgr.schedule_actor_task(t, "pid", on_result=pids.append)
    assert _drive(mgr, lambda: pids)
    os.kill(pids[0], signal.SIGKILL)
    assert _drive(mgr, lambda: t.state == "FAILED", timeout=90)
    assert failures == [False]
    assert t.last_error is not None
    # terminal failure released the PG without an explicit remove_actor
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not _no_reserved_pgs():
        time.sleep(0.1)
    assert _no_reserved_pgs()
    mgr.clear()


# ---------- gang semantics ----------


def test_gang_shares_one_pg_released_with_last_member(ray_start_regular):
    """A multi-bundle request shared by N actors holds ONE placement group,
    refcounted: removing one member keeps it, removing the last frees it."""
    from ray_tpu._private.state import GlobalState

    mgr = ActorManager(PlacementGroupResourceManager())
    req = ResourceRequest([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    gang = [
        mgr.add_actor(
            _Worker, {"tag": f"g{i}"}, resource_request=req, bundle_index=i
        )
        for i in range(2)
    ]
    mgr.wait_for_actors(gang, timeout=60)
    state = GlobalState()
    live = [pg for pg in state.placement_groups() if pg["state"] == "CREATED"]
    assert len(live) == 1 and len(live[0]["bundles"]) == 2

    mgr.remove_actor(gang[0])
    live = [pg for pg in state.placement_groups() if pg["state"] == "CREATED"]
    assert len(live) == 1  # still held by the surviving member

    mgr.remove_actor(gang[1])
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not _no_reserved_pgs():
        time.sleep(0.1)
    assert _no_reserved_pgs()
    mgr.clear()


def test_backend_executor_gang_restart_releases_resources(ray_start_regular):
    """Train's gang restart through the manager must not leak acquisitions:
    after a worker death + whole-gang restart + shutdown, the full CPU
    budget is back and no tracked actor survives."""
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train._internal.backend_executor import BackendExecutor, JaxBackend

    marker = f"/tmp/rtpu_air_gang_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    def flaky_loop(config):
        import os as _os

        from ray_tpu.air import session

        if not _os.path.exists(config["marker"]):
            with open(config["marker"], "w") as f:
                f.write("1")
            _os._exit(1)
        session.report({"ok": 1})

    executor = BackendExecutor(
        JaxBackend(), ScalingConfig(num_workers=1), max_failures=1
    )
    executor.start()
    reports = executor.run(flaky_loop, config={"marker": marker})
    assert reports[0]["ok"] == 1
    assert executor.num_gang_restarts == 1
    executor.shutdown()
    assert executor._actor_manager.num_tracked_actors == 0
    assert _cluster_cpus_free()
    os.unlink(marker)
