"""Core task/object API tests (analog of the reference's python/ray/tests/test_basic.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


def test_put_get(ray_start_regular):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3], "b": "x"})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3], "b": "x"}


def test_put_get_large_numpy(ray_start_regular):
    arr = np.random.default_rng(0).standard_normal((512, 512)).astype(np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=60) == 2


def test_task_kwargs_and_defaults(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1), timeout=60) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3), timeout=60) == 6


def test_many_parallel_tasks(ray_start_regular):
    @ray_tpu.remote
    def sq(i):
        return i * i

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(20)]


def test_task_with_ref_arg(ray_start_regular):
    @ray_tpu.remote
    def plus(x, y):
        return x + y

    a = ray_tpu.put(10)
    b = plus.remote(a, 5)
    c = plus.remote(b, a)
    assert ray_tpu.get(c, timeout=60) == 25


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=60) + 1

    assert ray_tpu.get(outer.remote(10), timeout=120) == 21


def test_num_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c], timeout=60) == [1, 2, 3]


def test_options_override(ray_start_regular):
    @ray_tpu.remote
    def f():
        return "ok"

    ref = f.options(name="custom", num_cpus=1).remote()
    assert ray_tpu.get(ref, timeout=60) == "ok"


def test_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_error_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(TaskError):
        ray_tpu.get(consume.remote(boom.remote()), timeout=60)


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        import time

        time.sleep(30)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_wait(ray_start_regular):
    import time

    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(60)

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=30)
    assert ready == [f]
    assert not_ready == [s]


def test_direct_call_raises(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_cluster_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4


def test_large_arg_auto_plasma(ray_start_regular):
    arr = np.ones((1024, 512), dtype=np.float32)  # 2 MB > inline cutoff

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(arr), timeout=60) == float(arr.sum())


def test_object_ref_in_container(ray_start_regular):
    inner_ref = ray_tpu.put(7)

    @ray_tpu.remote
    def unwrap(d):
        return ray_tpu.get(d["ref"], timeout=30) + 1

    assert ray_tpu.get(unwrap.remote({"ref": inner_ref}), timeout=60) == 8


def test_rpc_wire_schema_validation(ray_start_regular):
    """N4 analog of protobuf message types: msgpack payloads are validated
    against per-handler schemas at dispatch — malformed frames get a typed
    schema-violation error instead of a handler stack trace, and unknown
    extra keys pass (proto3-style forward compatibility)."""
    from ray_tpu._private import worker_context
    from ray_tpu._private.rpc import validate_payload

    cw = worker_context.get_core_worker()
    # Well-formed call passes.
    assert cw.gcs.call("kv_put", {"key": "schema/x", "value": b"1"})["ok"]
    # Missing required field -> schema violation, not a KeyError traceback.
    import pytest as _pytest

    with _pytest.raises(Exception, match="schema violation"):
        cw.gcs.call("kv_put", {"value": b"1"})
    # Wrong type.
    with _pytest.raises(Exception, match="schema violation"):
        cw.gcs.call("kv_put", {"key": 42, "value": b"1"})
    # Extra keys tolerated.
    assert cw.gcs.call("kv_put", {"key": "schema/y", "value": b"2", "future_field": 1})["ok"]
    # Validator unit behavior: optional fields.
    assert validate_payload({}, {"a": [int]}) is None
    assert validate_payload({"a": "x"}, {"a": [int]}) is not None
