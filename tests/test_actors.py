"""Actor tests (analog of the reference's python/ray/tests/test_actor.py family)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.v = start

    def inc(self, d=1):
        self.v += d
        return self.v

    def get(self):
        return self.v

    def boom(self):
        raise RuntimeError("actor method failed")

    def die(self):
        import os

        os._exit(1)


def test_actor_basic(ray_start_regular):
    c = Counter.remote(5)
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 6
    assert ray_tpu.get(c.get.remote(), timeout=30) == 6


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    out = ray_tpu.get([c.inc.remote() for _ in range(50)], timeout=120)
    assert out == list(range(1, 51))


def test_actor_method_error(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(TaskError, match="actor method failed"):
        ray_tpu.get(c.boom.remote(), timeout=60)
    # Actor survives a method exception.
    assert ray_tpu.get(c.inc.remote(), timeout=30) == 1


def test_named_actor(ray_start_regular):
    Counter.options(name="counter-x").remote(100)
    handle = ray_tpu.get_actor("counter-x")
    assert ray_tpu.get(handle.inc.remote(), timeout=60) == 101


def test_get_if_exists(ray_start_regular):
    a = Counter.options(name="shared", get_if_exists=True).remote(0)
    ray_tpu.get(a.inc.remote(), timeout=60)
    b = Counter.options(name="shared", get_if_exists=True).remote(0)
    assert ray_tpu.get(b.inc.remote(), timeout=60) == 2


def test_actor_handle_in_task(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(h):
        return ray_tpu.get(h.inc.remote(), timeout=30)

    assert ray_tpu.get(bump.remote(c), timeout=60) == 1
    assert ray_tpu.get(c.get.remote(), timeout=30) == 1


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=60)
    ray_tpu.kill(c)
    time.sleep(0.3)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_crash_raises(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=60)
    c.die.remote()
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_restart(ray_start_regular):
    RestartingCounter = Counter.options(max_restarts=1, max_task_retries=2)
    c = RestartingCounter.remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    # kill(no_restart=False) tears down the process but leaves the restart
    # budget to bring up a fresh incarnation (an actor-method suicide would be
    # retried by max_task_retries and burn the restart budget repeatedly).
    ray_tpu.kill(c, no_restart=False)
    # After restart, state resets; retried call should succeed on the new
    # incarnation (reference: max_restarts + max_task_retries semantics).
    deadline = time.monotonic() + 60
    value = None
    while time.monotonic() < deadline:
        try:
            value = ray_tpu.get(c.inc.remote(), timeout=30)
            break
        except Exception:
            time.sleep(0.2)
    # Retried actor tasks are at-least-once: an inc whose reply was lost to
    # the kill may have executed on the new incarnation before our loop's
    # attempt, so the counter restarts at 1 but may legitimately read 2.
    assert value in (1, 2)


def test_threaded_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self, t):
            time.sleep(t)
            return t

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(0.01), timeout=60)  # warm up (worker spawn)
    start = time.monotonic()
    refs = [s.nap.remote(0.5) for _ in range(4)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0.5] * 4
    # 4 concurrent naps should take well under 4 * 0.5s.
    assert time.monotonic() - start < 1.9


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncActor:
        async def ping(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x + 1

    a = AsyncActor.remote()
    assert ray_tpu.get(a.ping.remote(1), timeout=60) == 2
