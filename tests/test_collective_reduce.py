"""Tree reduce / allreduce on the device-object collective plane (ISSUE 16).

- Bit-exact oracle: the tree allreduce (reduce up the binomial tree with
  chunk-wise combine at relay hops, broadcast back down) matches the flat
  GCS-ring ``allreduce`` bit for bit across K ∈ {2, 4, 8} and the odd
  K = 5 — integer-valued float32 payloads so SUM is exact regardless of
  combine order.
- Verb semantics: ``reduce_send_payload`` lands the result ONLY on
  ``dst_rank`` (None elsewhere); MEAN sums up the tree and divides once at
  the root; a jax input comes back as a jax.Array on EVERY rank (the root
  finalizes once before the down-broadcast — payload-parity contract),
  while an np input stays np.
- ``device_object.allreduce``: a gang of holders combines their residents
  IN PLACE (each ref resolves to the reduced value afterwards; no extra
  residents appear).
- Typed failures: a silent child rank raises CollectiveTimeoutError
  NAMING it; a partitioned GCS makes ``fetch_member_addrs`` raise instead
  of reading as "nobody registered".

One module-scoped cluster; the 8 Red actors are reused across every K
(one collective-group init per K, distinct group names).
"""

import gc

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import CollectiveTimeoutError


@pytest.fixture(scope="module")
def red_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _contribution(rank: int, n: int) -> np.ndarray:
    """Integer-valued float32, distinct per rank: float32 SUM over ranks is
    EXACT, so tree-vs-ring comparisons are bit-for-bit, not tolerance."""
    return ((np.arange(n) % 97) + 3.0 * rank).astype(np.float32)


def _scatter_input(rank: int, k: int, n: int) -> np.ndarray:
    """Reduce-scatter input: leading dim == member count, every (rank,
    slice) cell distinct, still integer-valued float32 (exact SUM)."""
    return ((np.arange(k * n).reshape(k, n) % 97) + 3.0 * rank).astype(np.float32)


@ray_tpu.remote
class Red:
    """One reduce-group member: joins groups and runs the payload verbs."""

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def tree_allreduce(self, group_name, tag, n, op="SUM"):
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        g = col.get_group(group_name)
        out = g.allreduce_payload(_contribution(g.rank, n), tag, op=ReduceOp[op])
        return np.asarray(out)

    def ring_allreduce(self, group_name, n, op="SUM"):
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        g = col.get_group(group_name)
        return np.asarray(g.allreduce(_contribution(g.rank, n), op=ReduceOp[op]))

    def tree_reduce(self, group_name, tag, n, dst_rank=0):
        from ray_tpu.util import collective as col

        g = col.get_group(group_name)
        out = g.reduce_send_payload(_contribution(g.rank, n), tag, dst_rank=dst_rank)
        return None if out is None else np.asarray(out)

    def tree_allreduce_typed(self, group_name, tag, n, as_jax):
        """(type name, is-jax-array) of the allreduce output — the
        placement-parity probe."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        g = col.get_group(group_name)
        v = _contribution(g.rank, n)
        out = g.allreduce_payload(jnp.asarray(v) if as_jax else v, tag)
        return type(out).__name__, isinstance(out, jax.Array)

    def tree_reducescatter(self, group_name, tag, k, n, op="SUM"):
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        g = col.get_group(group_name)
        out = g.reducescatter_payload(_scatter_input(g.rank, k, n), tag, op=ReduceOp[op])
        return np.asarray(out)

    def ring_reducescatter(self, group_name, k, n, op="SUM"):
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        g = col.get_group(group_name)
        return np.asarray(g.reducescatter(_scatter_input(g.rank, k, n), op=ReduceOp[op]))

    def tree_reducescatter_typed(self, group_name, tag, k, n, as_jax):
        import jax
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        g = col.get_group(group_name)
        v = _scatter_input(g.rank, k, n)
        out = g.reducescatter_payload(jnp.asarray(v) if as_jax else v, tag)
        return type(out).__name__, isinstance(out, jax.Array)

    def coll_stats(self):
        from ray_tpu.util.collective.p2p import COLL

        return {k: getattr(COLL, k) for k in COLL.__slots__}


@ray_tpu.remote(tensor_transport="collective")
class Holder:
    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def make(self, n, rank):
        import jax.numpy as jnp

        return jnp.asarray(_contribution(rank, n))

    def residents(self):
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()["resident_count"]


# ---------------------------------------------------------------------------
# bit-exact oracle: tree allreduce == flat ring allreduce
# ---------------------------------------------------------------------------


def test_tree_allreduce_bit_exact_vs_ring_oracle(red_cluster):
    actors = [Red.remote() for _ in range(8)]
    # K=4 and the odd K=5 use a MULTI-chunk payload (640 KiB+tail at f32)
    # so chunk-wise combine at relay hops — including the ragged tail
    # chunk — is on the oracle path; the other Ks stay small for speed.
    for k, n in [(2, 4096), (4, 160 * 1024 + 7), (5, 160 * 1024 + 7), (8, 32768)]:
        group = f"oracle{k}"
        gang = actors[:k]
        ray_tpu.get(
            [a.init_collective.remote(k, i, "cpu", group) for i, a in enumerate(gang)],
            timeout=60,
        )
        expected = np.sum(
            [_contribution(r, n) for r in range(k)], axis=0, dtype=np.float64
        ).astype(np.float32)
        tree = ray_tpu.get(
            [a.tree_allreduce.remote(group, f"t{k}", n) for a in gang], timeout=120
        )
        for rank, out in enumerate(tree):
            np.testing.assert_array_equal(out, expected, err_msg=f"K={k} rank={rank}")
        ring = ray_tpu.get([a.ring_allreduce.remote(group, n) for a in gang], timeout=120)
        for rank, out in enumerate(ring):
            # The flat-ring oracle is bit-identical, not merely close.
            np.testing.assert_array_equal(out, tree[rank], err_msg=f"K={k} rank={rank}")
        stats = ray_tpu.get(gang[0].coll_stats.remote(), timeout=30)
        assert stats["reduce_sends"] >= 1, stats
        assert stats["allreduces"] >= 1, stats


def test_tree_reduce_lands_only_on_dst_rank(red_cluster):
    actors = [Red.remote() for _ in range(3)]
    group = "dst3"
    ray_tpu.get(
        [a.init_collective.remote(3, i, "cpu", group) for i, a in enumerate(actors)],
        timeout=60,
    )
    n = 2048
    outs = ray_tpu.get(
        [a.tree_reduce.remote(group, "r1", n, 2) for a in actors], timeout=60
    )
    assert outs[0] is None and outs[1] is None
    expected = np.sum(
        [_contribution(r, n) for r in range(3)], axis=0, dtype=np.float64
    ).astype(np.float32)
    np.testing.assert_array_equal(outs[2], expected)


def test_tree_allreduce_mean_divides_once_at_root(red_cluster):
    actors = [Red.remote() for _ in range(4)]
    group = "mean4"
    ray_tpu.get(
        [a.init_collective.remote(4, i, "cpu", group) for i, a in enumerate(actors)],
        timeout=60,
    )
    n = 2048
    outs = ray_tpu.get(
        [a.tree_allreduce.remote(group, "m1", n, "MEAN") for a in actors], timeout=60
    )
    # Integer sum / 4 (a power of two) is exact in float32.
    expected = (
        np.sum([_contribution(r, n) for r in range(4)], axis=0, dtype=np.float64) / 4.0
    ).astype(np.float32)
    for out in outs:
        np.testing.assert_array_equal(out, expected)


def test_tree_allreduce_placement_parity(red_cluster):
    actors = [Red.remote() for _ in range(2)]
    group = "place2"
    ray_tpu.get(
        [a.init_collective.remote(2, i, "cpu", group) for i, a in enumerate(actors)],
        timeout=60,
    )
    jax_outs = ray_tpu.get(
        [a.tree_allreduce_typed.remote(group, "pj", 1024, True) for a in actors],
        timeout=60,
    )
    for _, is_jax in jax_outs:
        assert is_jax  # jax in -> jax out on EVERY rank (root finalized once)
    np_outs = ray_tpu.get(
        [a.tree_allreduce_typed.remote(group, "pn", 1024, False) for a in actors],
        timeout=60,
    )
    for name, is_jax in np_outs:
        assert not is_jax, name  # np in -> np out (no surprise device hop)


# ---------------------------------------------------------------------------
# reduce-scatter (ISSUE 20 satellite): tree verb == flat ring oracle
# ---------------------------------------------------------------------------


def test_tree_reducescatter_bit_exact_vs_ring_oracle(red_cluster):
    actors = [Red.remote() for _ in range(5)]
    # K=4 uses a multi-chunk payload so chunk-wise combine on the reduce leg
    # is on the oracle path; the odd K=5 covers a non-power-of-two tree.
    for k, n in [(2, 4096), (4, 48 * 1024 + 7), (5, 2048)]:
        group = f"scat{k}"
        gang = actors[:k]
        ray_tpu.get(
            [a.init_collective.remote(k, i, "cpu", group) for i, a in enumerate(gang)],
            timeout=60,
        )
        full = np.sum(
            [_scatter_input(r, k, n) for r in range(k)], axis=0, dtype=np.float64
        ).astype(np.float32)
        # np.array: gets deserialize zero-copy over shm, and per-rank
        # DIFFERENT payloads must be materialized before the next round of
        # gets can recycle the arena pages under them (the allreduce oracle
        # never notices — every rank's output there is identical bytes).
        tree = [
            np.array(t)
            for t in ray_tpu.get(
                [a.tree_reducescatter.remote(group, f"s{k}", k, n) for a in gang],
                timeout=120,
            )
        ]
        for rank, out in enumerate(tree):
            # Rank i gets reduced slice i, bit-for-bit.
            np.testing.assert_array_equal(out, full[rank], err_msg=f"K={k} rank={rank}")
        ring = ray_tpu.get(
            [a.ring_reducescatter.remote(group, k, n) for a in gang], timeout=120
        )
        for rank, out in enumerate(ring):
            np.testing.assert_array_equal(out, tree[rank], err_msg=f"K={k} rank={rank}")
    stats = ray_tpu.get(actors[0].coll_stats.remote(), timeout=30)
    assert stats["reducescatters"] >= 3, stats
    assert stats["scatter_bytes"] > 0, stats  # rank 0 is always the root


def test_tree_reducescatter_placement_parity(red_cluster):
    actors = [Red.remote() for _ in range(2)]
    group = "scatplace2"
    ray_tpu.get(
        [a.init_collective.remote(2, i, "cpu", group) for i, a in enumerate(actors)],
        timeout=60,
    )
    jax_outs = ray_tpu.get(
        [a.tree_reducescatter_typed.remote(group, "sj", 2, 512, True) for a in actors],
        timeout=60,
    )
    for _, is_jax in jax_outs:
        assert is_jax  # jax in -> jax shard out on EVERY rank
    np_outs = ray_tpu.get(
        [a.tree_reducescatter_typed.remote(group, "sn", 2, 512, False) for a in actors],
        timeout=60,
    )
    for name, is_jax in np_outs:
        assert not is_jax, name


# ---------------------------------------------------------------------------
# device_object.allreduce: holders combine residents IN PLACE
# ---------------------------------------------------------------------------


def test_device_object_allreduce_in_place(red_cluster):
    from ray_tpu.experimental import device_object

    holders = [Holder.remote() for _ in range(3)]
    group = "doar3"
    ray_tpu.get(
        [h.init_collective.remote(3, i, "cpu", group) for i, h in enumerate(holders)],
        timeout=60,
    )
    n = 4096
    refs = [h.make.remote(n, i) for i, h in enumerate(holders)]
    ray_tpu.wait(refs, num_returns=3, timeout=60)
    before = sum(ray_tpu.get([h.residents.remote() for h in holders], timeout=30))
    info = device_object.allreduce(refs, group, timeout=120)
    assert info["kind"] == "collective", info
    assert info["mode"] == "allreduce" and info["op"] == "SUM", info
    assert sorted(info["ok_ranks"]) == [0, 1, 2], info
    assert info["failed"] == {}, info
    # Every ref now resolves to the SAME combined value — replaced in
    # place, no extra residents.
    expected = np.sum(
        [_contribution(r, n) for r in range(3)], axis=0, dtype=np.float64
    ).astype(np.float32)
    for ref in refs:
        np.testing.assert_array_equal(np.asarray(ray_tpu.get(ref, timeout=60)), expected)
    after = sum(ray_tpu.get([h.residents.remote() for h in holders], timeout=30))
    assert after == before, (before, after)
    del refs, info
    gc.collect()


# ---------------------------------------------------------------------------
# typed failures
# ---------------------------------------------------------------------------


def test_silent_child_raises_typed_timeout_naming_rank(red_cluster):
    from ray_tpu.util import collective as col

    lurker = Red.remote()
    group = "silent2"
    g = col.init_collective_group(2, 0, backend="cpu", group_name=group)
    try:
        ray_tpu.get(lurker.init_collective.remote(2, 1, "cpu", group), timeout=60)
        with pytest.raises(CollectiveTimeoutError) as ei:
            g.reduce_send_payload(np.ones((64,), np.float32), "hush", timeout=1.5)
        assert ei.value.group == group
        assert ei.value.ranks == [1]  # the child that never sent, NAMED
        assert ei.value.tag == "hush"
        assert not isinstance(ei.value, TimeoutError)
    finally:
        col.destroy_collective_group(group)


def test_fetch_member_addrs_propagates_gcs_transport_error(red_cluster):
    """A partitioned GCS must surface as a FAILURE, not read as 'nobody
    registered' (which silently degraded every rank to the mailbox
    fallback)."""
    from ray_tpu.util.collective.p2p import fetch_member_addrs

    class _DeadGcs:
        def acall(self, method, params, **kw):
            async def _boom():
                raise ConnectionError("gcs partitioned")

            return _boom()

    with pytest.raises(ConnectionError):
        fetch_member_addrs(_DeadGcs(), "anygroup", 4)
