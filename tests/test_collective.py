"""Collective plane tests (analog of the reference's
python/ray/util/collective/tests — NCCL/GLOO group tests re-targeted at the
XLA-over-mesh and object-store backends)."""

import numpy as np
import pytest

import ray_tpu
from conftest import skip_without_multiprocess_collectives
from ray_tpu.util.collective.types import ReduceOp


class TestTpuGroupSingleProcess:
    """world_size=1: the group degenerates to the local device mesh; ops are
    identity-like but compile the same shard_map programs."""

    def setup_method(self, _):
        from ray_tpu.util.collective.tpu_group import TpuCollectiveGroup

        self.group = TpuCollectiveGroup("g1", world_size=1, rank=0)

    def test_allreduce_identity(self):
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(self.group.allreduce(x))
        np.testing.assert_allclose(out, x)

    def test_allgather(self):
        x = np.arange(4, dtype=np.float32)
        out = np.asarray(self.group.allgather(x))
        assert out.shape == (1, 4)


def test_cpu_collective_group_over_actors(ray_start_regular):
    """Full multi-member collective over the object-store backend."""

    @ray_tpu.remote
    class Member:
        def init_collective(self, world, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend=backend, group_name=group_name)
            self.rank = rank
            return rank

        def do_allreduce(self):
            from ray_tpu.util import collective as col

            out = col.allreduce(np.full((4,), float(self.rank + 1)))
            return np.asarray(out)

        def do_broadcast(self):
            from ray_tpu.util import collective as col

            return np.asarray(col.broadcast(np.full((2,), float(self.rank)), src_rank=1))

        def do_allgather(self):
            from ray_tpu.util import collective as col

            return np.asarray(col.allgather(np.array([float(self.rank)])))

    from ray_tpu.util import collective as col

    members = [Member.remote() for _ in range(3)]
    col.create_collective_group(members, backend="cpu")
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=120)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 1.0 + 2.0 + 3.0))
    outs = ray_tpu.get([m.do_broadcast.remote() for m in members], timeout=120)
    for out in outs:
        np.testing.assert_allclose(out, np.full((2,), 1.0))
    outs = ray_tpu.get([m.do_allgather.remote() for m in members], timeout=120)
    for out in outs:
        np.testing.assert_allclose(out.ravel(), [0.0, 1.0, 2.0])


@skip_without_multiprocess_collectives
def test_multiprocess_tpu_backend_psum(ray_start_regular):
    """Two actor processes form a real XLA world (jax.distributed over the
    gloo CPU transport in tests; identical code path bootstraps ICI worlds on
    TPU pods) and allreduce through a compiled shard_map psum."""

    @ray_tpu.remote
    class XlaMember:
        def init_collective(self, world, rank, backend, group_name):
            # Workers inherit the 8-virtual-CPU-device XLA_FLAGS from the test
            # env: world=2 -> a 2x8 global mesh, psum over the proc axis.
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend=backend, group_name=group_name)
            self.rank = rank
            return rank

        def do_allreduce(self):
            from ray_tpu.util import collective as col

            out = col.allreduce(np.full((4,), float(self.rank + 1), dtype=np.float32))
            return np.asarray(out)

    from ray_tpu.util import collective as col

    members = [XlaMember.remote() for _ in range(2)]
    col.create_collective_group(members, backend="tpu")
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=300)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0, dtype=np.float32))


@skip_without_multiprocess_collectives
def test_tpu_group_destroy_and_reform(ray_start_regular):
    """Gang-restart lifecycle (SURVEY hard part #1): a 2-process XLA world
    forms, allreduces, is destroyed (jax.distributed.shutdown + epoch bump),
    and re-forms under the SAME group name with a fresh epoch."""

    @ray_tpu.remote
    class XlaMember:
        def init_collective(self, world, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend=backend, group_name=group_name)
            self.rank = rank
            return col.get_group(group_name).epoch

        def do_allreduce(self):
            from ray_tpu.util import collective as col

            return np.asarray(
                col.allreduce(np.full((4,), float(self.rank + 1), dtype=np.float32), group_name="reform")
            )

        def destroy(self, group_name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(group_name)
            return True

    from ray_tpu.util import collective as col

    members = [XlaMember.remote() for _ in range(2)]
    epochs = col.create_collective_group(members, backend="tpu", group_name="reform")
    assert len(set(epochs)) == 1
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=300)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0, dtype=np.float32))

    ray_tpu.get([m.destroy.remote("reform") for m in members], timeout=120)

    epochs2 = col.create_collective_group(members, backend="tpu", group_name="reform")
    assert len(set(epochs2)) == 1 and epochs2[0] == epochs[0] + 1
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members], timeout=300)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0, dtype=np.float32))


def test_rendezvous_advertises_node_ip(ray_start_regular):
    """The coordinator address published in the KV must carry the node's
    GCS-registered IP (round-1 bug: hardwired 127.0.0.1 cannot span hosts).
    On this single-host fixture the registered address IS loopback, so
    instead assert the epoch-scoped key layout and that the IP equals the
    node's registered address rather than a constant."""

    @ray_tpu.remote
    class XlaMember:
        def init_collective(self, world, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend=backend, group_name=group_name)
            return True

        def coordinator_in_kv(self, group_name):
            from ray_tpu._private import worker_context

            cw = worker_context.get_core_worker_if_initialized()
            epoch = int(bytes(cw.gcs.call("kv_get", {"key": f"collective/{group_name}/epoch"})["value"]).decode())
            resp = cw.gcs.call("kv_get", {"key": f"collective/{group_name}/coord/{epoch}"})
            nodes = cw.gcs.call("get_nodes")["nodes"]
            my_ip = nodes[cw.node_id]["address"][0]
            return bytes(resp["value"]).decode(), my_ip

    from ray_tpu.util import collective as col

    members = [XlaMember.remote() for _ in range(2)]
    col.create_collective_group(members, backend="tpu", group_name="ipcheck")
    coord, node_ip = ray_tpu.get(members[0].coordinator_in_kv.remote("ipcheck"), timeout=120)
    assert coord.split(":")[0] == node_ip


@skip_without_multiprocess_collectives
def test_tpu_group_member_kill_and_reform(ray_start_regular):
    """Gang-restart drill: a collective member is KILLED (no graceful
    destroy — worker death mid-step) and the group re-forms under the same
    name with a survivor + a replacement. The epoch bump is what makes the
    stale epoch's state unreachable (tpu_group.py _rendezvous)."""

    @ray_tpu.remote
    class XlaMember:
        def do_allreduce(self, group_name):
            from ray_tpu.util import collective as col

            return np.asarray(
                col.allreduce(
                    np.full((4,), float(self.rank + 1), dtype=np.float32),
                    group_name=group_name,
                )
            )

        def init_collective(self, world, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend=backend, group_name=group_name)
            self.rank = rank
            return col.get_group(group_name).epoch

    from ray_tpu.util import collective as col

    members = [XlaMember.remote() for _ in range(2)]
    epochs = col.create_collective_group(members, backend="tpu", group_name="drill")
    outs = ray_tpu.get([m.do_allreduce.remote("drill") for m in members], timeout=300)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0, dtype=np.float32))

    # Kill a member outright mid-lifecycle: no destroy, no epoch cleanup.
    # Whole-gang restart follows (BackendExecutor semantics: a dead member
    # invalidates the world, so every survivor is torn down too — one
    # process can host at most one multi-process XLA world, and a dead
    # peer's coordination service state cannot be re-joined).
    ray_tpu.kill(members[1])
    ray_tpu.kill(members[0])
    gang = [XlaMember.remote() for _ in range(2)]
    epochs2 = col.create_collective_group(gang, backend="tpu", group_name="drill")
    assert len(set(epochs2)) == 1 and epochs2[0] > epochs[0]
    outs = ray_tpu.get([m.do_allreduce.remote("drill") for m in gang], timeout=300)
    for out in outs:
        np.testing.assert_allclose(out, np.full((4,), 3.0, dtype=np.float32))
