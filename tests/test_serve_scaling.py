"""Serve scaling + replica fault tolerance (VERDICT r2 weak #8).

Separate file: these tests need a FRESH serve instance with free CPUs —
the shared module fixture in test_serve.py accumulates deployments.
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()



def test_replica_failure_is_reconciled(serve_instance):
    """The controller replaces a killed replica and routing recovers
    (reference: deployment_state recovery — VERDICT r2 weak #8: serve
    fault paths were under-tested)."""

    @serve.deployment(num_replicas=2)
    class Fragile:
        def __call__(self, request):
            return "alive"

        def pid(self):
            import os

            return os.getpid()

    h = serve.run(Fragile.bind(), route_prefix="/fragile")
    pids = {ray_tpu.get(h.pid.remote()) for _ in range(10)}
    assert len(pids) == 2

    # Kill one replica actor out from under the controller (found via the
    # routing table's actor names).
    import ray_tpu as rt

    from ray_tpu.serve._private.common import CONTROLLER_NAME

    controller = rt.get_actor(CONTROLLER_NAME)
    table = rt.get(controller.get_routing_table.remote(-1, 1.0))["table"]
    replica_names = [r["actor_name"] for r in table["Fragile"]["replicas"]]
    assert len(replica_names) == 2
    rt.kill(rt.get_actor(replica_names[0]))

    # The reconciler replaces it: back to 2 RUNNING replicas. In-flight
    # calls racing the death may surface ActorDiedError (reference handles
    # do the same); the service must RECOVER, not never-fail.
    from ray_tpu.exceptions import ActorDiedError, TaskError

    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            assert ray_tpu.get(h.remote(None), timeout=30) == "alive"
        except (ActorDiedError, TaskError, TimeoutError):
            pass  # transient, racing the dead replica's removal
        st = serve.status().get("Fragile", {})
        table = rt.get(controller.get_routing_table.remote(-1, 1.0))["table"]
        now_names = {r["actor_name"] for r in table.get("Fragile", {}).get("replicas", [])}
        if st.get("num_replicas") == 2 and now_names != set(replica_names):
            break
        time.sleep(0.3)
    else:
        raise AssertionError("killed replica was never replaced")
    # Steady state after recovery: calls succeed again.
    for _ in range(5):
        assert ray_tpu.get(h.remote(None), timeout=30) == "alive"


def test_autoscaling_up_and_back_down(serve_instance):
    """Queue-depth autoscaling grows replicas under sustained load and
    shrinks back to min when idle (reference: autoscaling_policy.py)."""
    import threading

    @serve.deployment(
        max_concurrent_queries=2,
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_num_ongoing_requests_per_replica": 1,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 2.0,
        },
    )
    class Slow:
        def __call__(self, request):
            time.sleep(0.4)
            return "done"

    h = serve.run(Slow.bind(), route_prefix="/slowscale")
    assert serve.status()["Slow"]["num_replicas"] == 1

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                ray_tpu.get(h.remote(None), timeout=60)
            except Exception:
                pass

    threads = [threading.Thread(target=hammer, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 90
        grew = False
        while time.time() < deadline:
            if serve.status()["Slow"]["num_replicas"] >= 2:
                grew = True
                break
            time.sleep(0.5)
        assert grew, "autoscaler never scaled up under sustained queue depth"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    deadline = time.time() + 120
    while time.time() < deadline:
        if serve.status()["Slow"]["num_replicas"] == 1:
            break
        time.sleep(0.5)
    assert serve.status()["Slow"]["num_replicas"] == 1, "never scaled back down"
