"""State API, runtime context, timeline, metrics.

Models the reference's test_state_api*.py / test_metrics*.py / runtime-context
coverage (python/ray/tests/)."""

import time

import pytest


def test_runtime_context_driver(ray_start_regular):
    import ray_tpu

    ctx = ray_tpu.get_runtime_context()
    assert len(ctx.get_job_id()) == 8
    assert ctx.get_node_id()
    assert ctx.get_task_id() is None
    assert ctx.get_actor_id() is None
    assert ctx.worker_mode == "driver"
    assert ctx.to_dict()["job_id"] == ctx.get_job_id()


def test_runtime_context_in_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def whoami():
        ctx = ray_tpu.get_runtime_context()
        return ctx.get_task_id(), ctx.get_task_name(), ctx.get_assigned_resources()

    task_id, name, resources = ray_tpu.get(whoami.remote())
    assert task_id is not None
    assert name == "whoami"
    assert resources.get("CPU") == 1


def test_runtime_context_in_actor(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ids(self):
            ctx = ray_tpu.get_runtime_context()
            return ctx.get_actor_id(), ctx.worker_mode

    a = A.remote()
    actor_id, mode = ray_tpu.get(a.ids.remote())
    assert actor_id is not None
    assert mode == "worker"


def test_list_nodes_and_workers(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    workers = state.list_workers()
    assert len(workers) >= 1


def test_list_tasks_and_summary(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import state

    @ray_tpu.remote
    def tracked_task():
        return 1

    ray_tpu.get([tracked_task.remote() for _ in range(3)])
    worker_context.get_core_worker().flush_task_events()
    deadline = time.time() + 10
    rows = []
    while time.time() < deadline:
        rows = [t for t in state.list_tasks() if t["name"] == "tracked_task"]
        if len(rows) == 3 and all(r["state"] == "FINISHED" for r in rows):
            break
        time.sleep(0.2)
    assert len(rows) == 3
    assert all(r["state"] == "FINISHED" for r in rows)

    summary = state.summarize_tasks()
    assert summary["tracked_task"]["total"] == 3
    assert summary["tracked_task"]["states"]["FINISHED"] == 3


def test_list_tasks_failed_state(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import state

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    worker_context.get_core_worker().flush_task_events()
    deadline = time.time() + 10
    while time.time() < deadline:
        rows = [t for t in state.list_tasks() if t["name"] == "boom"]
        if rows and rows[0]["state"] == "FAILED":
            break
        time.sleep(0.2)
    assert rows and rows[0]["state"] == "FAILED"
    assert rows[0].get("error_type") == "ValueError"


def test_list_actors_and_pgs(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.placement_group import placement_group

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert len(actors) >= 1

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    pgs = state.list_placement_groups()
    assert len(pgs) == 1 and pgs[0]["state"] == "CREATED"


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    import json

    import ray_tpu

    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(2)])
    out = tmp_path / "trace.json"
    deadline = time.time() + 10
    complete = []
    while time.time() < deadline:
        events = ray_tpu.timeline(str(out))
        complete = [e for e in events if e.get("ph") == "X" and e["name"] == "traced"]
        if len(complete) == 2:
            break
        time.sleep(0.2)
    assert len(complete) == 2
    assert all(e["dur"] > 0 for e in complete)
    on_disk = json.loads(out.read_text())
    assert len(on_disk) == len(events)


def test_metrics_counter_gauge_histogram(ray_start_regular):
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("test_inflight", "inflight")
    g.set(5)
    h = metrics.Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    cw = worker_context.get_core_worker()
    metrics.flush_metrics(cw)
    text = metrics.prometheus_text(cw.gcs)
    assert 'test_requests_total{' in text
    assert 'route="/a"' in text and "3.0" in text
    assert "test_inflight{" in text
    assert "test_latency_s_bucket" in text
    assert "test_latency_s_count" in text

    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})


def test_metrics_from_actor(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    @ray_tpu.remote
    class M:
        def __init__(self):
            from ray_tpu.util.metrics import Counter

            self.c = Counter("actor_side_counter", "x")

        def bump(self):
            from ray_tpu.util import metrics as m
            from ray_tpu._private import worker_context as wc

            self.c.inc()
            m.flush_metrics(wc.get_core_worker())
            return True

    a = M.remote()
    assert ray_tpu.get(a.bump.remote())
    cw = worker_context.get_core_worker()
    text = metrics.prometheus_text(cw.gcs)
    assert "actor_side_counter" in text


def test_global_state_resources(ray_start_regular):
    from ray_tpu._private.state import GlobalState

    state = GlobalState()
    assert state.cluster_resources().get("CPU") == 4
    assert len(state.nodes()) == 1
    live = state.node_state(state.nodes()[0])
    assert "store" in live and "workers" in live


# ---------------------------------------------------------------------------
# Runtime self-metrics (ISSUE 8): the ray_tpu_* instrument plane
# ---------------------------------------------------------------------------

import re

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'   # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'  # more labels
    r" -?[0-9.eE+-]+(?:inf|nan)?$"         # value
)


def _parse_exposition(text: str):
    """Strict Prometheus text-format check. Returns
    {name: {"type": kind, "samples": [(sample_name, labels_str, value)]}}."""
    families: dict = {}
    declared_help: set = set()
    current = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            declared_help.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = {"type": kind, "samples": []}
            current = name
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
        sample_name = line.split("{", 1)[0].split(" ", 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", sample_name)
        owner = sample_name if sample_name in families else base
        assert owner in families, f"sample {sample_name!r} precedes its TYPE"
        labels = ""
        if "{" in line:
            labels = line[line.index("{") + 1 : line.rindex("}")]
        value = float(line.rsplit(" ", 1)[1])
        families[owner]["samples"].append((sample_name, labels, value))
    for name in families:
        assert name in declared_help, f"TYPE without HELP for {name}"
    return families


def _check_histograms(families):
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        by_series: dict = {}
        for sample_name, labels, value in fam["samples"]:
            base_labels = ",".join(
                p for p in labels.split(",") if not p.startswith("le=")
            )
            entry = by_series.setdefault(base_labels, {"buckets": [], "count": None})
            if sample_name.endswith("_bucket"):
                le = [p for p in labels.split(",") if p.startswith("le=")][0]
                entry["buckets"].append((le.split("=")[1].strip('"'), value))
            elif sample_name.endswith("_count"):
                entry["count"] = value
        for labels, entry in by_series.items():
            counts = [v for _le, v in entry["buckets"]]
            assert counts == sorted(counts), f"{name}{{{labels}}} buckets not monotonic"
            inf = [v for le, v in entry["buckets"] if le == "+Inf"]
            assert inf, f"{name}{{{labels}}} missing +Inf bucket"
            assert inf[0] == entry["count"], (
                f"{name}{{{labels}}} +Inf bucket {inf[0]} != count {entry['count']}"
            )


def test_runtime_metrics_in_exposition(ray_start_regular):
    """With NO user instruments, /metrics exposes >= 10 distinct ray_tpu_*
    runtime families (lease, dispatch histogram, store, rpc) — and the whole
    body is strictly valid Prometheus text exposition."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    @ray_tpu.remote
    def f(i):
        return i

    # >= 2 sampled dispatches at the default 1-in-64 rate.
    ray_tpu.get([f.remote(i) for i in range(130)])
    # A plasma-sized object so the store seals + the gauges move.
    ray_tpu.put(np.zeros(300_000, dtype=np.uint8))
    time.sleep(1.2)  # one heartbeat (store gauges) + agent sample
    cw = worker_context.get_core_worker()
    metrics.flush_metrics(cw)
    text = metrics.prometheus_text(cw.gcs)

    families = _parse_exposition(text)
    _check_histograms(families)

    populated = {
        name for name, fam in families.items()
        if name.startswith("ray_tpu_") and fam["samples"]
    }
    assert len(populated) >= 10, sorted(populated)
    for required in (
        "ray_tpu_lease_grants_total",
        "ray_tpu_lease_reuses_total",
        "ray_tpu_lease_tasks_total",
        "ray_tpu_dispatch_latency_s",
        "ray_tpu_store_seals_total",
        "ray_tpu_store_bytes_used",
        "ray_tpu_rpc_frames_total",
        "ray_tpu_rpc_bytes_total",
        "ray_tpu_rpc_connects_total",
    ):
        assert required in populated, f"{required} missing; have {sorted(populated)}"
    # The dispatch histogram carries a path tag and real observations.
    hist = families["ray_tpu_dispatch_latency_s"]
    assert hist["type"] == "histogram"
    assert any("path=" in labels for _n, labels, _v in hist["samples"])
    # Warm-lease hit ratio is computable and sane: reuses <= tasks.
    def total(name):
        return sum(v for _n, _l, v in families[name]["samples"])

    assert 0 < total("ray_tpu_lease_reuses_total") <= total("ray_tpu_lease_tasks_total")


def test_node_gauges_from_agent_samples(ray_start_regular):
    """Dashboard-agent node samples export as ray_tpu_node_* gauges (they
    were previously reachable only via /api/cluster_status)."""
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    cw = worker_context.get_core_worker()
    deadline = time.time() + 15
    text = ""
    while time.time() < deadline:
        text = metrics.prometheus_text(cw.gcs)
        if "ray_tpu_node_cpu_percent" in text:
            break
        time.sleep(0.5)
    families = _parse_exposition(text)
    for name in ("ray_tpu_node_cpu_percent", "ray_tpu_node_mem_used_bytes", "ray_tpu_node_mem_total_bytes"):
        assert name in families and families[name]["samples"], name
        assert all("NodeId=" in l for _n, l, _v in families[name]["samples"])
    mem_total = families["ray_tpu_node_mem_total_bytes"]["samples"][0][2]
    assert mem_total > 1024**3  # a real host figure, not a placeholder


def test_serve_and_data_metric_wiring(ray_start_regular):
    """The Serve-router and Data-operator hooks feed the shared registry and
    come out of /metrics (unit-level: no Serve/Data cluster needed)."""
    from ray_tpu._private import worker_context
    from ray_tpu.data._internal.stats import OpStats
    from ray_tpu.serve._private.router import Router
    from ray_tpu.util import metrics

    router = Router(None)  # bare-router seam: no controller, hand-fed table
    router._table = {
        "app": {"replicas": [{"actor_name": "r1", "max_concurrent_queries": 4}], "route_prefix": "/"}
    }
    replica = router.assign_replica("app", timeout_s=1)
    router.release(replica, deployment="app", duration_s=0.01)

    class _Meta:
        num_rows = 42
        size_bytes = 1000

    stats = OpStats(name="map_test")
    stats.mark_start()
    stats.record_output(_Meta())

    cw = worker_context.get_core_worker()
    metrics.flush_metrics(cw)
    text = metrics.prometheus_text(cw.gcs)
    families = _parse_exposition(text)
    assert families["ray_tpu_serve_requests_total"]["samples"]
    assert families["ray_tpu_serve_router_queue_depth"]["samples"]
    assert families["ray_tpu_serve_replica_latency_s"]["samples"]
    rows = [v for _n, l, v in families["ray_tpu_data_output_rows_total"]["samples"] if 'op="map_test"' in l]
    assert rows == [42.0]


def test_timeline_hop_flow_events(ray_start_regular):
    """`ray_tpu timeline` renders hop records as per-stage slices plus flow
    arrows when records are present (full hop timing here; the sampled path
    produces the identical record shape)."""
    import ray_tpu
    from ray_tpu._private.config import get_config
    from ray_tpu._private import worker_context

    get_config().hop_timing = True
    try:
        @ray_tpu.remote
        def traced():
            return 1

        ray_tpu.get([traced.remote() for _ in range(3)])
        deadline = time.time() + 10
        while time.time() < deadline:
            if worker_context.get_core_worker().hop_records():
                break
            time.sleep(0.1)
        events = ray_tpu.timeline()
    finally:
        get_config().hop_timing = False
    hop = [e for e in events if e.get("cat") == "hop"]
    assert any(e["ph"] == "X" for e in hop)
    flows = [e for e in hop if e["ph"] in ("s", "f")]
    assert flows and {e["ph"] for e in flows} == {"s", "f"}
    # Stage slices land on a per-path track with wall-clock timestamps.
    assert any(str(e.get("pid", "")).startswith("hop:") for e in hop)


def test_compiled_dag_channel_metrics(ray_start_regular):
    """Compiled-graph channel writes surface as ray_tpu_channel_* series."""
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.dag import InputNode
    from ray_tpu.util import metrics

    @ray_tpu.remote
    class Stage:
        def work(self, x):
            return x + 1

    with InputNode() as inp:
        dag = Stage.bind().work.bind(inp)
    compiled = dag.experimental_compile()
    try:
        for i in range(3):
            assert compiled.execute(i).get() == i + 1
    finally:
        compiled.teardown()
    cw = worker_context.get_core_worker()
    metrics.flush_metrics(cw)
    text = metrics.prometheus_text(cw.gcs)
    families = _parse_exposition(text)
    writes = families["ray_tpu_channel_writes_total"]["samples"]
    assert writes and writes[0][2] >= 3
