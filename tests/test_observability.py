"""State API, runtime context, timeline, metrics.

Models the reference's test_state_api*.py / test_metrics*.py / runtime-context
coverage (python/ray/tests/)."""

import time

import pytest


def test_runtime_context_driver(ray_start_regular):
    import ray_tpu

    ctx = ray_tpu.get_runtime_context()
    assert len(ctx.get_job_id()) == 8
    assert ctx.get_node_id()
    assert ctx.get_task_id() is None
    assert ctx.get_actor_id() is None
    assert ctx.worker_mode == "driver"
    assert ctx.to_dict()["job_id"] == ctx.get_job_id()


def test_runtime_context_in_task(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def whoami():
        ctx = ray_tpu.get_runtime_context()
        return ctx.get_task_id(), ctx.get_task_name(), ctx.get_assigned_resources()

    task_id, name, resources = ray_tpu.get(whoami.remote())
    assert task_id is not None
    assert name == "whoami"
    assert resources.get("CPU") == 1


def test_runtime_context_in_actor(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ids(self):
            ctx = ray_tpu.get_runtime_context()
            return ctx.get_actor_id(), ctx.worker_mode

    a = A.remote()
    actor_id, mode = ray_tpu.get(a.ids.remote())
    assert actor_id is not None
    assert mode == "worker"


def test_list_nodes_and_workers(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    workers = state.list_workers()
    assert len(workers) >= 1


def test_list_tasks_and_summary(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import state

    @ray_tpu.remote
    def tracked_task():
        return 1

    ray_tpu.get([tracked_task.remote() for _ in range(3)])
    worker_context.get_core_worker().flush_task_events()
    deadline = time.time() + 10
    rows = []
    while time.time() < deadline:
        rows = [t for t in state.list_tasks() if t["name"] == "tracked_task"]
        if len(rows) == 3 and all(r["state"] == "FINISHED" for r in rows):
            break
        time.sleep(0.2)
    assert len(rows) == 3
    assert all(r["state"] == "FINISHED" for r in rows)

    summary = state.summarize_tasks()
    assert summary["tracked_task"]["total"] == 3
    assert summary["tracked_task"]["states"]["FINISHED"] == 3


def test_list_tasks_failed_state(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import state

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    worker_context.get_core_worker().flush_task_events()
    deadline = time.time() + 10
    while time.time() < deadline:
        rows = [t for t in state.list_tasks() if t["name"] == "boom"]
        if rows and rows[0]["state"] == "FAILED":
            break
        time.sleep(0.2)
    assert rows and rows[0]["state"] == "FAILED"
    assert rows[0].get("error_type") == "ValueError"


def test_list_actors_and_pgs(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.placement_group import placement_group

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert len(actors) >= 1

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    pgs = state.list_placement_groups()
    assert len(pgs) == 1 and pgs[0]["state"] == "CREATED"


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    import json

    import ray_tpu

    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(2)])
    out = tmp_path / "trace.json"
    deadline = time.time() + 10
    complete = []
    while time.time() < deadline:
        events = ray_tpu.timeline(str(out))
        complete = [e for e in events if e.get("ph") == "X" and e["name"] == "traced"]
        if len(complete) == 2:
            break
        time.sleep(0.2)
    assert len(complete) == 2
    assert all(e["dur"] > 0 for e in complete)
    on_disk = json.loads(out.read_text())
    assert len(on_disk) == len(events)


def test_metrics_counter_gauge_histogram(ray_start_regular):
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("test_inflight", "inflight")
    g.set(5)
    h = metrics.Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    cw = worker_context.get_core_worker()
    metrics.flush_metrics(cw)
    text = metrics.prometheus_text(cw.gcs)
    assert 'test_requests_total{' in text
    assert 'route="/a"' in text and "3.0" in text
    assert "test_inflight{" in text
    assert "test_latency_s_bucket" in text
    assert "test_latency_s_count" in text

    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})


def test_metrics_from_actor(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.util import metrics

    @ray_tpu.remote
    class M:
        def __init__(self):
            from ray_tpu.util.metrics import Counter

            self.c = Counter("actor_side_counter", "x")

        def bump(self):
            from ray_tpu.util import metrics as m
            from ray_tpu._private import worker_context as wc

            self.c.inc()
            m.flush_metrics(wc.get_core_worker())
            return True

    a = M.remote()
    assert ray_tpu.get(a.bump.remote())
    cw = worker_context.get_core_worker()
    text = metrics.prometheus_text(cw.gcs)
    assert "actor_side_counter" in text


def test_global_state_resources(ray_start_regular):
    from ray_tpu._private.state import GlobalState

    state = GlobalState()
    assert state.cluster_resources().get("CPU") == 4
    assert len(state.nodes()) == 1
    live = state.node_state(state.nodes()[0])
    assert "store" in live and "workers" in live
