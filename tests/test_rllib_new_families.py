"""Tests for the round-4 algorithm families: SimpleQ, A3C, DDPPO, ApexDDPG.

Same tiering as test_rllib_algorithms.py (mirroring the reference's
rllib/algorithms/*/tests): learning checks for the on-policy families on
CartPole, compile-and-improve smoke tests for the off-policy/distributed
ones.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_simple_q_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import SimpleQConfig

    cfg = (
        SimpleQConfig()
        .environment("CartPole-v1")
        .rollouts(num_envs_per_worker=4)
        .training(
            lr=1e-3, train_batch_size=64, learning_starts=500,
            epsilon_timesteps=4000, rollout_steps_per_iter=500,
            model_hiddens=(64, 64),
        )
        .debugging(seed=0)
    )
    assert not cfg.double_q and not cfg.prioritized_replay
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(16):
            r = algo.step()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            if best >= 80:
                break
        assert best >= 80, f"SimpleQ failed to improve on CartPole (best={best})"
    finally:
        algo.cleanup()


def test_simple_q_rejects_dqn_extensions():
    from ray_tpu.rllib import SimpleQConfig

    with pytest.raises(ValueError):
        SimpleQConfig().training(double_q=True)
    with pytest.raises(ValueError):
        SimpleQConfig().training(prioritized_replay=True)


def test_a3c_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import A3CConfig

    cfg = (
        A3CConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=8, rollout_fragment_length=40)
        .training(lr=2e-3, entropy_coeff=0.005, grad_clip=1.0, grads_per_step=12)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.step()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"A3C failed to improve on CartPole (best={best})"
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_ddppo_learns_cartpole_in_lockstep(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DDPPOConfig

    cfg = (
        DDPPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=8, rollout_fragment_length=60)
        .training(lr=1e-3, entropy_coeff=0.005, num_sgd_iter=4, sgd_minibatch_size=120)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        # training_step itself asserts the workers' weight digests agree
        # (decentralized updates must stay bit-identical).
        for _ in range(40):
            r = algo.step()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"DDPPO failed to improve on CartPole (best={best})"
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_apex_ddpg_pendulum_smoke(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import ApexDDPGConfig

    cfg = (
        ApexDDPGConfig()
        .environment("Pendulum-v1")
        .rollouts(num_rollout_workers=2)
        .training(
            lr=1e-3, train_batch_size=64, learning_starts=300,
            rollout_fragment_length=50, train_rounds_per_iter=3,
            updates_per_round=2, model_hiddens=(32, 32),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(2):
            r = algo.step()
        assert np.isfinite(r["critic_loss"])
        assert r["replay_size"] > 0
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert -2.0 <= float(np.asarray(a).ravel()[0]) <= 2.0
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()
