"""Failure / fault-tolerance tests (analog of the reference's test_failure*.py,
test_chaos.py with the NodeKillerActor fault injector, test_utils.py:1360)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError, WorkerCrashedError


def test_task_retry_on_worker_crash(ray_start_regular):
    """A task that kills its worker is retried (reference: task_manager.h:335)."""
    marker = f"/tmp/rtpu_retry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        import os as _os

        if not _os.path.exists(path):
            with open(path, "w") as f:
                f.write("1")
            _os._exit(1)  # kill the worker on first attempt
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=120) == "recovered"
    os.unlink(marker)


def test_task_no_retry_exhausted(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        import os as _os

        _os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=120)


def test_retry_exceptions(ray_start_regular):
    marker = f"/tmp/rtpu_retryexc_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky(path):
        import os as _os

        if not _os.path.exists(path):
            with open(path, "w") as f:
                f.write("1")
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=120) == "ok"
    os.unlink(marker)


def test_lineage_reconstruction(ray_start_cluster):
    """A lost plasma object is rebuilt by re-executing its creating task
    (reference: object_recovery_manager.h:90)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1})
    victim = cluster.add_node(num_cpus=1, resources={"victim": 1})
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"victim": 1}, max_retries=2)
    def produce():
        return np.ones((512, 512), dtype=np.float32)  # 1MB -> plasma on victim

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    # Kill the node holding the only copy.
    cluster.remove_node(victim)
    cluster.add_node(num_cpus=1, resources={"victim": 1})
    time.sleep(1.0)
    out = ray_tpu.get(ref, timeout=120)
    assert out.shape == (512, 512)


def test_node_death_detected(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    extra = cluster.add_node(num_cpus=1)
    cluster.connect()
    cluster.wait_for_nodes()
    extra_id = extra.node_id
    cluster.remove_node(extra)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
        if states.get(extra_id) == "DEAD":
            return
        time.sleep(0.2)
    pytest.fail("node death not detected")


def test_chaos_task_retry(ray_start_cluster):
    """Tasks survive a node being killed mid-workload (reference:
    test_chaos.py:66 test_chaos_task_retry)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"stable": 2})
    victim = cluster.add_node(num_cpus=2)
    cluster.connect()
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=3)
    def work(i):
        time.sleep(0.1)
        return i

    refs = [work.remote(i) for i in range(12)]
    time.sleep(0.3)
    cluster.remove_node(victim)
    out = ray_tpu.get(refs, timeout=180)
    assert out == list(range(12))


def test_memory_monitor_kills_and_surfaces_oom():
    """With the threshold forced to 0, any running task worker is killed by
    the memory monitor and the error surfaces as OutOfMemoryError after
    retries are exhausted (reference: test_memory_pressure / worker killing
    policy)."""
    import time as _time

    from ray_tpu.exceptions import OutOfMemoryError

    ray_tpu.init(
        num_cpus=2,
        object_store_memory=64 * 1024 * 1024,
        _system_config={
            "memory_usage_threshold": 0.0,  # everything is "over threshold"
            "memory_monitor_interval_s": 0.2,
        },
    )
    try:

        @ray_tpu.remote(max_retries=1)
        def hog():
            _time.sleep(30)
            return "finished"

        with pytest.raises(OutOfMemoryError):
            ray_tpu.get(hog.remote(), timeout=120)
    finally:
        ray_tpu.shutdown()


def test_memory_monitor_disabled_by_config():
    ray_tpu.init(
        num_cpus=2,
        object_store_memory=64 * 1024 * 1024,
        _system_config={
            "memory_usage_threshold": 0.0,
            "memory_monitor_enabled": False,
        },
    )
    try:

        @ray_tpu.remote
        def quick():
            return "ok"

        assert ray_tpu.get(quick.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()
