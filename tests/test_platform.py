"""Platform layer tests: dashboard REST, job submission, CLI.

Modeled on the reference's dashboard/modules/job/tests/test_job_manager.py,
dashboard/tests/, and python/ray/tests/test_cli.py: REST state endpoints, job
lifecycle (submit/status/logs/stop), and the start/status/stop CLI flow.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def dashboard():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    from ray_tpu.dashboard import DashboardHead

    node = ray_tpu._global_node
    head = DashboardHead(node.gcs_address, node.session_dir)
    yield head
    head.stop()
    ray_tpu.shutdown()


def _get(head, path):
    url = "http://%s:%d%s" % (head.address[0], head.address[1], path)
    with urllib.request.urlopen(url, timeout=30) as resp:
        return json.loads(resp.read())


def test_dashboard_state_endpoints(dashboard):
    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote()) == 1

    ver = _get(dashboard, "/api/version")
    assert ver["version"] == ray_tpu.__version__
    status = _get(dashboard, "/api/cluster_status")
    assert status["cluster_resources"]["CPU"] == 4
    assert len([n for n in status["nodes"] if n["state"] == "ALIVE"]) == 1
    nodes = _get(dashboard, "/api/v0/nodes")["result"]
    assert len(nodes) == 1
    # Task events flush to the GCS asynchronously (task_event_buffer
    # analog); poll briefly instead of racing the flush interval.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        tasks = _get(dashboard, "/api/v0/tasks")["result"]
        if any(t["name"] == "f" for t in tasks):
            break
        time.sleep(0.25)
    assert any(t["name"] == "f" for t in tasks)


def test_dashboard_metrics_endpoint(dashboard):
    from ray_tpu.util import metrics

    c = metrics.Counter("platform_test_total", tag_keys=("k",))
    c.inc(2.0, tags={"k": "v"})
    metrics.flush_metrics()
    url = "http://%s:%d/metrics" % dashboard.address
    with urllib.request.urlopen(url, timeout=30) as resp:
        text = resp.read().decode()
    assert "platform_test_total" in text
    assert 'k="v"' in text


def test_job_submission_end_to_end(dashboard):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient("http://%s:%d" % dashboard.address)
    script = (
        "import sys; sys.path.insert(0, %r); "
        "import ray_tpu; ray_tpu.init(); "
        "print('task says', ray_tpu.get(ray_tpu.remote(lambda: 40 + 2).remote()))"
    ) % REPO
    sid = client.submit_job(entrypoint=f"{sys.executable} -c \"{script}\"")
    status = client.wait_until_finished(sid, timeout=120)
    logs = client.get_job_logs(sid)
    assert status == "SUCCEEDED", logs
    assert "task says 42" in logs
    jobs = client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)


def test_job_stop(dashboard):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient("http://%s:%d" % dashboard.address)
    sid = client.submit_job(entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'")
    deadline = time.time() + 30
    while client.get_job_status(sid) == "PENDING" and time.time() < deadline:
        time.sleep(0.1)
    assert client.stop_job(sid) is True
    status = client.wait_until_finished(sid, timeout=30)
    assert status == "STOPPED"


def test_job_submit_missing_entrypoint_400(dashboard):
    req = urllib.request.Request(
        "http://%s:%d/api/jobs/" % dashboard.address,
        data=json.dumps({}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=30)
    assert exc_info.value.code == 400


def test_cli_start_status_stop(tmp_path):
    """Full CLI flow in subprocesses: start --head, status, connect a driver
    via address="auto", stop."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    run = lambda *cmd, **kw: subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.scripts", *cmd],
        capture_output=True,
        text=True,
        env=env,
        timeout=kw.pop("timeout", 120),
    )
    # Make sure no stale cluster file blocks the start.
    subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.scripts", "stop"],
        capture_output=True,
        env=env,
        timeout=60,
    )
    out = run("start", "--head", "--num-cpus", "2", "--no-dashboard")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "Started head node" in out.stdout
    try:
        st = run("status")
        assert st.returncode == 0, st.stdout + st.stderr
        assert "1 alive" in st.stdout
        assert "CPU" in st.stdout

        driver = subprocess.run(
            [
                sys.executable,
                "-c",
                "import sys; sys.path.insert(0, %r); import ray_tpu; "
                'ray_tpu.init(address="auto"); '
                "print(ray_tpu.get(ray_tpu.remote(lambda: 'via-cli').remote()))" % REPO,
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=120,
        )
        assert driver.returncode == 0, driver.stdout + driver.stderr
        assert "via-cli" in driver.stdout
    finally:
        out = run("stop")
        assert "Stopped" in out.stdout


def test_dashboard_index_page(dashboard):
    url = "http://%s:%d/" % dashboard.address
    with urllib.request.urlopen(url, timeout=30) as resp:
        body = resp.read().decode()
        ctype = resp.headers.get("Content-Type", "")
    assert "text/html" in ctype
    assert "ray_tpu dashboard" in body
    assert "/api/cluster_status" in body  # the page polls the REST API


def test_node_stats_agent_reports(dashboard):
    """Per-node agent (dashboard/agent.py) ships host + per-worker stats to
    the GCS; the head's cluster_status carries them (reference:
    dashboard/agent.py + reporter module)."""

    @ray_tpu.remote
    class Holder:
        def pid(self):
            return os.getpid()

    h = Holder.remote()
    wpid = ray_tpu.get(h.pid.remote())
    deadline = time.monotonic() + 30
    stats = {}
    while time.monotonic() < deadline:
        status = _get(dashboard, "/api/cluster_status")
        nodes = [n for n in status["nodes"] if n["state"] == "ALIVE"]
        stats = nodes[0].get("stats") or {}
        if stats.get("workers") and any(
            w.get("pid") == wpid for w in stats["workers"].values()
        ):
            break
        time.sleep(1.0)
    assert stats.get("mem_total", 0) > 0
    assert "cpu_percent" in stats
    assert any(w.get("pid") == wpid and w.get("rss", 0) > 0 for w in stats.get("workers", {}).values())


def test_dashboard_log_endpoints(dashboard):
    @ray_tpu.remote
    def chatty():
        print("hello-from-worker-log")
        return 1

    assert ray_tpu.get(chatty.remote()) == 1
    deadline = time.monotonic() + 20
    files = []
    while time.monotonic() < deadline:
        files = _get(dashboard, "/api/v0/logs")["result"]
        if files:
            break
        time.sleep(0.5)
    assert files, "no log files listed"
    target = next((f["file"] for f in files if f["file"].endswith(".out") and f["size"] > 0), None)
    if target is not None:
        tail = _get(dashboard, "/api/v0/logs/tail?file=" + urllib.parse.quote(target) + "&lines=50")
        assert isinstance(tail["lines"], list)
    # Path traversal must 404.
    try:
        _get(dashboard, "/api/v0/logs/tail?file=..%2F..%2Fetc%2Fpasswd")
        raise AssertionError("traversal not rejected")
    except urllib.error.HTTPError as e:
        assert e.code == 404
