"""Dataset.window()/repeat() epoch pipelining (reference
python/ray/data/dataset_pipeline.py): windows stream through without
materializing the source; repeat() re-executes a lazy plan per epoch."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_window_groups_blocks_and_preserves_rows(ray_start_regular):
    ds = rd.range(100, parallelism=10)  # 10 blocks of 10
    pipe = ds.window(blocks_per_window=4)
    windows = list(pipe.iter_datasets())
    assert len(windows) == 3  # 4 + 4 + 2 blocks
    assert [w.num_blocks() for w in windows] == [4, 4, 2]
    rows = [r["id"] for w in windows for r in w.iter_rows()]
    assert sorted(rows) == list(range(100))
    # the source dataset itself was never materialized
    assert ds._cached_bundles is None


def test_window_transforms_apply_per_window(ray_start_regular):
    pipe = (
        rd.range(40, parallelism=8)
        .window(blocks_per_window=2)
        .map_batches(lambda b: {"id": b["id"] * 2})
    )
    rows = sorted(r["id"] for r in pipe.iter_rows())
    assert rows == [2 * i for i in range(40)]


def test_repeat_reexecutes_lazy_plan_per_epoch(ray_start_regular):
    calls = []

    def tag(batch):
        calls.append(len(batch["id"]))
        return batch

    ds = rd.range(30, parallelism=3).map_batches(tag)
    pipe = ds.repeat(3)
    epochs = list(pipe.iter_epochs())
    assert len(epochs) == 3
    for ep in epochs:
        got = sorted(r["id"] for r in ep.iter_rows())
        assert got == list(range(30))
    # The udf ran in remote workers; the local `calls` list stays empty —
    # instead assert re-execution through the uncached source dataset.
    assert ds._cached_bundles is None


def test_window_repeat_three_epoch_train_ingest(ray_start_regular):
    """The VERDICT's done-bar: 3 epochs over a windowed read, batches flow,
    nothing materialized wholesale."""
    ds = rd.range(64, parallelism=8)
    pipe = ds.window(blocks_per_window=2).repeat(3)
    epoch_sums = []
    for epoch_ds in pipe.iter_epochs():
        total = 0
        n = 0
        for batch in epoch_ds.iter_batches(batch_size=16):
            total += int(np.sum(batch["id"]))
            n += len(batch["id"])
        assert n == 64
        epoch_sums.append(total)
    assert epoch_sums == [sum(range(64))] * 3
    assert ds._cached_bundles is None


def test_repeat_forever_is_lazy(ray_start_regular):
    pipe = rd.range(10, parallelism=2).repeat()  # infinite epochs
    it = pipe.iter_rows()
    first = [next(it) for _ in range(25)]  # 2.5 epochs, lazily
    assert [r["id"] for r in first[:10]] == list(range(10))
    assert [r["id"] for r in first[20:25]] == list(range(5))


def test_pipeline_arg_validation(ray_start_regular):
    ds = rd.range(10, parallelism=2)
    with pytest.raises(ValueError):
        ds.window(blocks_per_window=0)
    with pytest.raises(ValueError):
        ds.repeat(0)
    with pytest.raises(ValueError):
        ds.repeat(2).repeat(2)
