"""Off-policy estimators (reference: rllib/offline/estimators/).

Ground-truth check on a 2-armed bandit-style episodic task where the
target policy's true value is computable in closed form: IS/WIS/DM/DR
must all land near it while the naive behavior-average does not.
"""

import numpy as np
import pytest

from ray_tpu.rllib.offline import (
    AlgorithmPolicyAdapter,
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    WeightedImportanceSampling,
)
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


def _make_logged_data(n_episodes=4000, seed=0):
    """One-step episodes: obs ~ {0,1}; action 1 pays obs+1, action 0 pays
    0.5. Behavior policy: uniform. Target policy: always action 1.
    True target value = E[obs + 1] = 1.5; behavior value = 1.0."""
    rng = np.random.default_rng(seed)
    rows = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS, EPS_ID, "action_prob")}
    for ep in range(n_episodes):
        obs = float(rng.integers(0, 2))
        a = int(rng.integers(0, 2))
        reward = (obs + 1.0) if a == 1 else 0.5
        rows[OBS].append([obs])
        rows[ACTIONS].append(a)
        rows[REWARDS].append(np.float32(reward))
        rows[DONES].append(np.float32(1.0))
        rows[NEXT_OBS].append([obs])
        rows[EPS_ID].append(ep)
        rows["action_prob"].append(np.float32(0.5))
    return SampleBatch({k: np.asarray(v) for k, v in rows.items()})


def _target_policy():
    # Deterministic "always arm 1".
    return AlgorithmPolicyAdapter(
        lambda obs: np.tile(np.array([[0.0, 1.0]], np.float32), (len(obs), 1))
    )


def test_is_and_wis_recover_target_value():
    batch = _make_logged_data()
    policy = _target_policy()
    is_est = ImportanceSampling(policy, gamma=1.0).estimate(batch)
    wis_est = WeightedImportanceSampling(policy, gamma=1.0).estimate(batch)
    assert abs(is_est["v_behavior"] - 1.0) < 0.05
    assert abs(is_est["v_target"] - 1.5) < 0.1, is_est
    assert abs(wis_est["v_target"] - 1.5) < 0.1, wis_est


def test_dm_and_dr_recover_target_value():
    batch = _make_logged_data(n_episodes=2000, seed=1)
    policy = _target_policy()
    dm = DirectMethod(policy, gamma=1.0, fqe_iterations=400)
    dm_est = dm.estimate(batch)
    assert abs(dm_est["v_target"] - 1.5) < 0.15, dm_est
    dr = DoublyRobust(policy, gamma=1.0, fqe_iterations=400)
    dr_est = dr.estimate(batch)
    assert abs(dr_est["v_target"] - 1.5) < 0.15, dr_est


def test_multi_step_episodes_split_on_dones():
    """Episode splitting falls back to DONES when EPS_ID is absent."""
    rng = np.random.default_rng(2)
    n = 300
    batch = SampleBatch({
        OBS: rng.normal(size=(n, 1)).astype(np.float32),
        ACTIONS: rng.integers(0, 2, n),
        REWARDS: np.ones(n, np.float32),
        DONES: np.asarray([1.0 if (i % 3) == 2 else 0.0 for i in range(n)], np.float32),
        NEXT_OBS: rng.normal(size=(n, 1)).astype(np.float32),
        "action_prob": np.full(n, 0.5, np.float32),
    })
    policy = AlgorithmPolicyAdapter(
        lambda obs: np.full((len(obs), 2), 0.5, np.float32)
    )
    est = WeightedImportanceSampling(policy, gamma=1.0).estimate(batch)
    assert est["num_episodes"] == 100
    # Same policy as behavior -> target value == behavior value == 3.
    assert abs(est["v_target"] - 3.0) < 1e-6
