"""Tests for the AlgorithmConfig fluent sections added for reference parity
(.exploration / .fault_tolerance / .reporting / .offline_data / .callbacks /
.framework) and their wiring into the Algorithm runtime.

Reference: rllib/algorithms/algorithm_config.py (the fluent builder) and
rllib/algorithms/callbacks.py (DefaultCallbacks).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_config_sections_set_attributes():
    from ray_tpu.rllib import DQNConfig

    cfg = (
        DQNConfig()
        .exploration(explore=False, exploration_config={"final_epsilon": 0.05})
        .fault_tolerance(recreate_failed_workers=False, max_worker_restarts=3)
        .reporting(metrics_num_episodes_for_smoothing=25, min_time_s_per_iteration=0.0)
        .offline_data(output="/tmp/rollouts")
    )
    assert cfg.explore is False
    assert cfg.final_epsilon == 0.05
    assert cfg.recreate_failed_workers is False
    assert cfg.max_worker_restarts == 3
    assert cfg.metrics_num_episodes_for_smoothing == 25
    assert cfg.output == "/tmp/rollouts"


def test_framework_section_rejects_non_jax():
    from ray_tpu.rllib import PPOConfig

    PPOConfig().framework("jax")
    PPOConfig().framework(None)
    with pytest.raises(ValueError, match="JAX-native"):
        PPOConfig().framework("torch")


def test_callbacks_fire_on_train(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import A2CConfig, DefaultCallbacks

    events = []

    class Recorder(DefaultCallbacks):
        def on_algorithm_init(self, *, algorithm):
            events.append("init")

        def on_train_result(self, *, algorithm, result):
            events.append("train")
            result["custom_metric"] = 42

    cfg = (
        A2CConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=1, num_envs_per_worker=2)
        .training(train_batch_size=80)
        .callbacks(Recorder)
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        assert "init" in events
        result = algo.train()
        assert "train" in events
        # on_train_result may mutate the result in place (reference
        # semantics — custom metrics land in the reported dict).
        assert result["custom_metric"] == 42
    finally:
        algo.cleanup()


def test_worker_set_degrades_without_restart_budget(ray_cluster):
    """fault_tolerance(recreate_failed_workers=False): a dead worker is
    dropped, not respawned, and sampling continues on the survivors."""
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.evaluation.rollout_worker import WorkerSet
    from ray_tpu.rllib.models import ModelCatalog

    probe = gym.make("CartPole-v1")
    spec = ModelCatalog.get_model_spec(
        probe.observation_space, probe.action_space,
        {"fcnet_hiddens": (8,), "conv_filters": None},
    )
    probe.close()
    ws = WorkerSet(
        "CartPole-v1", spec, num_workers=2, recreate_failed_workers=False,
    )
    try:
        from ray_tpu.rllib.core import rl_module

        weights = jax.tree_util.tree_map(
            np.asarray, rl_module.init_params(jax.random.PRNGKey(0), spec)
        )
        ws.sync_weights(weights)
        assert ws.num_workers == 2
        ray_tpu.kill(ws._workers[0])
        # kill() is asynchronous: sample until the death is observed.
        import time

        for _ in range(20):
            batches = ws.sample(10)
            if ws.num_workers == 1:
                break
            time.sleep(0.2)
        assert ws.num_workers == 1, "dead worker should be dropped, not respawned"
        assert len(batches) >= 1
        # The last worker dying must raise, not silently sample nothing.
        ray_tpu.kill(ws._workers[0])
        with pytest.raises(RuntimeError, match="last rollout worker"):
            for _ in range(20):
                ws.sample(10)
                time.sleep(0.2)
    finally:
        ws.stop()


def test_worker_restart_budget_consumed(ray_cluster):
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.evaluation.rollout_worker import WorkerSet
    from ray_tpu.rllib.models import ModelCatalog

    probe = gym.make("CartPole-v1")
    spec = ModelCatalog.get_model_spec(
        probe.observation_space, probe.action_space,
        {"fcnet_hiddens": (8,), "conv_filters": None},
    )
    probe.close()
    ws = WorkerSet("CartPole-v1", spec, num_workers=2, max_worker_restarts=1)
    try:
        weights = jax.tree_util.tree_map(
            np.asarray, rl_module.init_params(jax.random.PRNGKey(0), spec)
        )
        ws.sync_weights(weights)
        import time

        # First death: budget of 1 allows a respawn.
        ray_tpu.kill(ws._workers[0])
        for _ in range(20):
            ws.sample(5)
            if ws._restarts == 1:
                break
            time.sleep(0.2)
        assert ws._restarts == 1 and ws.num_workers == 2
        # Second death: budget spent -> degrade.
        ray_tpu.kill(ws._workers[1])
        for _ in range(20):
            ws.sample(5)
            if ws.num_workers == 1:
                break
            time.sleep(0.2)
        assert ws.num_workers == 1
    finally:
        ws.stop()
