"""Tests: Tune logger stack (CSV/JSON/TBX), RLTrainer/RLPredictor bridge,
gated integrations/spark shim.

Reference analogs: tune/tests/test_logger.py, train/tests/test_rl_trainer.py.
"""

import csv
import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


def test_trial_dirs_get_csv_json_tbx(ray_start_regular, tmp_path):
    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1), "note": "text-skipped-in-csv"})

    results = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="logexp", storage_path=str(tmp_path)),
    ).fit()
    assert len(results) == 2
    exp_dir = tmp_path / "logexp"
    trial_dirs = [d for d in exp_dir.iterdir() if d.is_dir()]
    assert len(trial_dirs) == 2
    for td in trial_dirs:
        with open(td / "progress.csv") as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 3
        assert float(rows[1]["score"]) == 2 * float(rows[0]["score"])
        with open(td / "result.json") as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert lines[0]["note"] == "text-skipped-in-csv"
        assert json.load(open(td / "params.json"))["x"] in (1.0, 2.0)
        # TensorBoard event file from tensorboardX.
        assert any(name.startswith("events.out") for name in os.listdir(td))


def test_rl_trainer_fit_and_predict(ray_start_regular):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.train.rl import RLPredictor, RLTrainer

    trainer = RLTrainer(
        algorithm="PPO",
        config={
            "env": "CartPole-v1",
            "num_rollout_workers": 1,
            "num_envs_per_worker": 2,
            "train_batch_size": 400,
            "sgd_minibatch_size": 128,
            "num_sgd_iter": 2,
        },
        stop={"training_iteration": 2},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["training_iteration"] == 2
    assert result.checkpoint is not None
    assert result.checkpoint.metadata["algorithm"] == "PPO"
    assert result.metrics_dataframe is not None and len(result.metrics_dataframe) == 2
    predictor = RLPredictor.from_checkpoint(
        result.checkpoint,
        algorithm="PPO",
        config={"env": "CartPole-v1", "num_rollout_workers": 0},
    )
    try:
        actions = predictor.predict(np.zeros((3, 4), np.float32))
        assert actions.shape == (3,)
        assert set(actions.tolist()) <= {0, 1}
    finally:
        predictor.close()


def test_gated_shims_raise_with_guidance():
    from ray_tpu.air.integrations import setup_mlflow, setup_wandb
    from ray_tpu.util.spark import setup_ray_cluster

    for fn, pkg in ((setup_wandb, "wandb"), (setup_mlflow, "mlflow"), (setup_ray_cluster, "pyspark")):
        with pytest.raises(ImportError, match=pkg):
            fn()
