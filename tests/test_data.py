"""ray_tpu.data tests.

Modeled on the reference's python/ray/data/tests/ (test_dataset.py,
test_map.py, test_all_to_all.py, test_splitblocks.py, test_consumption.py):
creation, transforms, fusion, shuffle/sort/groupby, iteration, splits, IO.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_range_count_take(ray_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_and_schema(ray_cluster):
    ds = rd.from_items([{"a": i, "b": float(i)} for i in range(10)])
    assert ds.count() == 10
    assert set(ds.columns()) == {"a", "b"}


def test_map_batches_fusion_preserves_order(ray_cluster):
    ds = (
        rd.range(200, parallelism=4)
        .map_batches(lambda b: {"id": b["id"], "x": b["id"] * 2})
        .map_batches(lambda b: {"id": b["id"], "x": b["x"] + 1})
    )
    rows = ds.take_all()
    assert [r["id"] for r in rows] == list(range(200))
    assert all(r["x"] == r["id"] * 2 + 1 for r in rows)


def test_map_and_filter_and_flat_map(ray_cluster):
    ds = rd.range(20, parallelism=2).map(lambda r: {"id": r["id"], "y": r["id"] ** 2})
    f = ds.filter(lambda r: r["id"] % 2 == 0)
    assert f.count() == 10
    fm = rd.range(5, parallelism=1).flat_map(lambda r: [{"v": r["id"]}, {"v": -r["id"]}])
    assert fm.count() == 10


def test_map_batches_actor_pool(ray_cluster):
    class AddConst:
        def __init__(self):
            self.c = 100

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(40, parallelism=4).map_batches(AddConst, compute=rd.ActorPoolStrategy(size=2))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 140))


def test_random_shuffle_and_sort(ray_cluster):
    ds = rd.range(500, parallelism=4)
    sh = ds.random_shuffle(seed=42)
    ids = [r["id"] for r in sh.take_all()]
    assert sorted(ids) == list(range(500))
    assert ids != list(range(500))
    back = sh.sort("id")
    assert [r["id"] for r in back.take(10)] == list(range(10))
    desc = ds.sort("id", descending=True)
    assert [r["id"] for r in desc.take(3)] == [499, 498, 497]


def test_single_block_shuffle_and_groupby(ray_cluster):
    # Regression: num_outputs == 1 shuffle must unwrap the 1-tuple map result.
    ds = rd.range(10, parallelism=1)
    assert sorted(r["id"] for r in ds.random_shuffle(seed=1).take_all()) == list(range(10))
    out = rd.from_items([{"k": 0, "v": i} for i in range(5)], parallelism=1).groupby("k").sum("v")
    assert out.take_all() == [{"k": 0, "sum(v)": 10}]


def test_streaming_split_count_not_destructive(ray_cluster):
    ds = rd.range(40, parallelism=4)
    it = ds.streaming_split(2)[0]
    n = it.count()
    total = sum(len(b["id"]) for b in it.iter_batches(batch_size=8))
    assert total == n  # count() must not consume the shard


def test_repartition(ray_cluster):
    ds = rd.range(100, parallelism=10).repartition(3)
    assert ds.num_blocks() == 3
    assert [r["id"] for r in ds.take_all()] == list(range(100))


def test_limit_union_zip(ray_cluster):
    ds = rd.range(100, parallelism=4).limit(17)
    assert ds.count() == 17
    u = rd.range(10).union(rd.range(6))
    assert u.count() == 16
    a = rd.range(10, parallelism=2)
    b = rd.range(10, parallelism=3).map_batches(lambda x: {"d": x["id"] * 10})
    z = a.zip(b)
    rows = z.take_all()
    assert all(r["d"] == r["id"] * 10 for r in rows)


def test_aggregates(ray_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.sum("id") == sum(range(100))
    assert ds.min("id") == 0
    assert ds.max("id") == 99
    assert abs(ds.mean("id") - 49.5) < 1e-9
    assert abs(ds.std("id") - np.std(np.arange(100), ddof=1)) < 1e-6


def test_groupby(ray_cluster):
    ds = rd.from_items([{"k": i % 4, "v": i} for i in range(40)])
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {}
    for i in range(40):
        expect[i % 4] = expect.get(i % 4, 0) + i
    assert out == expect
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10, 3: 10}


def test_groupby_map_groups(ray_cluster):
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(30)])
    normed = ds.groupby("k").map_groups(
        lambda batch: {"k": batch["k"], "v": batch["v"] - batch["v"].mean()}
    )
    rows = normed.take_all()
    assert len(rows) == 30
    by_k: dict = {}
    for r in rows:
        by_k.setdefault(r["k"], []).append(r["v"])
    for vs in by_k.values():
        assert abs(sum(vs)) < 1e-9


def test_iter_batches_shapes(ray_cluster):
    ds = rd.range(1000, parallelism=5)
    batches = list(ds.iter_batches(batch_size=128))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 1000
    assert all(s == 128 for s in sizes[:-1])
    # drop_last
    batches = list(ds.iter_batches(batch_size=128, drop_last=True))
    assert all(len(b["id"]) == 128 for b in batches)
    # pandas format
    pdb = next(iter(ds.iter_batches(batch_size=10, batch_format="pandas")))
    assert list(pdb["id"]) == list(range(10))


def test_iter_jax_batches_sharded(ray_cluster):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ds = rd.range(64, parallelism=2)
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    batch = next(iter(ds.iter_jax_batches(batch_size=32, sharding=sharding)))
    assert batch["id"].shape == (32,)
    assert batch["id"].sharding == sharding


def test_tensor_columns_roundtrip(ray_cluster):
    arr = np.arange(60, dtype=np.float32).reshape(10, 2, 3)
    ds = rd.from_numpy(arr, column="img")
    out = next(iter(ds.iter_batches(batch_size=10)))["img"]
    np.testing.assert_array_equal(out, arr)
    # through a map
    ds2 = ds.map_batches(lambda b: {"img": b["img"] * 2})
    out2 = next(iter(ds2.iter_batches(batch_size=10)))["img"]
    np.testing.assert_array_equal(out2, arr * 2)


def test_split_and_streaming_split(ray_cluster):
    ds = rd.range(90, parallelism=4)
    parts = ds.split(3, equal=True)
    assert [p.count() for p in parts] == [30, 30, 30]
    all_ids = sorted(r["id"] for p in parts for r in p.take_all())
    assert all_ids == list(range(90))
    its = ds.streaming_split(2)
    totals = [sum(len(b["id"]) for b in it.iter_batches(batch_size=16)) for it in its]
    assert sum(totals) == 90


def test_split_at_indices_train_test(ray_cluster):
    ds = rd.range(100, parallelism=4)
    a, b, c = ds.split_at_indices([30, 70])
    assert (a.count(), b.count(), c.count()) == (30, 40, 30)
    train, test = ds.train_test_split(0.2)
    assert (train.count(), test.count()) == (80, 20)


def test_parquet_csv_json_roundtrip(ray_cluster, tmp_path):
    ds = rd.range(50, parallelism=2).map_batches(lambda b: {"id": b["id"], "v": b["id"] * 1.5})
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rd.read_parquet(pq_dir)
    assert back.count() == 50
    assert back.sum("id") == sum(range(50))

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    assert rd.read_csv(csv_dir).count() == 50

    js_dir = str(tmp_path / "js")
    ds.write_json(js_dir)
    assert rd.read_json(js_dir).count() == 50


def test_read_text_binary(ray_cluster, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("alpha\nbeta\n\ngamma\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["alpha", "beta", "gamma"]
    b = rd.read_binary_files(str(p))
    assert b.take_all()[0]["bytes"] == p.read_bytes()


def test_materialize_caches(ray_cluster):
    ds = rd.range(30, parallelism=3).map_batches(lambda b: {"id": b["id"] + 1})
    mat = ds.materialize()
    assert mat.count() == 30
    assert mat.count() == 30  # second consumption reuses cached bundles
    assert sorted(r["id"] for r in mat.take_all()) == list(range(1, 31))


def test_random_sample_add_column(ray_cluster):
    ds = rd.range(1000, parallelism=2).random_sample(0.5, seed=0)
    assert 300 < ds.count() < 700
    ds2 = rd.range(10, parallelism=1).add_column("double", lambda df: df["id"] * 2)
    assert all(r["double"] == r["id"] * 2 for r in ds2.take_all())


def test_unique_and_stats(ray_cluster):
    ds = rd.from_items([{"x": i % 5} for i in range(25)])
    assert ds.unique("x") == [0, 1, 2, 3, 4]
    assert "blocks" in ds.stats()


def test_streaming_backpressure_bounded(ray_cluster):
    """Budget gating (reference: streaming_executor_state select_operator_to_run
    + under_output_budget): with 10x more blocks than max_tasks_in_flight, no
    op runs further ahead than the per-op block budget — a fast read can't
    materialize the whole dataset while the map stage lags."""
    from ray_tpu.data._internal.executor import ExecutionContext, execute_streaming
    import ray_tpu.data as rdata

    ds = rdata.range(400, parallelism=40).map_batches(lambda b: b)
    ctx = ExecutionContext(max_tasks_in_flight=2)
    out = list(execute_streaming(ds._plan, ctx))
    assert sum(m.num_rows for _, m in out) == 400
    budget = ctx.per_op_budget_blocks
    assert ctx.stats["max_inter_op_queued"] <= budget, ctx.stats
    assert ctx.stats["max_inflight"] <= budget, ctx.stats


def test_shuffle_blocks_stay_off_driver(ray_cluster):
    """random_shuffle moves blocks peer-to-peer via refs; the driver sees
    only metadata. Guard: the result bundles are refs, and the total rows
    survive the shuffle."""
    import ray_tpu.data as rdata
    from ray_tpu.object_ref import ObjectRef

    ds = rdata.range(1000, parallelism=8).random_shuffle(seed=7)
    bundles = ds._execute()
    assert all(isinstance(ref, ObjectRef) for ref, _ in bundles)
    assert sum(m.num_rows for _, m in bundles) == 1000
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(1000))


def test_streaming_split_equal_rows(ray_cluster):
    """streaming_split(equal=True): every shard sees the same number of rows
    even with ragged blocks (SPMD gang safety — reference: OutputSplitter
    with equal=True)."""
    ds = rd.from_items([{"x": i} for i in range(103)])  # ragged vs 4 shards
    shards = ds.streaming_split(4, equal=True)
    counts = []
    for it in shards:
        counts.append(sum(len(b["x"]) for b in it.iter_batches(batch_size=10)))
    assert len(set(counts)) == 1, counts
    assert counts[0] >= 20


def test_push_based_shuffle_matches_pull_based(ray_cluster):
    """The 3-stage push-based shuffle is a drop-in for the 2-stage one
    (reference: push_based_shuffle.py) — same rows out, fewer reducer
    inputs."""
    from ray_tpu.data._internal import shuffle as shuffle_mod
    from ray_tpu.data.context import DataContext

    ds = rd.range(500, parallelism=20)
    bundles = list(ds.iter_internal_refs())
    pushed = shuffle_mod.push_based_shuffle(bundles, seed=7)
    assert sum(m.num_rows for _, m in pushed) == 500
    assert len(pushed) == 20
    ctx = DataContext.get_current()
    old = ctx.use_push_based_shuffle
    try:
        ctx.use_push_based_shuffle = True
        out = rd.range(500, parallelism=20).random_shuffle(seed=7)
        ids = sorted(r["id"] for r in out.take_all())
        assert ids == list(range(500))
        # And the order actually changed (it IS a shuffle).
        assert [r["id"] for r in out.take_all()] != list(range(500))
    finally:
        ctx.use_push_based_shuffle = old


def test_dataset_stats_per_operator(ray_cluster):
    ds = rd.range(200, parallelism=4).map_batches(lambda b: {"id": b["id"] * 2}).random_shuffle(seed=0)
    ds.materialize()
    s = ds.stats()
    assert "Operator" in s
    assert "RandomShuffle" in s
    assert "rows" in s and "blocks" in s
    # totals line still present
    assert "Dataset: " in s


def test_sql_datasource_roundtrip(ray_cluster, tmp_path):
    """read_sql + write_sql over sqlite3 (reference: sql_datasource.py)."""
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE src (id INTEGER, val TEXT)")
    conn.executemany(
        "INSERT INTO src VALUES (?, ?)", [(i, f"v{i}") for i in range(100)]
    )
    conn.execute("CREATE TABLE dst (id INTEGER, val TEXT)")
    conn.commit()
    conn.close()

    factory = lambda: __import__("sqlite3").connect(db)  # noqa: E731

    # Single-task read.
    ds = rd.read_sql("SELECT * FROM src", factory)
    rows = ds.take_all()
    assert len(rows) == 100
    assert sorted(r["id"] for r in rows) == list(range(100))

    # Sharded read: multiple read tasks over id ranges.
    ds2 = rd.read_sql("SELECT * FROM src", factory, parallelism=4, shard_column="id")
    assert ds2.num_blocks() > 1
    assert sorted(r["id"] for r in ds2.take_all()) == list(range(100))

    # NULL shard-column rows must survive sharded reads (they fail every
    # range predicate; a dedicated NULL-shard task catches them).
    conn = sqlite3.connect(db)
    conn.execute("INSERT INTO src VALUES (NULL, 'null-row')")
    conn.commit()
    conn.close()
    ds3 = rd.read_sql("SELECT * FROM src", factory, parallelism=4, shard_column="id")
    assert len(ds3.take_all()) == 101

    # write_sql back into another table.
    written = ds2.write_sql("dst", factory)
    assert written == 100
    check = sqlite3.connect(db)
    assert check.execute("SELECT COUNT(*), MIN(id), MAX(id) FROM dst").fetchone() == (100, 0, 99)
    check.close()
