"""graftlint unit tests: synthetic fixture modules with PLANTED concurrency
bugs, one per pass — a loop-affinity leak, a blocking call in ``async def``,
an AB/BA lock cycle — asserting each pass catches exactly its bug (and not
the correct twin right next to it), plus the baseline + pragma suppression
mechanics and the RAY_TPU_DEBUG_AFFINITY runtime asserts."""

import os
import textwrap

import pytest

from ray_tpu.tools.graftlint.cli import analyze, main
from ray_tpu.tools.graftlint.findings import write_baseline

AFFINITY_FIXTURE = """
    import asyncio
    import threading
    import time

    from ray_tpu._private.concurrency import any_thread, blocking, loop_only


    class Client:
        @loop_only
        def send_frame(self, data):
            pass

        @blocking
        def call(self, method):
            time.sleep(0.1)


    class Good:
        def __init__(self, client, loop):
            self.client = client
            self._loop = loop

        @any_thread
        def submit(self, item):
            # correct: threadsafe hop onto the loop before touching the
            # loop-only fast path
            self._loop.call_soon_threadsafe(self._drain)

        @loop_only
        def _drain(self):
            self.client.send_frame(b"x")

        async def handler(self, req):
            # correct: blocking work leaves the loop
            loop = asyncio.get_event_loop()
            return await loop.run_in_executor(None, self.client.call, "m")


    class Leaky:
        def __init__(self, client):
            self.client = client

        def start(self):
            threading.Thread(target=self._worker_loop).start()

        def _worker_loop(self):
            # PLANTED: thread context straight into a @loop_only function
            self.client.send_frame(b"x")


    class DeadlockRisk:
        def __init__(self, client):
            self.client = client

        async def rpc_handler(self, req):
            # PLANTED: @blocking call on the event loop
            return self.client.call("m")


    class Redundant:
        def __init__(self, loop):
            self._loop = loop

        @loop_only
        def _already_on_loop(self):
            # PLANTED: threadsafe hop from code that is already on the loop
            self._loop.call_soon_threadsafe(self._noop)

        def _noop(self):
            pass
"""

BLOCKING_FIXTURE = """
    import asyncio
    import subprocess
    import time


    async def bad_sleep():
        time.sleep(0.5)  # PLANTED
        return 1


    async def good_sleep():
        await asyncio.sleep(0.01)
        return 1


    async def bad_wait(ev):
        ev.wait()  # PLANTED (threading.Event)


    async def good_wait(aev):
        await asyncio.wait_for(aev.wait(), 1.0)  # asyncio idiom: not a block


    async def bad_spawn(cmd):
        subprocess.check_output(cmd)  # PLANTED


    async def good_spawn(fn):
        await asyncio.get_event_loop().run_in_executor(None, fn)


    async def allowed_sleep():
        time.sleep(0.01)  # graftlint: ignore[sleep-in-async] — documented
"""

LOCK_FIXTURE = """
    import asyncio
    import threading


    class ABBA:
        def __init__(self):
            self._lock_a = threading.Lock()
            self._lock_b = threading.Lock()

        def a_then_b(self):
            with self._lock_a:
                with self._lock_b:
                    pass

        def b_then_a(self):
            # PLANTED: reverse order via an interprocedural edge
            with self._lock_b:
                self._take_a()

        def _take_a(self):
            with self._lock_a:
                pass


    class SelfNest:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self):
            with self._lock:
                self._helper()  # PLANTED: re-acquires while held

        def _helper(self):
            with self._lock:
                pass


    class Ordered:
        def __init__(self):
            self._lock_x = threading.Lock()
            self._lock_y = threading.Lock()

        def fine(self):
            with self._lock_x:
                with self._lock_y:
                    pass

        def also_fine(self):
            with self._lock_x:
                pass

        async def bad_await(self):
            with self._lock_x:
                await asyncio.sleep(0.1)  # PLANTED: await under sync lock
"""


@pytest.fixture
def fixture_pkg(tmp_path):
    pkg = tmp_path / "fixturepkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "aff.py").write_text(textwrap.dedent(AFFINITY_FIXTURE))
    (pkg / "blk.py").write_text(textwrap.dedent(BLOCKING_FIXTURE))
    (pkg / "lck.py").write_text(textwrap.dedent(LOCK_FIXTURE))
    return str(pkg)


def _by_code(findings):
    out = {}
    for f in findings:
        out.setdefault(f.code, []).append(f)
    return out


def test_affinity_pass_catches_planted_leak(fixture_pkg):
    _, findings = analyze([fixture_pkg], passes={"affinity"})
    by_code = _by_code(findings)
    leaks = by_code.get("affinity-leak", [])
    assert len(leaks) == 1, [f.message for f in findings]
    assert leaks[0].symbol == "Leaky._worker_loop"
    assert "send_frame" in leaks[0].detail
    blocked = by_code.get("blocking-on-loop", [])
    assert len(blocked) == 1, [f.message for f in findings]
    assert blocked[0].symbol == "DeadlockRisk.rpc_handler"
    redundant = by_code.get("redundant-hop", [])
    assert len(redundant) == 1
    assert redundant[0].symbol == "Redundant._already_on_loop"
    # the correct twins produced nothing
    assert not any("Good" in f.symbol for f in findings), [f.message for f in findings]


def test_blocking_pass_catches_planted_calls(fixture_pkg):
    _, findings = analyze([fixture_pkg], passes={"blocking"})
    symbols = {(f.symbol, f.code) for f in findings}
    assert ("bad_sleep", "sleep-in-async") in symbols
    assert ("bad_wait", "sync-wait-in-async") in symbols
    assert ("bad_spawn", "subprocess-in-async") in symbols
    # asyncio idioms and the pragma-suppressed sleep stay clean
    assert not any("good" in s for s, _ in symbols), symbols
    assert not any(s == "allowed_sleep" for s, _ in symbols)
    assert len(findings) == 3, [f.message for f in findings]


def test_lockorder_pass_catches_cycle_selfnest_and_await(fixture_pkg):
    _, findings = analyze([fixture_pkg], passes={"lockorder"})
    by_code = _by_code(findings)
    cycles = by_code.get("lock-cycle", [])
    assert len(cycles) == 1, [f.message for f in findings]
    assert "ABBA._lock_a" in cycles[0].detail and "ABBA._lock_b" in cycles[0].detail
    self_nest = by_code.get("lock-self-nest", [])
    assert len(self_nest) == 1
    assert self_nest[0].detail == "SelfNest._lock"
    awaits = by_code.get("await-under-lock", [])
    assert len(awaits) == 1
    assert awaits[0].symbol == "Ordered.bad_await"
    # the consistently-ordered Ordered locks are not part of any cycle
    assert not any("Ordered" in f.detail for f in cycles)


def test_baseline_suppresses_only_baselined_findings(fixture_pkg, tmp_path):
    _, findings = analyze([fixture_pkg])
    assert findings
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, findings)
    # with every current finding baselined the CLI exits 0
    assert main([fixture_pkg, "--baseline", baseline_path]) == 0
    # a NEW violation still fails, and is the only one reported
    extra = os.path.join(fixture_pkg, "extra.py")
    with open(extra, "w") as f:
        f.write("import time\nasync def fresh():\n    time.sleep(1)\n")
    assert main([fixture_pkg, "--baseline", baseline_path]) == 1
    _, findings2 = analyze([fixture_pkg])
    new_keys = {x.key for x in findings2} - {x.key for x in findings}
    assert len(new_keys) == 1 and "fresh" in next(iter(new_keys))
    # --write-baseline + rerun converges back to exit 0
    write_baseline(baseline_path, findings2)
    assert main([fixture_pkg, "--baseline", baseline_path]) == 0


def test_fix_annotations_suggests_roles(fixture_pkg, capsys):
    rc = main([fixture_pkg, "--fix-annotations", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1  # planted findings still fail
    # Redundant._noop is a call_soon_threadsafe target without a marker
    assert "Redundant._noop" in out and "@loop_only" in out
    # Leaky._worker_loop is a Thread target without a marker
    assert "Leaky._worker_loop" in out and "@any_thread" in out


def test_debug_affinity_runtime_asserts():
    """Dynamic backup for the static checks: with RAY_TPU_DEBUG_AFFINITY=1
    (set by tests/conftest.py before ray_tpu import) the markers assert."""
    from ray_tpu._private import concurrency

    if not concurrency.DEBUG_AFFINITY:
        pytest.skip("RAY_TPU_DEBUG_AFFINITY not enabled at import time")

    @concurrency.loop_only
    def on_loop_fn():
        return "ok"

    @concurrency.blocking
    def blocking_fn():
        return "ok"

    # off-loop: loop_only must assert, blocking must pass
    with pytest.raises(AssertionError, match="loop_only"):
        on_loop_fn()
    assert blocking_fn() == "ok"

    # on a running loop: loop_only passes, blocking asserts
    import asyncio

    async def drive():
        assert on_loop_fn() == "ok"
        with pytest.raises(AssertionError, match="blocking"):
            blocking_fn()

    asyncio.run(drive())
