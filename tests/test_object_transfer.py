"""Push-side object transfer + broadcast (reference: push_manager.h:29,
pull_manager.h:52; VERDICT r1 #4)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.object_transfer import broadcast_object


def _locations(oid_hex):
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    locs = cw.gcs.call("get_object_locations", {"object_id": oid_hex})["locations"]
    return {loc["node_id"] for loc in locs}


def test_broadcast_reaches_all_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(4):
        cluster.add_node(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    cluster.connect()
    cluster.wait_for_nodes()

    data = np.arange(8 * 1024 * 1024, dtype=np.uint8)  # 8 MiB -> multiple chunks
    ref = ray_tpu.put(data)
    n = broadcast_object(ref)
    assert n == 3  # pushed to every node except the one already holding it
    assert len(_locations(ref.hex())) == 4
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(np.asarray(out), data)


def test_broadcast_subset_and_idempotent(ray_start_cluster):
    cluster = ray_start_cluster
    nodes = [cluster.add_node(num_cpus=1, object_store_memory=64 * 1024 * 1024) for _ in range(3)]
    cluster.connect()
    cluster.wait_for_nodes()

    ref = ray_tpu.put(np.ones(512 * 1024, dtype=np.float32))
    have = _locations(ref.hex())
    target = next(n.node_id for n in nodes if n.node_id not in have)
    assert broadcast_object(ref, node_ids=[target]) == 1
    assert target in _locations(ref.hex())
    # Re-broadcast: target already holds it, nothing pushed.
    assert broadcast_object(ref, node_ids=[target]) == 0


def test_broadcast_small_object_raises(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()
    cluster.wait_for_nodes()

    ref = ray_tpu.put(42)  # in-process store, no plasma copy
    with pytest.raises(ValueError, match="plasma"):
        broadcast_object(ref)


def test_concurrent_broadcasts(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    cluster.connect()
    cluster.wait_for_nodes()

    refs = [ray_tpu.put(np.full(256 * 1024, i, dtype=np.int32)) for i in range(4)]
    import threading

    errs = []

    def bc(r):
        try:
            broadcast_object(r)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=bc, args=(r,)) for r in refs]
    [t.start() for t in ts]
    [t.join(timeout=300) for t in ts]
    assert not errs
    for i, r in enumerate(refs):
        assert len(_locations(r.hex())) == 3
        np.testing.assert_array_equal(
            np.asarray(ray_tpu.get(r)), np.full(256 * 1024, i, dtype=np.int32)
        )
