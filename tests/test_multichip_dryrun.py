"""Multi-chip dryrun at larger/uneven device counts (VERDICT r3 #10).

Runs __graft_entry__.dryrun_multichip in subprocesses with N virtual CPU
devices: 16 (the next pod step beyond the driver's 8-device check) and 12
(uneven — a non-power-of-two mesh forces factorizations like dp=2,tp=2,pp=3
and sp=2,ep=6 through every sharding rule). Both passes must execute and
print finite losses.
"""

import os
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ENTRY = os.path.join(_REPO, "__graft_entry__.py")


def _run(n_devices: int) -> str:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "RAY_TPU_JAX_CONFIG_PLATFORMS": "cpu",
        "RAY_TPU_NUM_TPUS": "0",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={n_devices}",
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, _ENTRY, str(n_devices)],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
        cwd=_REPO,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip({n_devices}) failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-3000:]}"
    )
    return proc.stdout


@pytest.mark.parametrize("n_devices", [16, 12])
def test_dryrun_multichip_scales(n_devices):
    out = _run(n_devices)
    m = re.search(
        rf"dryrun_multichip\({n_devices}\): pass1\(dp=(\d+),tp=(\d+),pp=(\d+)\) "
        r"loss=([\d.]+); pass2\(sp=(\d+),ep=(\d+),moe\) loss=([\d.]+)",
        out,
    )
    assert m, f"unexpected dryrun output:\n{out[-1500:]}"
    dp, tp, pp, loss1, sp, ep, loss2 = m.groups()
    assert int(dp) * int(tp) * int(pp) == n_devices
    assert int(sp) * int(ep) == n_devices
    if n_devices == 12:
        # Uneven: at least one factor is not a power of two.
        assert any(int(x) % 2 == 1 and int(x) > 1 for x in (dp, tp, pp, sp, ep))
    assert float(loss1) == float(loss1) and float(loss1) < 100  # finite, sane
    assert float(loss2) == float(loss2) and float(loss2) < 100
