"""Self-healing LLM serving (ISSUE 14): mid-stream request migration with
teacher-forced resumption after a seeded replica kill, drain-before-retire
under rolling updates, and the assign->dead-replica handle reassign.

Layout (tier-1 budget): ONE module-scoped single-node cluster + serve
instance + 2-replica LLMDeployment hosts everything; the seeded-sampling
migration arm and the rolling-update drain oracle are marked `slow` (each
spawns extra replica processes); the greedy migration oracle — THE tentpole
acceptance test — runs in tier-1.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve._private.common import CONTROLLER_NAME

MODEL = dict(
    vocab_size=64,
    d_model=32,
    n_layers=1,
    n_heads=2,
    n_kv_heads=2,
    d_ff=48,
    max_seq_len=64,
    dtype="float32",
    remat=False,
)
ENGINE = dict(num_slots=4, block_size=4, max_model_len=64, prefill_chunk=4)


@pytest.fixture(scope="module")
def ft_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    try:
        cluster.add_node(num_cpus=6, object_store_memory=96 * 1024 * 1024)
        cluster.connect()
        cluster.wait_for_nodes()
        serve.start()
        yield cluster
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


@pytest.fixture(scope="module")
def llm_app(ft_cluster):
    from ray_tpu.serve.llm import LLMDeployment

    app = serve.deployment(num_replicas=2, version="v1")(LLMDeployment).bind(
        MODEL, engine_config=dict(ENGINE)
    )
    handle = serve.run(app, route_prefix="/llm")
    return ft_cluster, handle


def _oracle(prompt, n, **sampling):
    """Uninterrupted reference run on a LOCAL engine with the same
    seed-deterministic params the replicas build (init_seed=0)."""
    import jax

    from ray_tpu.models.transformer import TransformerConfig, init_params
    from ray_tpu.serve.llm import LLMEngine

    kw = dict(MODEL)
    import jax.numpy as jnp

    kw["dtype"] = jnp.dtype(kw["dtype"]).type
    cfg = TransformerConfig(**kw)
    eng = LLMEngine(init_params(jax.random.PRNGKey(0), cfg), cfg, **ENGINE)
    try:
        return eng.submit(prompt, max_new_tokens=n, **sampling).result(120)
    finally:
        eng.shutdown()


def _replica_actors():
    """actor_name list for the llm deployment, from the controller table."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    table = ray_tpu.get(controller.get_routing_table.remote(-2, 0.1))["table"]
    entry = table.get("LLMDeployment") or {}
    return [r["actor_name"] for r in entry.get("replicas", [])]


def _stream_sse(url, body, toks, events, timeout=300):
    """POST one streaming request and drain its SSE events."""
    req = urllib.request.Request(url, data=json.dumps(body).encode())
    return _stream_sse_resp(urllib.request.urlopen(req, timeout=timeout), toks, events)


def _stream_sse_resp(resp, toks, events):
    """Read one SSE stream incrementally; tokens append into `toks` as they
    arrive (so callers can act mid-stream); events records (t, kind)."""
    buf = b""
    while True:
        chunk = resp.read(64)
        if not chunk:
            return False
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            if not event.startswith(b"data: "):
                continue
            payload = event[6:]
            if payload == b"[DONE]":
                events.append((time.monotonic(), "done"))
                return True
            toks.append(json.loads(payload)["token"])
            events.append((time.monotonic(), "token"))


def _flight_events(cluster, kind, since_wall):
    io_events = []
    from ray_tpu._private.rpc import EventLoopThread

    resp = EventLoopThread.get().run(cluster.nodes[0].rpc_debug_dump({}), timeout=15)
    for proc in resp.get("processes", []):
        for ev in proc.get("events", []):
            if ev.get("type") == kind and ev.get("ts", 0) >= since_wall - 2.0:
                io_events.append(ev)
    return io_events


def _run_migration_oracle(llm_app, prompt, n, sampling):
    """Kill the serving replica mid-stream with a SEEDED plan; the stream
    must resume on another replica and the client must see the byte-exact
    uninterrupted token sequence, nothing re-emitted, nothing dropped.

    The victim is PRE-PICKED: the request carries its prefix routing hint,
    which pins it to replicas[crc32(hint) % n] — so the kill plan can be
    armed in that replica's process BEFORE the request, and the kill point
    (the 3rd actor-call response after install: the request accept + 2
    stream-chunk pumps) is seeded and replayable."""
    import zlib

    from ray_tpu.serve._private.common import PREFIX_HINT_HEADER
    from ray_tpu.serve.llm import prefix_route_hint

    cluster, _handle = llm_app
    expect = _oracle(prompt, n, **sampling)
    host, port = serve.http_address()
    t_wall0 = time.time()
    hint = prefix_route_hint(prompt, ENGINE["block_size"])
    assert hint
    # A previous kill's replacement may still be booting; the victim pick
    # needs the full 2-replica table.
    deadline = time.monotonic() + 180
    actors = _replica_actors()
    while len(actors) < 2 and time.monotonic() < deadline:
        time.sleep(0.25)
        actors = _replica_actors()
    assert len(actors) == 2, actors
    victim = actors[zlib.crc32(hint.encode()) % len(actors)]
    assert cluster.install_plan_in_actor(
        victim,
        {"rules": [{"kind": "kill", "method": ["actor_call"],
                    "side": "resp", "after": 2, "times": 1}]},
        seed=13,
    )
    toks: list = []
    events: list = []
    body = dict(tokens=prompt, max_new_tokens=n, **sampling)
    req = urllib.request.Request(
        f"http://{host}:{port}/llm",
        data=json.dumps(body).encode(),
        headers={PREFIX_HINT_HEADER: hint},
    )
    done = _stream_sse_resp(urllib.request.urlopen(req, timeout=240), toks, events)
    assert done, "stream ended without [DONE]"
    assert toks == expect, (toks, expect)
    # The proxy recorded the migration; the victim's last words are the
    # chaos_kill event in its (SIGKILL-surviving) flight ring.
    assert _flight_events(cluster, "llm_migrate", t_wall0), "no migration recorded"
    assert _flight_events(cluster, "chaos_kill", t_wall0), "no kill recorded"
    # Leak oracle: every LIVE replica's KV pool is back to full.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        stats = []
        for name in _replica_actors():
            try:
                stats.append(ray_tpu.get(
                    ray_tpu.get_actor(name).handle_request.remote(
                        "get_stats", (), {}
                    ),
                    timeout=15,
                ))
            except Exception:
                pass
        if stats and all(
            s["free_blocks"] + s["cached_blocks"] == s["num_blocks"] for s in stats
        ):
            return
        time.sleep(0.25)
    pytest.fail(f"surviving replicas leaked KV blocks: {stats}")


def test_midstream_kill_migrates_greedy(llm_app):
    """THE tentpole oracle, greedy arm: a replica SIGKILLed mid-decode by a
    seeded plan; the proxy resubmits with resume_tokens= and the client's
    token sequence is byte-identical to an uninterrupted run."""
    _run_migration_oracle(
        llm_app, prompt=[3, 1, 4, 1, 5, 9, 2, 6], n=24, sampling={}
    )


@pytest.mark.slow
def test_midstream_kill_migrates_seeded_sampling(llm_app):
    """Sampled arm: the counter-based per-request RNG stream makes the
    migrated continuation bit-identical too."""
    _run_migration_oracle(
        llm_app,
        prompt=[2, 7, 1, 8, 2, 8, 1, 8],
        n=24,
        sampling=dict(temperature=0.9, top_k=16, seed=11),
    )


@pytest.mark.slow
def test_rolling_update_drains_streams(llm_app):
    """Drain oracle: a rolling update (v1 -> v2) under a CLOSED LOOP of
    concurrent streams completes with ZERO dropped streams and every
    stream's tokens matching the oracle — streams that straddle a retire
    finish on the draining replica; new requests land on live ones (the
    proxy reassigns across the drain-refusal race)."""
    from ray_tpu.serve.llm import LLMDeployment

    cluster, _handle = llm_app
    host, port = serve.http_address()
    t_wall0 = time.time()
    n = 32
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 64, 6).tolist() for _ in range(3)]
    oracles = [_oracle(p, n) for p in prompts]
    stop = threading.Event()
    failures: list = []
    completions = [0]

    def closed_loop(i):
        while not stop.is_set():
            toks: list = []
            try:
                done = _stream_sse(
                    f"http://{host}:{port}/llm",
                    dict(tokens=prompts[i], max_new_tokens=n),
                    toks, [],
                )
                assert done, "stream ended without [DONE]"
                assert toks == oracles[i], (toks, oracles[i])
                completions[0] += 1
            except Exception as e:  # noqa: BLE001
                failures.append(f"stream {i}: {type(e).__name__}: {e}")
                return

    threads = [
        threading.Thread(target=closed_loop, args=(i,), daemon=True)
        for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 60
    while completions[0] < 2 and not failures and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not failures, failures
    # Roll to v2 while the loop keeps streaming. serve.run blocks until
    # the new version covers the target (old replicas drain in background).
    app2 = serve.deployment(num_replicas=2, version="v2")(LLMDeployment).bind(
        MODEL, engine_config=dict(ENGINE)
    )
    serve.run(app2, route_prefix="/llm")
    time.sleep(1.0)  # a few post-update iterations
    stop.set()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads)
    assert not failures, f"dropped/corrupt streams across the update: {failures}"
    st = serve.status()["LLMDeployment"]
    assert st["version"] == "v2"
    # The drains were recorded (begin + a terminal outcome per old replica).
    drains = [e["detail"] for e in _flight_events(cluster, "replica_drain", t_wall0)]
    assert any(d.endswith(":begin") for d in drains), drains
    assert any(
        d.split(":", 1)[1] in ("clean", "timeout") for d in drains
    ), drains


def test_handle_reassigns_off_dead_replica(ft_cluster):
    """Satellite: a non-streaming handle call assigned to a replica that
    died before accepting transparently reassigns ONCE (bounded) instead of
    surfacing raw ActorDiedError — pinned on a bare Router with a stale
    hand-fed table that still lists the corpse."""
    import os as _os

    from ray_tpu.serve._private.router import Router
    from ray_tpu.serve.handle import DeploymentHandle

    class FakeReplica:
        def handle_request(self, method, args, kwargs, multiplexed_model_id=""):
            return f"pong-{_os.getpid()}"

    a = ray_tpu.remote(name="ftrep-a")(FakeReplica).remote()
    b = ray_tpu.remote(name="ftrep-b")(FakeReplica).remote()
    try:
        ray_tpu.get(a.handle_request.remote("__call__", (), {}), timeout=60)
        ray_tpu.get(b.handle_request.remote("__call__", (), {}), timeout=60)
        ray_tpu.kill(a)
        # Wait until the GCS reflects the death (the probe's source of truth).
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                ray_tpu.get_actor("ftrep-a")
                time.sleep(0.1)
            except Exception:
                break
        router = Router(None)
        router._table = {
            "dep": {
                "replicas": [
                    {"replica_id": "ra", "actor_name": "ftrep-a",
                     "max_concurrent_queries": 10},
                    {"replica_id": "rb", "actor_name": "ftrep-b",
                     "max_concurrent_queries": 10},
                ],
                "route_prefix": None,
            }
        }
        router._rr["dep"] = 0  # round-robin picks the corpse first
        handle = DeploymentHandle("dep", router)
        out = ray_tpu.get(handle.remote(), timeout=60)
        assert out.startswith("pong-")
        # The dead replica's claimed slot was released on reassign.
        assert router._inflight.get("ftrep-a", 0) == 0
    finally:
        for h in (a, b):
            try:
                ray_tpu.kill(h)
            except Exception:
                pass
