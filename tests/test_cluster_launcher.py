"""Cluster launcher (`ray_tpu up/down/exec`) + bandits + tuned-example tests.

Reference analogs: `ray up/down` (scripts.py:1235/1311) with the fake
multi-node provider, rllib/algorithms/bandit tests, tuned_examples regression
runs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cluster_up_exec_down(tmp_path):
    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        """
cluster_name: launcher_test
max_workers: 2
head_node:
  resources: {CPU: 2}
provider:
  type: fake
available_node_types:
  cpu_worker:
    resources: {CPU: 2}
    max_workers: 2
"""
    )
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               RAY_TPU_JAX_CONFIG_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    up = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.scripts", "up", str(cfg)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert up.returncode == 0, up.stdout + up.stderr
    assert "is up" in up.stdout
    try:
        with open("/tmp/ray_tpu/clusters/launcher_test.json") as f:
            info = json.load(f)
        # exec: a driver against the launched cluster sees it via env.
        script = tmp_path / "probe.py"
        script.write_text(
            "import ray_tpu\n"
            "ray_tpu.init(address='auto')\n"
            "print('CPUS', int(ray_tpu.cluster_resources().get('CPU', 0)))\n"
            "ray_tpu.shutdown()\n"
        )
        ex = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.scripts", "exec", str(cfg),
             f"{sys.executable} {script}"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert ex.returncode == 0, ex.stdout + ex.stderr
        assert "CPUS" in ex.stdout
    finally:
        down = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.scripts", "down", str(cfg)],
            env=env, capture_output=True, text=True, timeout=120,
        )
    assert down.returncode == 0, down.stdout + down.stderr
    assert not os.path.exists("/tmp/ray_tpu/clusters/launcher_test.json")


class _ContextBanditEnv:
    """2-arm contextual bandit: arm 0 pays when ctx[0] > 0, else arm 1."""

    import gymnasium as gym

    observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
    action_space = gym.spaces.Discrete(2)

    def __init__(self, config=None):
        self._rng = np.random.default_rng(0)
        self._ctx = None

    def _next(self):
        self._ctx = self._rng.uniform(-1, 1, 2).astype(np.float32)
        return self._ctx

    def reset(self, *, seed=None, options=None):
        return self._next(), {}

    def step(self, action):
        good = 0 if self._ctx[0] > 0 else 1
        r = 1.0 if int(action) == good else 0.0
        return self._next(), r, True, False, {}

    def close(self):
        pass


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


@pytest.mark.parametrize("cls_name", ["BanditLinUCB", "BanditLinTS"])
def test_bandits_learn_context(ray_start_regular, cls_name):
    import ray_tpu.rllib as rllib

    cls = getattr(rllib, cls_name)
    cfg = cls.get_default_config().environment(lambda config: _ContextBanditEnv(config))
    cfg.steps_per_iter = 200
    algo = cfg.build()
    try:
        for _ in range(5):
            r = algo.step()
        # Random play gets 0.5; a fitted linear model should be near-perfect.
        assert r["mean_reward"] > 0.8, r
        assert algo.compute_single_action(np.array([0.9, 0.0], np.float32)) == 0
        assert algo.compute_single_action(np.array([-0.9, 0.0], np.float32)) == 1
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_tuned_example_runs(ray_start_regular, capsys):
    from ray_tpu.rllib.train import run_tuned_example

    path = os.path.join(REPO, "ray_tpu", "rllib", "tuned_examples", "cartpole-ppo.yaml")
    out = run_tuned_example(path, max_iters_override=2)
    assert "cartpole-ppo" in out
    assert "episode_reward_mean" in out["cartpole-ppo"]
    printed = capsys.readouterr().out
    assert "[cartpole-ppo] iter 1" in printed
