"""Router/proxy unit tests for disaggregated serving (ISSUE 20) — no
cluster: a bare ``Router(None)`` with a hand-fed table, and the proxy ASGI
app driven directly with fake replica actors.

Pins the drain satellite:
- draining replicas are excluded from EVERY assignment policy (round-robin,
  model_id affinity, prefix-affinity pin AND its least-depth spill);
- a drain-refused assignment never burns one of the proxy's bounded
  reassign retries (the bound exists for crashes, not polite refusals);
and the disaggregation tentpole's proxy leg:
- a paired ``<name>--prefill`` deployment reroutes the prefill leg and the
  handoff envelope rewrites the decode-pool body;
- any prefill-leg failure falls back to the decode pool recomputing —
  never a client-visible error.
"""

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import pytest

from ray_tpu.exceptions import ActorDiedError, ReplicaDrainingError
from ray_tpu.serve._private.asgi import ProxyASGIApp
from ray_tpu.serve._private.router import Router


def _bare_router(table):
    r = Router(None)
    r._table = table
    return r


def _replicas(names, max_q=8):
    return [{"actor_name": n, "max_concurrent_queries": max_q} for n in names]


# ---------------------------------------------------------------------------
# router: draining exclusion in every policy
# ---------------------------------------------------------------------------


def test_draining_excluded_from_round_robin_and_model_affinity():
    router = _bare_router(
        {"dep": {"route_prefix": "/dep", "replicas": _replicas(["a", "b", "c"])}}
    )
    router.mark_draining("b")
    picks = set()
    for _ in range(9):
        rep = router.assign_replica("dep", timeout_s=1)
        picks.add(rep["actor_name"])
        router.release(rep, deployment="dep")
    assert picks == {"a", "c"}
    # model_id affinity never lands on the draining replica either, for any
    # model id (crc32 start point is arbitrary — sweep several).
    for mid in ("m0", "m1", "m2", "m3", "m4"):
        rep = router.assign_replica("dep", timeout_s=1, model_id=mid)
        assert rep["actor_name"] != "b"
        router.release(rep, deployment="dep")


def test_draining_excluded_from_prefix_pin_and_spill():
    router = _bare_router(
        {"dep": {"route_prefix": "/dep", "replicas": _replicas(["a", "b", "c"], max_q=2)}}
    )
    # Find a hint that pins to "b", then drain "b": the pin must move, and
    # with the pin target saturated the SPILL candidates must skip "b" too.
    import zlib

    hint = next(
        h
        for h in (f"hint{i}" for i in range(64))
        if zlib.crc32(h.encode()) % 3 == 1
    )
    assert router.assign_replica("dep", prefix_hint=hint)["actor_name"] == "b"
    router.release(router._table["dep"]["replicas"][1], deployment="dep")
    router.mark_draining("b")
    seen = set()
    held = []
    for _ in range(4):  # 2 slots each on a and c
        rep = router.assign_replica("dep", timeout_s=1, prefix_hint=hint)
        seen.add(rep["actor_name"])
        held.append(rep)
    assert seen == {"a", "c"}  # pin moved off b, spill filled a AND c
    for rep in held:
        router.release(rep, deployment="dep")


def test_draining_ttl_expires_and_replica_returns():
    router = _bare_router(
        {"dep": {"route_prefix": "/dep", "replicas": _replicas(["a", "b"])}}
    )
    router.mark_draining("a", ttl_s=0.2)
    assert router.is_draining("a")
    for _ in range(4):
        rep = router.assign_replica("dep", timeout_s=1)
        assert rep["actor_name"] == "b"
        router.release(rep, deployment="dep")
    time.sleep(0.25)
    assert not router.is_draining("a")
    picks = set()
    for _ in range(4):
        rep = router.assign_replica("dep", timeout_s=1)
        picks.add(rep["actor_name"])
        router.release(rep, deployment="dep")
    assert picks == {"a", "b"}  # back in rotation after the TTL


def test_all_draining_parks_until_one_recovers():
    """Every replica draining: assign parks (no busy-fail) and completes as
    soon as a drain verdict expires — the rolling-restart steady state."""
    router = _bare_router(
        {"dep": {"route_prefix": "/dep", "replicas": _replicas(["a", "b"])}}
    )
    router.mark_draining("a", ttl_s=0.3)
    router.mark_draining("b", ttl_s=10.0)
    got = {}

    def assign():
        got["r"] = router.assign_replica("dep", timeout_s=5)

    t = threading.Thread(target=assign)
    t.start()
    t.join(timeout=5)
    assert got["r"]["actor_name"] == "a"


# ---------------------------------------------------------------------------
# proxy: fake-actor harness (no cluster)
# ---------------------------------------------------------------------------


class _FakeActor:
    """Stands in for a replica handle: ``handle_http_request.remote`` runs
    the behavior synchronously and the monkeypatched ``ray_tpu.get`` below
    passes its return value straight through."""

    def __init__(self, fn):
        self.handle_http_request = SimpleNamespace(remote=fn)

    def cancel_stream(self, *a, **k):  # pragma: no cover - teardown path
        return SimpleNamespace(remote=lambda *a2, **k2: None)


def _drive(app, path, body):
    """Run one POST through the proxy ASGI app; returns (status, body bytes)."""

    async def go():
        sent = {"status": None, "chunks": []}
        delivered = [False]

        async def receive():
            if not delivered[0]:
                delivered[0] = True
                return {"type": "http.request", "body": body, "more_body": False}
            return {"type": "http.disconnect"}

        async def send(ev):
            if ev["type"] == "http.response.start":
                sent["status"] = ev["status"]
            elif ev["type"] == "http.response.body":
                sent["chunks"].append(ev.get("body", b""))

        scope = {
            "type": "http",
            "method": "POST",
            "path": path,
            "query_string": b"",
            "headers": [],
        }
        await app(scope, receive, send)
        return sent["status"], b"".join(sent["chunks"])

    return asyncio.run(go())


@pytest.fixture
def proxy_env(monkeypatch):
    """(router, actors, pool) with ray_tpu.get pass-through and handle_for
    resolving into the ``actors`` dict."""
    import ray_tpu

    monkeypatch.setattr(ray_tpu, "get", lambda ref, timeout=None: ref)
    actors: dict = {}
    router = _bare_router({})
    router.handle_for = lambda replica: actors[replica["actor_name"]]
    router.invalidate_handle = lambda replica: None
    pool = ThreadPoolExecutor(max_workers=2)
    yield router, actors, pool
    pool.shutdown(wait=False)


def test_drain_refusal_never_burns_the_reassign_retry(proxy_env):
    """The request hits a draining replica (refusal), THEN a corpse, and
    still lands on the healthy survivor. The old accounting burned the
    single bounded retry on the drain refusal and 500'd the client on the
    corpse; drain refusals must not count. (Round-robin walks the filtered
    list, so the visit order after excluding r0 is r2 then r1.)"""
    router, actors, pool = proxy_env
    router._table = {
        "dep": {"route_prefix": "/dep", "replicas": _replicas(["r0", "r1", "r2"])}
    }
    router._rr["dep"] = 0
    calls = []

    def refuse(*a):
        calls.append("r0")
        raise ReplicaDrainingError(replica_id="r0")

    def die(*a):
        calls.append("r2")
        raise ActorDiedError("r2 died")

    def ok(*a):
        calls.append("r1")
        return {"pong": True}

    actors.update(
        {"r0": _FakeActor(refuse), "r1": _FakeActor(ok), "r2": _FakeActor(die)}
    )
    status, out = _drive(ProxyASGIApp(router, pool), "/dep", b"{}")
    assert status == 200 and json.loads(out) == {"pong": True}
    assert calls == ["r0", "r2", "r1"]
    # The refusal also poisoned r0 for future assignments on this router.
    assert router.is_draining("r0") and not router.is_draining("r1")
    # No leaked queue slots on any arm.
    assert all(v == 0 for v in router._inflight.values()), router._inflight


def test_prefill_handoff_rewrites_decode_body(proxy_env):
    """A paired --prefill deployment gets the prefill leg; the decode pool
    receives the envelope body + resume_tokens + kv_import + echo_resume."""
    router, actors, pool = proxy_env
    router._table = {
        "llm": {"route_prefix": "/llm", "replicas": _replicas(["dec0"])},
        "llm--prefill": {"route_prefix": None, "replicas": _replicas(["pre0"])},
    }
    desc = {"oid": "ab" * 14, "addr": ["n", 1], "nbytes": 128, "kv_pos": 4,
            "blocks": 1, "block_size": 4}
    orig = {"tokens": [1, 2, 3, 4], "max_new_tokens": 3, "stream": False,
            "seed": 7}
    seen = {}

    def prefill(method, path, query, body, *rest):
        seen["prefill_body"] = json.loads(body)
        return {
            "__llm_handoff__": {
                "kv_import": desc,
                "resume_tokens": [42],
                "body": dict(orig),
            }
        }

    def decode(method, path, query, body, *rest):
        seen["decode_body"] = json.loads(body)
        return {"tokens": [42, 5, 6]}

    actors.update({"pre0": _FakeActor(prefill), "dec0": _FakeActor(decode)})
    status, out = _drive(ProxyASGIApp(router, pool), "/llm",
                         json.dumps(orig).encode())
    assert status == 200 and json.loads(out) == {"tokens": [42, 5, 6]}
    assert seen["prefill_body"] == orig  # prefill saw the original request
    assert seen["decode_body"] == dict(
        orig, resume_tokens=[42], kv_import=desc, echo_resume=True
    )
    assert all(v == 0 for v in router._inflight.values()), router._inflight


def test_prefill_pool_failure_falls_back_to_decode_recompute(proxy_env):
    """Prefill replica dead + its retry refused by a draining sibling: the
    decode pool gets the ORIGINAL body (recompute), client sees no error."""
    router, actors, pool = proxy_env
    router._table = {
        "llm": {"route_prefix": "/llm", "replicas": _replicas(["dec0"])},
        "llm--prefill": {"route_prefix": None, "replicas": _replicas(["pre0", "pre1"])},
    }
    router._rr["llm--prefill"] = 0
    orig = {"tokens": [9, 8, 7], "stream": False}
    seen = {}

    def pre_die(*a):
        raise ActorDiedError("pre0 died")

    def pre_drain(*a):
        raise ReplicaDrainingError(replica_id="pre1")

    def decode(method, path, query, body, *rest):
        seen["decode_body"] = json.loads(body)
        return {"tokens": [1]}

    actors.update({
        "pre0": _FakeActor(pre_die),
        "pre1": _FakeActor(pre_drain),
        "dec0": _FakeActor(decode),
    })
    status, out = _drive(ProxyASGIApp(router, pool), "/llm",
                         json.dumps(orig).encode())
    assert status == 200 and json.loads(out) == {"tokens": [1]}
    assert seen["decode_body"] == orig  # untouched original body
    assert all(v == 0 for v in router._inflight.values()), router._inflight


def test_non_llm_posts_skip_the_prefill_leg(proxy_env):
    """A paired prefill pool must not tax unrelated POSTs on the decode
    route: no 'tokens' key (or an existing resume) goes straight through."""
    router, actors, pool = proxy_env
    router._table = {
        "llm": {"route_prefix": "/llm", "replicas": _replicas(["dec0"])},
        "llm--prefill": {"route_prefix": None, "replicas": _replicas(["pre0"])},
    }
    prefill_calls = []

    def prefill(*a):  # pragma: no cover - must never run
        prefill_calls.append(1)
        return {}

    bodies = []

    def decode(method, path, query, body, *rest):
        bodies.append(json.loads(body))
        return {"ok": True}

    actors.update({"pre0": _FakeActor(prefill), "dec0": _FakeActor(decode)})
    app = ProxyASGIApp(router, pool)
    for body in ({"not_llm": 1},
                 {"tokens": [1], "resume_tokens": [2], "stream": False}):
        status, out = _drive(app, "/llm", json.dumps(body).encode())
        assert status == 200 and json.loads(out) == {"ok": True}
    assert prefill_calls == []
    assert bodies == [{"not_llm": 1},
                      {"tokens": [1], "resume_tokens": [2], "stream": False}]
