"""Tests for autoscaler v2: instance lifecycle state machine, CAS storage,
batching node provider, and the v2 reconcile loop.

Reference: python/ray/autoscaler/v2/tests/ (instance manager + reconciler
tests) and autoscaler/batching_node_provider.py semantics — one ScaleRequest
per update, membership read once per tick.
"""

import pytest

from ray_tpu.autoscaler.v2 import (
    AutoscalerV2,
    BatchingNodeProvider,
    Instance,
    InstanceManager,
    InstanceStatus,
    InstanceStorage,
    NodeData,
)


# ---------------------------------------------------------------------------
# storage + state machine
# ---------------------------------------------------------------------------

def test_instance_storage_cas():
    st = InstanceStorage()
    insts, v0 = st.get_instances()
    assert insts == {} and v0 == 0
    a = Instance.new("cpu")
    assert st.batch_upsert([a], v0)
    # Stale writer loses.
    assert not st.batch_upsert([Instance.new("cpu")], v0)
    insts, v1 = st.get_instances()
    assert v1 == 1 and list(insts) == [a.instance_id]


def test_lifecycle_transitions_validated():
    im = InstanceManager()
    (inst,) = im.add_instances(["cpu"])
    assert inst.status == InstanceStatus.QUEUED
    im.set_status(inst.instance_id, InstanceStatus.REQUESTED)
    with pytest.raises(ValueError, match="illegal transition"):
        im.set_status(inst.instance_id, InstanceStatus.RAY_RUNNING)
    im.set_status(inst.instance_id, InstanceStatus.ALLOCATED, cloud_instance_id="c1")
    im.set_status(inst.instance_id, InstanceStatus.RAY_RUNNING, ray_node_id="n1")
    got = im.instances(InstanceStatus.RAY_RUNNING)[0]
    assert got.cloud_instance_id == "c1" and got.ray_node_id == "n1"


def test_reconcile_adopts_and_detects_failures():
    im = InstanceManager()
    (inst,) = im.add_instances(["cpu"])
    im.set_status(inst.instance_id, InstanceStatus.REQUESTED)
    # Provider satisfied the request.
    im.reconcile({"cloud-1": "cpu"}, {})
    assert im.instances(InstanceStatus.ALLOCATED)[0].cloud_instance_id == "cloud-1"
    # Raylet registered.
    im.reconcile({"cloud-1": "cpu"}, {"cloud-1": "ray-node-1"})
    assert im.instances(InstanceStatus.RAY_RUNNING)[0].ray_node_id == "ray-node-1"
    # Raylet vanished while the cloud instance persists.
    im.reconcile({"cloud-1": "cpu"}, {})
    assert im.instances(InstanceStatus.RAY_FAILED)
    # Cloud instance gone entirely -> terminal.
    im.set_status(
        im.instances(InstanceStatus.RAY_FAILED)[0].instance_id,
        InstanceStatus.TERMINATING,
    )
    im.reconcile({}, {})
    assert im.instances(InstanceStatus.TERMINATED)


def test_request_timeout_retries_then_fails():
    im = InstanceManager(request_timeout_s=0.0, max_launch_attempts=2)
    (inst,) = im.add_instances(["cpu"])
    im.set_status(inst.instance_id, InstanceStatus.REQUESTED)
    im.reconcile({}, {})  # nothing allocated, timeout hit -> back to QUEUED
    retried = im.instances(InstanceStatus.QUEUED)[0]
    assert retried.launch_attempts == 1
    im.set_status(retried.instance_id, InstanceStatus.REQUESTED)
    im.reconcile({}, {})  # attempts exhausted
    assert im.instances(InstanceStatus.ALLOCATION_FAILED)


# ---------------------------------------------------------------------------
# batching provider + v2 loop
# ---------------------------------------------------------------------------

class FakeBatchingBackend(BatchingNodeProvider):
    """In-memory declarative backend: scale requests apply instantly at the
    NEXT membership read (like a k8s operator reconciling replicas)."""

    def __init__(self):
        super().__init__({}, "test")
        self.cluster = {"head-0": NodeData("head", "head")}
        self.submitted = []
        self._counter = 0
        self.allocate = True  # flip off to simulate a stuck provider

    def get_node_data(self):
        return dict(self.cluster)

    def submit_scale_request(self, req):
        self.submitted.append(
            (dict(req.desired_num_workers), set(req.workers_to_delete))
        )
        if not self.allocate:
            return
        for nid in req.workers_to_delete:
            self.cluster.pop(nid, None)
        for ntype, want in req.desired_num_workers.items():
            have = [n for n, d in self.cluster.items() if d.type == ntype and d.kind == "worker"]
            for _ in range(want - len(have)):
                self._counter += 1
                self.cluster[f"{ntype}-{self._counter}"] = NodeData("worker", ntype)


CONFIG = {
    "max_workers": 4,
    "idle_timeout_s": 9999,
    "node_types": {
        "cpu_worker": {"resources": {"CPU": 2}, "max_workers": 4},
    },
}


def _mk(state, provider=None, **cfg_overrides):
    provider = provider or FakeBatchingBackend()
    cfg = {**CONFIG, **cfg_overrides}
    auto = AutoscalerV2(cfg, provider, state_reader=lambda: state())
    return auto, provider


def test_v2_batches_scale_up_into_one_request():
    # Two pending CPU:2 tasks, no workers -> ONE scale request for 2 nodes.
    state = lambda: (
        [{
            "node_id": "head-ray", "state": "ALIVE", "total": {"CPU": 0},
            "available": {}, "labels": {"provider_node_id": "head-0"},
            "load": [{"resources": {"CPU": 2}, "count": 2}],
        }],
        [],
    )
    auto, provider = _mk(state)
    auto.update()
    assert len(provider.submitted) == 1, "creates must batch into one ScaleRequest"
    desired, deleted = provider.submitted[0]
    assert desired == {"cpu_worker": 2} and not deleted
    assert len(auto.im.instances(InstanceStatus.REQUESTED)) == 2
    # Next tick: backend satisfied the request; raylets registered too.
    ray_nodes = [
        {
            "node_id": f"ray-{n}", "state": "ALIVE", "total": {"CPU": 2},
            "available": {"CPU": 2}, "labels": {"provider_node_id": n}, "load": [],
        }
        for n, d in provider.cluster.items()
        if d.kind == "worker"
    ]
    state2 = lambda: (ray_nodes, [])
    auto._state_reader = state2
    auto.update()
    assert len(auto.im.instances(InstanceStatus.RAY_RUNNING)) == 2
    # Demand satisfied: no further scale requests.
    assert len(provider.submitted) == 1


def test_v2_idle_scale_down_batches_deletes():
    state_empty_load = lambda: (
        [
            {
                "node_id": "ray-1", "state": "ALIVE", "total": {"CPU": 2},
                "available": {"CPU": 2}, "labels": {"provider_node_id": "cpu_worker-1"},
                "load": [],
            },
        ],
        [],
    )
    provider = FakeBatchingBackend()
    provider.cluster["cpu_worker-1"] = NodeData("worker", "cpu_worker")
    auto, provider = _mk(state_empty_load, provider=provider, idle_timeout_s=0.0)
    # Adopt the running node first; with idle_timeout 0 the same tick then
    # terminates it (adopt -> RAY_RUNNING -> idle -> TERMINATING).
    (inst,) = auto.im.add_instances(["cpu_worker"])
    auto.im.set_status(inst.instance_id, InstanceStatus.REQUESTED)
    auto.update()
    assert auto.im.instances(InstanceStatus.TERMINATING)
    desired, deleted = provider.submitted[-1]
    assert "cpu_worker-1" in deleted and desired.get("cpu_worker", 0) == 0
    # Backend applied the delete; next tick observes it gone.
    auto.update()
    assert auto.im.instances(InstanceStatus.TERMINATED)


def test_v2_stuck_provider_requeues_then_gives_up():
    state = lambda: (
        [{
            "node_id": "head-ray", "state": "ALIVE", "total": {"CPU": 0},
            "available": {}, "labels": {"provider_node_id": "head-0"},
            "load": [{"resources": {"CPU": 2}, "count": 1}],
        }],
        [],
    )
    provider = FakeBatchingBackend()
    provider.allocate = False
    auto, provider = _mk(
        state, provider=provider, request_timeout_s=0.0, max_launch_attempts=2
    )
    auto.update()  # queue + request (attempt 1)
    auto.update()  # timeout -> requeue -> request (attempt 2)
    auto.update()  # timeout -> attempts exhausted
    assert auto.im.instances(InstanceStatus.ALLOCATION_FAILED)
