"""Flight recorder: ring mechanics, crash survival, cluster merge, postmortem.

Covers ISSUE 8's observability plane: ring wrap, dump-on-signal, merge
ordering by stamp, the worker/raylet ``debug_dump`` RPCs, the dashboard
endpoint, and the acceptance scenario — a SIGKILLed worker's final ring
events surfacing in the merged cluster dump."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from ray_tpu._private import flight_recorder as fr

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_wrap_and_parse(tmp_path):
    path = str(tmp_path / "flight" / "flight-1-test.bin")
    rec = fr.FlightRecorder(path, slots=8, role="test", ident="abc")
    for i in range(20):
        rec.record(fr._CODE["mark"], f"ev{i}")
    events = rec.dump()
    # Ring holds the NEWEST 8 of 20; seq keeps the absolute position.
    assert len(events) == 8
    assert [e["detail"] for e in events] == [f"ev{i}" for i in range(12, 20)]
    assert [e["seq"] for e in events] == list(range(12, 20))
    monos = [e["mono"] for e in events]
    assert monos == sorted(monos)
    # The backing file parses to the same events (what a post-SIGKILL
    # collector sees).
    parsed = fr.parse_file(path)
    assert parsed is not None
    assert parsed["role"] == "test" and parsed["ident"] == "abc"
    assert [e["detail"] for e in parsed["events"]] == [e["detail"] for e in events]
    rec.close()


def test_parse_rejects_bogus_files(tmp_path):
    bogus = tmp_path / "flight-2-x.bin"
    bogus.write_bytes(b"not a flight ring")
    assert fr.parse_file(str(bogus)) is None
    (tmp_path / "flight-3-y.bin").write_bytes(b"")
    assert fr.parse_file(str(tmp_path / "flight-3-y.bin")) is None
    # collect_dir skips unparseable rings instead of raising.
    assert fr.collect_dir(str(tmp_path.parent / "nonexistent")) == []


def test_merge_ordering_by_stamp(tmp_path):
    d = tmp_path / "flight"
    a = fr.FlightRecorder(str(d / "flight-10-a.bin"), slots=16, role="a", ident="")
    b = fr.FlightRecorder(str(d / "flight-11-b.bin"), slots=16, role="b", ident="")
    expected = []
    for i in range(6):
        rec = a if i % 2 == 0 else b
        rec.record(fr._CODE["mark"], f"i{i}")
        expected.append(f"i{i}")
        time.sleep(0.002)
    merged = fr.merge_events(
        [{**a.meta(), "events": a.dump()}, {**b.meta(), "events": b.dump()}]
    )
    # Same-host rings share the monotonic base: the interleaving survives
    # the merge exactly.
    assert [e["detail"] for e in merged] == expected
    assert {e["role"] for e in merged} == {"a", "b"}
    a.close()
    b.close()


def test_detail_truncation_and_unicode(tmp_path):
    rec = fr.FlightRecorder(str(tmp_path / "f.bin"), slots=4, role="t", ident="")
    rec.record(fr._CODE["mark"], "x" * 500)
    rec.record(fr._CODE["mark"], "ünïcode→")
    events = rec.dump()
    assert events[0]["detail"] == "x" * fr._DETAIL_MAX
    assert events[1]["detail"] == "ünïcode→"
    rec.close()


def test_dump_on_fatal_signal(tmp_path):
    """install_signal_dump stamps a final fatal_signal event before the
    process dies on SIGTERM; the mmap file shows it afterwards."""
    import uuid

    # Unique session name: flight_dir() keys the (tmpfs) ring dir by the
    # session BASENAME, and pytest recycles tmp_path basenames across runs.
    session = str(tmp_path / f"sess_{uuid.uuid4().hex[:10]}")
    script = f"""
import signal, os
from ray_tpu._private import flight_recorder as fr
fr._enabled = True
fr.attach({session!r}, role="victim", ident="v1")
fr.record("mark", "before-signal")
fr.install_signal_dump([signal.SIGTERM])
signal.raise_signal(signal.SIGTERM)
raise SystemExit("unreachable: SIGTERM should have killed us")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], cwd=REPO, capture_output=True, timeout=60
    )
    assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
    procs = fr.collect_dir(session)
    assert len(procs) == 1
    types = [e["type"] for e in procs[0]["events"]]
    assert types[-1] == "fatal_signal"
    assert "mark" in types
    assert procs[0]["events"][-1]["detail"] == "SIGTERM"


def test_cluster_postmortem_sigkill(ray_start_regular):
    """Acceptance: `debug dump` on a cluster with a SIGKILLed worker contains
    that worker's final ring events, and they merge into the Chrome trace."""
    import ray_tpu
    from ray_tpu._private.state import GlobalState

    @ray_tpu.remote
    def whoami():
        return os.getpid()

    victim = ray_tpu.get(whoami.remote())
    os.kill(victim, signal.SIGKILL)
    deadline = time.time() + 10
    events = []
    while time.time() < deadline:
        merged = GlobalState().flight_recorder_dump()
        events = [e for e in merged if e["pid"] == victim]
        if any(e["type"] == "task_exec" for e in events):
            break
        time.sleep(0.3)
    assert any(e["type"] == "task_exec" for e in events), events
    assert any("whoami" in e["detail"] for e in events if e["type"] == "task_exec")
    # Driver-side ring shows the ship; raylet ring eventually shows the death.
    assert any(e["type"] == "task_ship" and "whoami" in e["detail"] for e in merged)
    # Merged Chrome trace carries the flight events next to task rows.
    trace = GlobalState().chrome_tracing_dump(flight_events=merged)
    flight_rows = [t for t in trace if t.get("cat") == "flight"]
    assert any(t["name"] == "task_exec" for t in flight_rows)


def test_debug_dump_rpcs(ray_start_regular):
    """Worker/raylet debug_dump RPC surface: the driver's own core-worker
    server answers with its ring; the raylet answers node-wide."""
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu._private.rpc import RpcClient

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    cw = worker_context.get_core_worker()
    client = RpcClient(tuple(cw.address), label="test-debug")
    try:
        own = client.call("debug_dump", {})
    finally:
        client.close()
    assert len(own["processes"]) == 1
    assert any(e["type"] == "task_ship" for e in own["processes"][0]["events"])

    node = cw.raylet.call("debug_dump", {})
    assert len(node["processes"]) >= 2  # head process + >= 1 worker
    roles = {p["role"] for p in node["processes"]}
    assert any("raylet" in r for r in roles)
    assert any("worker" in r for r in roles)


def test_dashboard_flight_recorder_endpoint(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker_context
    from ray_tpu.dashboard import DashboardHead

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    cw = worker_context.get_core_worker()
    head = DashboardHead(cw.gcs.address, cw.session_dir)
    try:
        url = "http://%s:%d/api/v0/debug/flight_recorder" % head.address
        with urllib.request.urlopen(url, timeout=30) as resp:
            body = json.loads(resp.read())
        events = body["result"]
        assert any(e["type"] == "task_exec" for e in events)
    finally:
        head.stop()


def test_ring_disabled_via_env(tmp_path):
    old = fr._enabled
    try:
        fr.set_enabled(False)
        fr.record("mark", "dropped")  # must not raise, must not buffer
        assert fr.dump() is None or all(
            e["detail"] != "dropped" for e in fr.dump()["events"]
        )
    finally:
        fr._enabled = old
