"""Tests: serve deploy config schema, tune syncer, dask-graph scheduler,
ray stack CLI.

Reference analogs: serve/tests/test_schema.py + test_cli.py,
tune/tests/test_syncer.py, util/dask tests, `ray stack`.
"""

import json
import os
import sys
import textwrap
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


# ---------- serve schema + deploy ----------

def test_serve_schema_validation(tmp_path):
    from ray_tpu.serve.schema import ServeDeploySchema, load_config

    cfg = ServeDeploySchema(applications=[
        {"name": "a", "import_path": "mod:app"},
        {"name": "b", "import_path": "mod2:app",
         "deployments": [{"name": "D", "num_replicas": 2,
                          "autoscaling_config": {"min_replicas": 1, "max_replicas": 3}}]},
    ])
    assert cfg.applications[1].deployments[0].autoscaling_config.max_replicas == 3
    with pytest.raises(Exception):
        ServeDeploySchema(applications=[
            {"name": "x", "import_path": "m:app"},
            {"name": "x", "import_path": "m2:app"},
        ])
    with pytest.raises(Exception):
        ServeDeploySchema(applications=[{"name": "a", "import_path": "noseparator"}])
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"applications": [{"name": "a", "import_path": "m:app"}]}))
    assert load_config(str(p)).applications[0].name == "a"


def test_serve_deploy_from_config(ray_start_regular, tmp_path):
    from ray_tpu.serve.schema import apply_config, load_config

    app_mod = tmp_path / "my_serve_app.py"
    app_mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment(route_prefix="/echo")
        class Echo:
            def __call__(self, request):
                return {"echo": request.query_params.get("q", "")}

        app = Echo.bind()
    """))
    cfg_file = tmp_path / "deploy.json"
    cfg_file.write_text(json.dumps({
        "applications": [{
            "name": "echo_app",
            "import_path": "my_serve_app:app",
            "route_prefix": "/echo",
            "deployments": [{"name": "Echo", "num_replicas": 2}],
        }]
    }))
    sys.path.insert(0, str(tmp_path))
    try:
        from ray_tpu import serve

        routes = apply_config(load_config(str(cfg_file)))
        assert routes == {"echo_app": "/echo"}
        st = serve.status()
        assert st["Echo"]["num_replicas"] == 2
        import urllib.request

        host, port = serve.http_address()
        with urllib.request.urlopen(f"http://{host}:{port}/echo?q=hi", timeout=10) as r:
            assert json.loads(r.read())["echo"] == "hi"
        serve.shutdown()
    finally:
        sys.path.remove(str(tmp_path))


# ---------- tune syncer ----------

def test_syncer_local_roundtrip(tmp_path):
    from ray_tpu.tune.syncer import SyncConfig, SyncManager, get_syncer

    src = tmp_path / "exp"
    (src / "sub").mkdir(parents=True)
    (src / "state.json").write_text("{}")
    (src / "sub" / "ckpt.bin").write_bytes(b"\x00" * 64)
    mgr = SyncManager(SyncConfig(upload_dir=str(tmp_path / "remote"), sync_period_s=0),
                      str(src), "exp1")
    assert mgr.enabled and mgr.maybe_sync_up(force=True)
    assert (tmp_path / "remote" / "exp1" / "state.json").exists()
    assert (tmp_path / "remote" / "exp1" / "sub" / "ckpt.bin").read_bytes() == b"\x00" * 64
    # Cloud schemes are gated with guidance.
    with pytest.raises(ValueError, match="cloud"):
        get_syncer("s3://bucket/path")


def test_tuner_syncs_experiment_dir(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune.syncer import SyncConfig

    def trainable(config):
        tune.report({"score": config["x"]})

    results = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="sync_exp", storage_path=str(tmp_path / "local"),
            sync_config=SyncConfig(upload_dir=str(tmp_path / "up"), sync_period_s=0),
        ),
    ).fit()
    assert len(results) == 2
    assert (tmp_path / "up" / "sync_exp" / "experiment_state.json").exists()


# ---------- dask-on-ray_tpu ----------

def test_dask_graph_scheduler(ray_start_regular):
    from operator import add, mul

    from ray_tpu.util.dask import ray_tpu_dask_get

    dsk = {
        "a": 1,
        "b": (add, "a", 2),          # 3
        "c": (mul, "b", "b"),        # 9
        "d": (sum, ["a", "b", "c"]),  # 13 — list of keys
        "e": (add, (mul, "a", 10), "b"),  # nested inline task: 13
    }
    assert ray_tpu_dask_get(dsk, "c") == 9
    assert ray_tpu_dask_get(dsk, ["c", "d", "e"]) == [9, 13, 13]
    with pytest.raises(ValueError, match="cycle|missing"):
        ray_tpu_dask_get({"x": (add, "y", 1), "y": (add, "x", 1)}, "x")


def test_dask_scheduler_moves_arrays_through_store(ray_start_regular):
    from ray_tpu.util.dask import ray_tpu_dask_get

    def make(n):
        return np.ones(n)

    def total(x, y):
        return float(x.sum() + y.sum())

    dsk = {
        "x": (make, 200_000),
        "y": (make, 100_000),
        "t": (total, "x", "y"),
    }
    assert ray_tpu_dask_get(dsk, "t") == 300_000.0


# ---------- ray stack ----------

def test_ray_stack_cli(ray_start_regular, capsys):
    from ray_tpu.scripts.scripts import cmd_stack

    @ray_tpu.remote
    class Sleeper:
        def spin(self):
            time.sleep(5)
            return True

    s = Sleeper.remote()
    ref = s.spin.remote()
    time.sleep(1.5)  # worker is inside spin()
    cmd_stack(None)
    out = capsys.readouterr().out
    assert "signalled" in out
    assert ray_tpu.get(ref, timeout=30) is True


def test_apply_overrides_handles_containers_and_sharing():
    from ray_tpu import serve
    from ray_tpu.serve.schema import DeploymentSchema, _apply_overrides

    @serve.deployment
    class Inner:
        pass

    @serve.deployment
    class Outer:
        def __init__(self, models, cfg):
            pass

    shared = Inner.bind()
    app = Outer.bind([shared, shared], {"extra": Inner.bind()})
    overrides = {"Inner": DeploymentSchema(name="Inner", num_replicas=3)}
    used: set = set()
    rebuilt = _apply_overrides(app, overrides, used)
    assert used == {"Inner"}
    models, cfg = rebuilt.init_args
    # Container nesting: override reached the list and dict elements.
    assert models[0].deployment.config.num_replicas == 3
    assert cfg["extra"].deployment.config.num_replicas == 3
    # Shared bindings stay the SAME object after rebuild (diamond detection).
    assert models[0] is models[1]
    # No overrides -> object graph untouched.
    untouched = _apply_overrides(app, {}, set())
    assert untouched is app
