"""Tests for the wider algorithm family (A2C/APPO/SAC/DDPG/TD3/ES/CQL).

Mirrors the reference's per-algorithm test style (rllib/algorithms/*/tests):
a learning check for the on-policy actor-critics on CartPole, compile-and-
improve smoke tests for the off-policy/offline/black-box families (their full
learning runs live in the reference's nightly tier, not unit CI).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_a2c_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import A2CConfig

    cfg = (
        A2CConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=8)
        .training(lr=2e-3, train_batch_size=2000, entropy_coeff=0.005, grad_clip=1.0)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"A2C failed to improve on CartPole (best={best})"
    finally:
        algo.cleanup()


def test_appo_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import APPOConfig

    cfg = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=4)
        .training(lr=1e-3, train_batch_size=2048, entropy_coeff=0.01, num_sgd_iter=2, kl_coeff=0.0)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"APPO failed to learn CartPole (best={best})"
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_sac_pendulum_smoke(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import SACConfig

    cfg = (
        SACConfig()
        .environment("Pendulum-v1")
        .training(
            lr=3e-4, train_batch_size=64, learning_starts=200,
            rollout_steps_per_iter=300, model_hiddens=(32, 32),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(3):
            r = algo.step()
        assert np.isfinite(r["critic_loss"])
        assert np.isfinite(r["alpha"]) and r["alpha"] > 0
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert a.shape == (1,) and -2.0 <= float(a[0]) <= 2.0
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_sac_discrete_smoke(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import SACConfig

    cfg = (
        SACConfig()
        .environment("CartPole-v1")
        .training(
            lr=3e-4, train_batch_size=64, learning_starts=200,
            rollout_steps_per_iter=300, model_hiddens=(32, 32), target_entropy=0.3,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        r = algo.step()
        assert np.isfinite(r["critic_loss"])
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_td3_pendulum_smoke(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import TD3Config

    cfg = (
        TD3Config()
        .environment("Pendulum-v1")
        .training(
            lr=1e-3, train_batch_size=64, learning_starts=200,
            rollout_steps_per_iter=300, model_hiddens=(32, 32),
        )
        .debugging(seed=0)
    )
    assert cfg.twin_q and cfg.policy_delay == 2 and cfg.smooth_target_policy
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        for _ in range(2):
            r = algo.step()
        assert np.isfinite(r["critic_loss"])
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert -2.0 <= float(a[0]) <= 2.0
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_es_improves_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import ESConfig

    cfg = (
        ESConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2)
        .training(
            episodes_per_batch=16, stepsize=0.02, noise_stdev=0.05,
            episode_horizon=200, eval_episodes=3, model_hiddens=(16,),
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        rewards = []
        for _ in range(6):
            r = algo.step()
            if np.isfinite(r["episode_reward_mean"]):
                rewards.append(r["episode_reward_mean"])
        # Random CartPole is ~20; ES should clearly move the mean up.
        assert max(rewards) > 35, f"ES made no progress: {rewards}"
        assert algo.compute_single_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.cleanup()


def test_cql_offline_smoke(ray_cluster, tmp_path):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import gymnasium as gym

    from ray_tpu.rllib import CQLConfig
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.policy.sample_batch import (
        ACTIONS,
        DONES,
        NEXT_OBS,
        OBS,
        REWARDS,
        SampleBatch,
    )

    # Collect a small random-policy dataset on Pendulum.
    env = gym.make("Pendulum-v1")
    writer = JsonWriter(str(tmp_path / "cql_data"))
    rng = np.random.default_rng(0)
    obs, _ = env.reset(seed=0)
    rows = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)}
    for _ in range(400):
        a = rng.uniform(-1, 1, size=(1,)).astype(np.float32)
        nobs, r, term, trunc, _ = env.step(a * 2.0)
        rows[OBS].append(np.asarray(obs, np.float32))
        rows[ACTIONS].append(a)
        rows[REWARDS].append(np.float32(r))
        rows[DONES].append(np.float32(term or trunc))
        rows[NEXT_OBS].append(np.asarray(nobs, np.float32))
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
    writer.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    writer.close()
    env.close()

    cfg = (
        CQLConfig()
        .environment("Pendulum-v1")
        .offline_data(input_=str(tmp_path / "cql_data"))
        .training(train_batch_size=32, updates_per_iter=20, model_hiddens=(32, 32), cql_alpha=0.5)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    try:
        r = algo.step()
        assert np.isfinite(r["bellman_loss"])
        # The conservative term is a logsumexp gap — must be finite, usually +.
        assert np.isfinite(r["cql_term"])
        a = algo.compute_single_action(np.zeros(3, np.float32))
        assert a.shape == (1,)
        ckpt = algo.save_checkpoint()
        algo.load_checkpoint(ckpt)
    finally:
        algo.cleanup()


def test_pg_learns_cartpole(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PGConfig

    cfg = (
        PGConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, num_envs_per_worker=8)
        .training(lr=4e-3, train_batch_size=2000)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = 0.0
    try:
        for _ in range(40):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 100:
                break
        assert best >= 100, f"PG failed to improve on CartPole (best={best})"
    finally:
        algo.cleanup()


def test_dt_imitates_expert_cartpole(ray_cluster, tmp_path):
    """Decision Transformer: offline sequence modeling on scripted-expert
    CartPole data; conditioned on the dataset's best return it should act
    near-expert (random play scores ~22)."""
    import gymnasium as gym
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DTConfig
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.policy.sample_batch import (
        ACTIONS,
        DONES,
        NEXT_OBS,
        OBS,
        REWARDS,
        SampleBatch,
    )

    env = gym.make("CartPole-v1")
    writer = JsonWriter(str(tmp_path / "dt_data"))
    rows = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)}
    for ep in range(25):
        obs, _ = env.reset(seed=ep)
        for _ in range(200):
            a = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0  # PD controller, ~200 reward
            nobs, r, term, trunc, _ = env.step(a)
            rows[OBS].append(np.asarray(obs, np.float32))
            rows[ACTIONS].append(np.int64(a))
            rows[REWARDS].append(np.float32(r))
            rows[DONES].append(np.float32(term or trunc))
            obs = nobs
            rows[NEXT_OBS].append(np.asarray(obs, np.float32))
            if term or trunc:
                break
        rows[DONES][-1] = np.float32(1.0)  # close the final episode
    writer.write(SampleBatch({k: np.asarray(v) for k, v in rows.items()}))
    writer.close()
    env.close()

    cfg = (
        DTConfig()
        .environment("CartPole-v1")
        .training(
            lr=1e-3,
            train_batch_size=64,
            context_length=20,
            updates_per_iter=150,
            eval_episodes=3,
            max_ep_len=200,
        )
        .debugging(seed=0)
        .offline_data(str(tmp_path / "dt_data"))
    )
    algo = cfg.build()
    best = 0.0
    try:
        for _ in range(4):
            r = algo.step()
            best = max(best, r["episode_reward_mean"])
            if best >= 120:
                break
        assert best >= 120, f"DT failed to imitate the expert (best={best})"
    finally:
        algo.cleanup()
