"""Continuous-batching LLM serving (ISSUE 11): engine scheduler, prefix
cache, preemption, stream hygiene, cache-aware routing, end-to-end SSE.

Layout (mindful of the tier-1 budget): engine/replica/router tests run with
NO cluster (one shared tiny model, compiled programs shared through the
engine's process-level jit cache); the end-to-end HTTP tests share ONE
module-scoped cluster; the concurrency sweep is marked `slow`.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

MODEL = dict(
    vocab_size=128,
    d_model=48,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=64,
    max_seq_len=64,
    dtype="float32",
    remat=False,
)


def _cfg():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig

    kw = dict(MODEL)
    kw["dtype"] = jnp.dtype(kw["dtype"]).type
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    import jax

    from ray_tpu.models.transformer import init_params

    cfg = _cfg()
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def _dense(params, cfg, prompt, n):
    import jax.numpy as jnp

    from ray_tpu.models.generate import generate

    return np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                 max_new_tokens=n, temperature=0.0)
    )[0].tolist()


def _rand_prompt(seed, n, vocab=128):
    return np.random.default_rng(seed).integers(0, vocab, n).tolist()


# ---------------------------------------------------------------------------
# engine (no cluster)
# ---------------------------------------------------------------------------


def test_continuous_schedule_matches_dense_generate(model):
    """THE acceptance oracle: greedy tokens across a multi-sequence schedule
    with MID-STREAM admissions are exactly the dense-cache generate()
    output per request — paged attention + slot scheduling are invisible."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=3, block_size=4,
                    max_model_len=32, prefill_chunk=4)
    try:
        prompts = [_rand_prompt(i + 1, 7) for i in range(5)]
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts[:3]]
        # Wait until decode is underway, then admit two more mid-stream.
        # (result() continues from the already-consumed first token.)
        firsts = [next(iter(r)) for r in reqs]
        reqs2 = [eng.submit(p, max_new_tokens=6) for p in prompts[3:]]
        outs = [[f] + r.result(timeout=120) for f, r in zip(firsts, reqs)]
        outs += [r.result(timeout=120) for r in reqs2]
        for p, o in zip(prompts, outs):
            assert o == _dense(params, cfg, p, 6)
        assert eng.stats()["admitted"] == 5
    finally:
        eng.shutdown()


def test_prefix_cache_reuse_refcounts_and_hint(model):
    """Admissions sharing a system prompt reuse its KV blocks (hit counters,
    fewer allocations), tokens still match the oracle, and refs return to 0
    so the blocks stay cached for the NEXT admission."""
    from ray_tpu.serve.llm import LLMEngine, prefix_route_hint

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=32, prefill_chunk=4)
    try:
        system = [5, 9, 3, 7, 1, 2, 8, 4]  # two full blocks
        p1, p2 = system + [11, 13], system + [17]
        assert prefix_route_hint(p1, 4) == prefix_route_hint(p2, 4) != ""
        o1 = eng.submit(p1, max_new_tokens=4).result(60)
        o2 = eng.submit(p2, max_new_tokens=4).result(60)
        s = eng.stats()
        assert s["prefix_hit_blocks"] == 2, s
        assert o1 == _dense(params, cfg, p1, 4)
        assert o2 == _dense(params, cfg, p2, 4)
        # Shared blocks are cached with refs 0 — a third request hits again.
        assert all(e.refs == 0 for e in eng._prefix.values())
        eng.submit(system + [19], max_new_tokens=3).result(60)
        assert eng.stats()["prefix_hit_blocks"] == 4
    finally:
        eng.shutdown()


def test_preemption_recompute_matches_oracle(model):
    """An undersized pool forces preemption mid-decode; the preempted
    sequence re-admits with its emitted tokens teacher-forced — final
    tokens for BOTH sequences still match the dense oracle exactly."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=40, num_blocks=13, prefill_chunk=4)
    try:
        pa, pb = [3] * 6, [9] * 6
        ra = eng.submit(pa, max_new_tokens=20)
        rb = eng.submit(pb, max_new_tokens=20)
        oa, ob = ra.result(120), rb.result(120)
        s = eng.stats()
        assert s["preemptions"] >= 1, s
        assert oa == _dense(params, cfg, pa, 20)
        assert ob == _dense(params, cfg, pb, 20)
        # No leak: every pool block is free or parked in the prefix cache.
        assert s["free_blocks"] + s["cached_blocks"] == s["num_blocks"]
    finally:
        eng.shutdown()


def test_prefix_eviction_under_pressure(model):
    """refs-0 cached prefix blocks are evicted LRU when the free list runs
    dry, instead of blocking admission forever."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    # 5 usable blocks; each 9-token request needs 3 — by the third
    # admission the free list is dry and refs-0 cached prefixes must go.
    eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                    max_model_len=24, num_blocks=6, prefill_chunk=4)
    try:
        eng.submit(_rand_prompt(7, 9), max_new_tokens=4).result(60)
        assert eng.stats()["cached_blocks"] == 2
        eng.submit(_rand_prompt(8, 9), max_new_tokens=4).result(60)
        eng.submit(_rand_prompt(9, 9), max_new_tokens=4).result(60)
        s = eng.stats()
        assert s["evicted_blocks"] >= 1, s
        assert s["free_blocks"] + s["cached_blocks"] == s["num_blocks"]
    finally:
        eng.shutdown()


def test_admission_does_not_double_count_cached_hits_as_evictable(model):
    """Regression: with the free list EMPTY and the only refs-0 cached
    blocks being the request's own prefix hits, admission must wait — not
    count those blocks as evictable supply, take refs on them, and then die
    on an empty alloc loop (which killed the scheduler thread engine-wide).

    The race state (every non-hit block held by running sequences) is built
    by hand with the scheduler thread STOPPED, and _admit() driven directly
    — the only deterministic way to pin this admission-time invariant."""
    from ray_tpu.serve.llm import LLMEngine, block_hashes
    from ray_tpu.serve.llm.engine import _PrefixEntry

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=24, num_blocks=7, prefill_chunk=4)
    eng.shutdown()  # idle: the loop's exit sweep has nothing to finalize
    eng._crashed = None  # white-box: re-open submits to drive _admit by hand
    prompt = _rand_prompt(41, 9)  # 3 blocks: 2 hashable + 1 tail
    hashes = block_hashes(prompt, 4)[:2]
    b1, b2 = eng._free.pop(), eng._free.pop()
    eng._prefix = {
        hashes[0]: _PrefixEntry(b1, refs=0, stamp=0.0),
        hashes[1]: _PrefixEntry(b2, refs=0, stamp=1.0),
    }
    eng._bid_hash = {b1: hashes[0], b2: hashes[1]}
    spare = eng._free.pop()
    eng._free.clear()  # everything else "held by running sequences"
    req = eng.submit(prompt, max_new_tokens=3)
    # need = 3 - 2 hits = 1, free = 0, and the only refs-0 entries ARE the
    # hits: pre-fix this admitted and died on `assert bid is not None`.
    eng._admit()
    assert eng._slots == [None, None]
    assert len(eng._waiting) == 1
    assert all(e.refs == 0 for e in eng._prefix.values())  # hits untouched
    # A running sequence frees a block -> the same admission now proceeds.
    eng._free.append(spare)
    eng._admit()
    assert req._sched_state == "prefill"
    assert req._sched_table == [b1, b2, spare]
    assert [e.refs for e in eng._prefix.values()] == [1, 1]


def test_engine_cancel_frees_blocks_immediately(model):
    """cancel() mid-decode returns the request's blocks to the pool within
    one scheduler iteration and terminates its consumer iterator."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=64, prefill_chunk=4)
    try:
        req = eng.submit([2] * 5, max_new_tokens=50)
        it = iter(req)
        next(it)  # decode underway
        eng.cancel(req)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = eng.stats()
            if s["running"] == 0 and s["free_blocks"] + s["cached_blocks"] == s["num_blocks"]:
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"blocks not freed after cancel: {eng.stats()}")
        assert eng.stats()["cancelled"] == 1
        assert len(list(it)) < 50  # iterator terminated early
    finally:
        eng.shutdown()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_submit_after_scheduler_crash_raises(model):
    """A crashed scheduler fails new submits loudly instead of parking the
    consumer on a queue nobody will ever feed; the in-flight request is
    finished with the crash error (not hung)."""
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.serve.llm.stats import ENGINES

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                    max_model_len=32, prefill_chunk=4)

    def boom(*_a, **_k):
        raise RuntimeError("boom")

    eng._prefill_fn = boom
    req = eng.submit([1, 2, 3], max_new_tokens=2)
    with pytest.raises(RuntimeError, match="boom"):
        req.result(timeout=30)
    eng._thread.join(timeout=10)
    assert not eng._thread.is_alive()
    assert eng not in ENGINES  # gauges stop counting a dead engine
    with pytest.raises(RuntimeError, match="scheduler died"):
        eng.submit([4, 5, 6], max_new_tokens=2)
    with pytest.raises(RuntimeError):
        eng.check_health()


def test_engine_registry_tracks_live_schedulers(model):
    """stats.ENGINES holds exactly the engines whose scheduler loop is
    running — the flush-time gauge sums drop an engine at shutdown instead
    of exporting its final values forever."""
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.serve.llm.stats import ENGINES

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                    max_model_len=32, prefill_chunk=4)
    assert eng in ENGINES
    eng.shutdown()
    assert eng not in ENGINES
    # A submit racing (or following) shutdown fails loudly instead of
    # parking its consumer on a queue the drained scheduler never feeds.
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit([1, 2, 3], max_new_tokens=2)


def test_submit_rejects_request_larger_than_pool(model):
    """A request whose full extent exceeds the KV pool can never be
    admitted — submit() must say so instead of wedging the FIFO head."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                    max_model_len=40, num_blocks=4, prefill_chunk=4)
    try:
        with pytest.raises(ValueError, match="num_blocks"):
            eng.submit([1] * 10, max_new_tokens=10)  # 5 blocks > 3 usable
        # A fitting request still sails through afterwards.
        assert len(eng.submit([1] * 5, max_new_tokens=4).result(60)) == 4
    finally:
        eng.shutdown()


def test_preemption_victim_is_youngest_even_when_needy(model):
    """Youngest-victim policy holds when the block-needing sequence IS the
    youngest: it preempts itself (minimal recompute) — an older sequence
    carrying more progress is never sacrificed for it."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=40, prefill_chunk=4)
    eng.shutdown()  # idle: drive the scheduler by hand, deterministically
    eng._crashed = None  # white-box: re-open submits
    ra = eng.submit([3] * 6, max_new_tokens=20)
    rb = eng.submit([9] * 6, max_new_tokens=20)
    eng._admit()
    while any(r is not None and r._sched_state == "prefill" for r in eng._slots):
        eng._prefill_tick()
    assert ra._sched_state == rb._sched_state == "decode"
    # Pool dry, nothing evictable, and B — the YOUNGER sequence — is the
    # one whose next write position crosses a block boundary.
    eng._free.clear()
    eng._prefix.clear()
    eng._bid_hash.clear()
    rb._sched_pos = len(rb._sched_table) * 4
    eng._decode_tick()
    assert rb._sched_state == "waiting"  # B preempted itself...
    assert list(eng._waiting) == [rb]
    assert eng._slots[ra._sched_slot] is ra  # ...and A kept its slot
    assert ra._sched_state == "decode"
    assert eng.stats()["preemptions"] == 1


def test_buffered_timeout_frees_slot_and_blocks(model):
    """Regression: a stream=false request whose result() times out must be
    cancelled engine-side — not left generating into an unread queue while
    holding a decode slot and KV blocks."""
    from ray_tpu.serve.llm import LLMDeployment

    dep = LLMDeployment(MODEL, engine_config=dict(
        num_slots=2, block_size=4, max_model_len=64, prefill_chunk=4))
    eng = dep.engine
    try:
        with pytest.raises(TimeoutError):
            dep({"tokens": [2] * 5, "max_new_tokens": 50, "stream": False,
                 "timeout": 0.001})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = eng.stats()
            if (
                s["running"] == 0
                and s["waiting"] == 0
                and s["cancelled"] == 1
                and s["free_blocks"] + s["cached_blocks"] == s["num_blocks"]
            ):
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"timed-out buffered request not cancelled: {eng.stats()}")
    finally:
        eng.shutdown()


def test_sampling_seeded_reproducible(model):
    """Temperature sampling: same seed -> same tokens, different seed ->
    (overwhelmingly) different; all tokens in-vocab."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=32, prefill_chunk=4)
    try:
        p = _rand_prompt(3, 6)
        a = eng.submit(p, max_new_tokens=8, temperature=0.9, top_k=16, seed=7).result(60)
        b = eng.submit(p, max_new_tokens=8, temperature=0.9, top_k=16, seed=7).result(60)
        c = eng.submit(p, max_new_tokens=8, temperature=0.9, top_k=16, seed=8).result(60)
        assert a == b
        assert all(0 <= t < 128 for t in a)
        assert a != c
    finally:
        eng.shutdown()


@pytest.mark.parametrize(
    "sampling",
    [dict(temperature=0.0), dict(temperature=0.9, top_k=16, seed=7)],
    ids=["greedy", "sampled"],
)
def test_resume_tokens_bit_identical(model, sampling):
    """THE migration oracle (ISSUE 14), engine half: a request resumed on a
    SECOND engine with resume_tokens= (the tokens the dead replica already
    emitted) continues BIT-IDENTICALLY — teacher-forced through chunked
    prefill like recompute preemption, nothing re-emitted — in both the
    greedy and seeded-sampling arms (the counter-based per-request RNG
    stream makes position k's draw replica-independent). KV blocks of both
    engines return to baseline."""
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    prompt = _rand_prompt(31, 7)
    eng_a = LLMEngine(params, cfg, num_slots=2, block_size=4,
                      max_model_len=32, prefill_chunk=4)
    eng_b = LLMEngine(params, cfg, num_slots=2, block_size=4,
                      max_model_len=32, prefill_chunk=4)
    try:
        full = eng_a.submit(prompt, max_new_tokens=8, **sampling).result(60)
        assert len(full) == 8
        for cut in (1, 4, 7, 8):
            resumed = eng_b.submit(
                prompt, max_new_tokens=8, resume_tokens=full[:cut], **sampling
            ).result(60)
            # Only the continuation is emitted; full sequence identical.
            assert resumed == full[cut:], (cut, resumed, full)
        for eng in (eng_a, eng_b):
            s = eng.stats()
            assert s["free_blocks"] + s["cached_blocks"] == s["num_blocks"], s
    finally:
        eng_a.shutdown()
        eng_b.shutdown()


def test_drain_refuses_new_submits_finishes_running(model):
    """Engine half of drain-before-retire: drain() refuses NEW submits with
    the TYPED ReplicaDrainingError (the proxy/handle reassign on it; an
    untyped error here 500s a client caught in the replica-gate/engine-
    drain race) while already-accepted requests decode to completion and
    release their blocks."""
    from ray_tpu.exceptions import ReplicaDrainingError
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    eng = LLMEngine(params, cfg, num_slots=2, block_size=4,
                    max_model_len=32, prefill_chunk=4)
    try:
        prompt = _rand_prompt(5, 6)
        req = eng.submit(prompt, max_new_tokens=6)
        eng.drain()
        with pytest.raises(ReplicaDrainingError, match="draining"):
            eng.submit(prompt, max_new_tokens=2)
        assert req.result(60) == _dense(params, cfg, prompt, 6)
        s = eng.stats()
        assert s["draining"] is True
        assert s["running"] == 0 and s["waiting"] == 0
        assert s["free_blocks"] + s["cached_blocks"] == s["num_blocks"], s
    finally:
        eng.shutdown()


def test_flight_events_recorded(model, tmp_path):
    """llm_admit/llm_prefix_hit land in the flight ring (codes 34+)."""
    from ray_tpu._private import flight_recorder as fr
    from ray_tpu.serve.llm import LLMEngine

    params, cfg = model
    fr._reset_for_tests()
    fr.attach(str(tmp_path / "sess"), "test-llm")
    try:
        eng = LLMEngine(params, cfg, num_slots=1, block_size=4,
                        max_model_len=32, prefill_chunk=4)
        try:
            system = [1, 2, 3, 4, 5, 6, 7, 8]
            eng.submit(system + [9], max_new_tokens=2).result(60)
            eng.submit(system + [10], max_new_tokens=2).result(60)
        finally:
            eng.shutdown()
        events = [e["type"] for e in (fr.dump() or {"events": []})["events"]]
        assert "llm_admit" in events
        assert "llm_prefix_hit" in events
    finally:
        fr._reset_for_tests()


# ---------------------------------------------------------------------------
# replica stream hygiene (no cluster: Replica driven directly)
# ---------------------------------------------------------------------------


def _llm_replica(engine_config=None):
    import cloudpickle

    from ray_tpu.serve._private.replica import Replica
    from ray_tpu.serve.llm import LLMDeployment

    spec = cloudpickle.dumps(
        (
            LLMDeployment,
            (MODEL,),
            {
                "engine_config": dict(
                    num_slots=2, block_size=4, max_model_len=64,
                    prefill_chunk=4, **(engine_config or {})
                )
            },
        )
    )
    return Replica(spec)


def _start_stream(replica, body):
    env = replica.handle_http_request(
        "POST", "/llm", {}, json.dumps(body).encode(), {}
    )
    assert "__serve_stream__" in env, env
    assert env["content_type"] == "text/event-stream"
    return env["__serve_stream__"]


def test_cancel_stream_frees_decode_slot_and_blocks(model):
    """Satellite: a client disconnect (cancel_stream) mid-decode frees the
    request's decode slot and KV blocks IMMEDIATELY via on_disconnect — not
    via the 5-minute idle reaper, and not only at the pump's next yield."""
    replica = _llm_replica()
    eng = replica._callable.engine
    try:
        sid = _start_stream(
            replica, {"tokens": [2] * 5, "max_new_tokens": 400 // 8}
        )
        # First chunk proves decode is underway.
        out = replica.next_stream_chunk(sid)
        assert out["chunks"] and not out["done"]
        assert eng.stats()["running"] == 1
        assert replica.cancel_stream(sid) is True
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = eng.stats()
            if (
                s["running"] == 0
                and s["cancelled"] == 1
                and s["free_blocks"] + s["cached_blocks"] == s["num_blocks"]
            ):
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"slot/blocks not freed after cancel_stream: {eng.stats()}")
        assert replica.next_stream_chunk(sid) is None  # stream is gone
    finally:
        replica.prepare_for_shutdown()


def test_idle_reap_cancels_stale_streams(model):
    """First direct test of _reap_idle_streams_locked: a stream nobody
    pumped for >5 minutes is torn down on the next stream registration —
    pump cancelled, on_disconnect fired (engine blocks freed)."""
    replica = _llm_replica()
    eng = replica._callable.engine
    try:
        sid = _start_stream(replica, {"tokens": [3] * 5, "max_new_tokens": 50})
        assert replica.next_stream_chunk(sid)["chunks"]
        pump = replica._streams[sid]
        pump.last_pump -= 301.0  # idle past the reap threshold
        sid2 = _start_stream(replica, {"tokens": [4] * 5, "max_new_tokens": 3})
        assert sid not in replica._streams
        assert pump.cancelled.is_set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if eng.stats()["cancelled"] >= 1:
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"reap did not cancel the engine request: {eng.stats()}")
        # The fresh stream still works end to end.
        chunks, done = [], False
        deadline = time.monotonic() + 30
        while not done and time.monotonic() < deadline:
            out = replica.next_stream_chunk(sid2)
            chunks += out["chunks"]
            done = out["done"]
        assert done and any(b"[DONE]" in c for c in chunks)
    finally:
        replica.prepare_for_shutdown()


# ---------------------------------------------------------------------------
# router (no cluster: bare Router with a hand-fed table)
# ---------------------------------------------------------------------------


def _bare_router(n_replicas=1, max_q=1):
    from ray_tpu.serve._private.router import Router

    r = Router(None)
    r._table = {
        "dep": {
            "route_prefix": "/dep",
            "replicas": [
                {"actor_name": f"rep{i}", "max_concurrent_queries": max_q}
                for i in range(n_replicas)
            ],
        }
    }
    return r


def test_release_unblocks_waiting_assign_within_10ms():
    """Satellite: a saturated assign parks on the Condition and a release()
    hands it the slot in <10 ms (the old path busy-slept 10 ms per probe)."""
    router = _bare_router(n_replicas=1, max_q=1)
    waits = []
    for _ in range(3):  # min-of-3: immune to a stray scheduler hiccup
        held = router.assign_replica("dep", timeout_s=5)
        woke = {}

        def blocked_assign():
            r = router.assign_replica("dep", timeout_s=5)
            woke["t"] = time.perf_counter()
            woke["r"] = r

        t = threading.Thread(target=blocked_assign)
        t.start()
        time.sleep(0.2)  # let it park on the condition
        assert "t" not in woke
        t0 = time.perf_counter()
        router.release(held, deployment="dep")
        t.join(timeout=5)
        assert "t" in woke, "assign never woke after release"
        waits.append(woke["t"] - t0)
        router.release(woke["r"], deployment="dep")
    assert min(waits) < 0.010, f"release->assign handoff too slow: {waits}"


def test_assign_deadline_semantics_preserved():
    router = _bare_router(n_replicas=1, max_q=1)
    router.assign_replica("dep", timeout_s=5)
    t0 = time.perf_counter()
    with pytest.raises(TimeoutError):
        router.assign_replica("dep", timeout_s=0.3)
    dt = time.perf_counter() - t0
    assert 0.25 <= dt < 3.0


def test_prefix_hint_affinity_and_least_depth_fallback():
    """Same hint -> same replica (stable); saturated hint target spills to
    the least-loaded unsaturated replica."""
    router = _bare_router(n_replicas=3, max_q=2)
    hint = "a" * 40
    r1 = router.assign_replica("dep", prefix_hint=hint)
    r2 = router.assign_replica("dep", prefix_hint=hint)
    assert r1["actor_name"] == r2["actor_name"]  # both slots on the target
    # Target now saturated: the spill goes to the LEAST-loaded survivor.
    others = [f"rep{i}" for i in range(3) if f"rep{i}" != r1["actor_name"]]
    router._inflight[others[0]] = 1  # load one survivor
    r3 = router.assign_replica("dep", prefix_hint=hint)
    assert r3["actor_name"] == others[1]
    # model_id affinity unchanged: stable replica (fresh router — the one
    # above is deliberately saturated).
    router2 = _bare_router(n_replicas=3, max_q=2)
    m1 = router2.assign_replica("dep", model_id="m")
    m2 = router2.assign_replica("dep", model_id="m")
    assert m1["actor_name"] == m2["actor_name"]


# ---------------------------------------------------------------------------
# end to end over HTTP (ONE module-scoped cluster)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def llm_serve(model):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    serve.start()
    app = serve.deployment(LLMDeployment).bind(
        MODEL,
        engine_config=dict(
            num_slots=4, block_size=4, max_model_len=64, prefill_chunk=8
        ),
    )
    handle = serve.run(app, route_prefix="/llm")
    yield handle
    serve.shutdown()
    ray_tpu.shutdown()


def _sse_tokens(resp):
    toks, buf = [], b""
    while True:
        chunk = resp.read(256)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            if not event.startswith(b"data: "):
                continue
            payload = event[6:]
            if payload == b"[DONE]":
                return toks, True
            toks.append(json.loads(payload)["token"])
    return toks, False


def test_http_sse_stream_matches_oracle(model, llm_serve):
    """deploy -> curl-style SSE: streamed greedy tokens equal the dense
    generate() oracle (replica params are seed-deterministic)."""
    from ray_tpu import serve

    params, cfg = model
    prompt = _rand_prompt(21, 7)
    host, port = serve.http_address()
    req = urllib.request.Request(
        f"http://{host}:{port}/llm",
        data=json.dumps({"tokens": prompt, "max_new_tokens": 6}).encode(),
    )
    resp = urllib.request.urlopen(req, timeout=120)
    assert resp.headers.get("Content-Type", "").startswith("text/event-stream")
    toks, done = _sse_tokens(resp)
    assert done
    assert toks == _dense(params, cfg, prompt, 6)


def test_handle_prefix_hint_routes_to_warm_replica(model, llm_serve):
    """Cache-aware routing end to end: two buffered requests sharing a
    system prompt and carrying its prefix_route_hint land on the same
    replica — the second one hits the prefix cache."""
    import ray_tpu
    from ray_tpu.serve.llm import prefix_route_hint

    system = [5, 9, 3, 7, 1, 2, 8, 4]
    hint = prefix_route_hint(system, 4)
    h = llm_serve.options(prefix_hint=hint)
    out1 = ray_tpu.get(
        h.remote({"tokens": system + [11], "max_new_tokens": 3, "stream": False}),
        timeout=120,
    )
    out2 = ray_tpu.get(
        h.remote({"tokens": system + [13], "max_new_tokens": 3, "stream": False}),
        timeout=120,
    )
    assert len(out1["tokens"]) == 3 and len(out2["tokens"]) == 3
    stats = ray_tpu.get(h.get_stats.remote(), timeout=60)
    assert stats["prefix_hit_blocks"] >= 2, stats


@pytest.mark.slow
def test_concurrent_streams_sweep(model, llm_serve):
    """Full concurrency sweep (slow): 8 closed-loop SSE streams against one
    replica — every stream completes, every completion matches the oracle,
    and mid-decode admissions actually happened (admitted > slots)."""
    from ray_tpu import serve

    params, cfg = model
    host, port = serve.http_address()
    errs, done_counts = [], []

    def stream(i):
        try:
            rng = np.random.default_rng(100 + i)
            for j in range(3):
                prompt = rng.integers(0, 128, 6).tolist()
                n = int(rng.integers(2, 8))
                req = urllib.request.Request(
                    f"http://{host}:{port}/llm",
                    data=json.dumps({"tokens": prompt, "max_new_tokens": n}).encode(),
                )
                toks, done = _sse_tokens(urllib.request.urlopen(req, timeout=300))
                assert done and toks == _dense(params, cfg, prompt, n)
                done_counts.append(1)
        except Exception as e:  # noqa: BLE001
            errs.append(f"stream {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=stream, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not errs, errs
    assert sum(done_counts) == 24
