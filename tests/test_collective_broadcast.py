"""Group broadcast on the device-object collective plane (ISSUE 15).

- cpu_group payload parity: ``broadcast`` round-trips a SHARDED jax.Array
  bit-exact (sharding preserved), ``allgather`` stacks bit-exact, and
  non-uniform shapes are rejected with a typed CollectiveError naming the
  per-rank shapes.
- Typed timeouts: the two collective paths that used to raise raw
  TimeoutError (ring ``_collect``, p2p ``mailbox_recv``) now raise
  CollectiveTimeoutError naming group/ranks/tag (the chaos-matrix typed
  contract).
- Group-broadcast descriptor resolution on all three consumer paths:
  same-process (live array), same-group (direct-mailbox landing zone,
  zero pull round trips), and the host fallback (cut-through relay copy /
  devobj_pull for non-members).
- Chaos: a sampler SIGKILLed MID-BROADCAST (seeded kill plan firing while
  it answers the fan-out's p2p_ack) surfaces CollectiveBroadcastError
  NAMING the dead rank while surviving ranks complete and consume their
  payload; device-object residents return to baseline after teardown.

One module-scoped cluster for the ring/resolution tests (cluster spin-up
dominates tier-1 wall otherwise); the kill test builds its own 2-node
Cluster because it needs worker handles to push the seeded plan into.
"""

import gc
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import (
    CollectiveBroadcastError,
    CollectiveError,
    CollectiveTimeoutError,
    RayTpuError,
)


@pytest.fixture(scope="module")
def coll_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _sharded(n=64):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    x = jnp.arange(float(n), dtype=jnp.float32).reshape(8, n // 8)
    return jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))


@ray_tpu.remote
class Member:
    """One collective-group member: joins groups, runs SPMD ring ops, and
    consumes device-object refs (arg resolution exercises the broadcast
    landing zone / pull fallback)."""

    def pid(self):
        return os.getpid()

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def ring_broadcast_sharded(self, group_name, src_rank, is_src):
        """All ranks call broadcast; src contributes a sharded array.
        Returns (values, device_count_of_result_sharding)."""
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        x = _sharded() if is_src else jnp.zeros((8, 8), jnp.float32)
        out = col.broadcast(x, src_rank=src_rank, group_name=group_name)
        devices = len(getattr(getattr(out, "sharding", None), "device_set", [None]))
        return np.asarray(out), devices

    def ring_allgather(self, group_name, value):
        from ray_tpu.util import collective as col

        return np.asarray(col.allgather(np.asarray(value), group_name=group_name))

    def ring_allgather_shaped(self, group_name, shape):
        from ray_tpu.util import collective as col

        try:
            col.allgather(np.ones(shape, np.float32), group_name=group_name)
            return "no-error"
        except CollectiveError as e:
            return f"typed:{type(e).__name__}:{e}"

    def consume(self, w):
        return float(np.asarray(w).reshape(-1)[0]), int(np.asarray(w).size)

    def coll_stats(self):
        from ray_tpu.util.collective.p2p import COLL

        return {k: getattr(COLL, k) for k in COLL.__slots__}

    def bcast_recv(self, group_name, src_rank, tag, timeout=30.0):
        from ray_tpu.util import collective as col

        out = col.get_group(group_name).bcast_recv_payload(src_rank, tag, timeout=timeout)
        return np.asarray(out).sum().item()

    def bcast_send(self, group_name, tag, n):
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        return col.get_group(group_name).bcast_send_payload(
            jnp.ones((n,), jnp.float32), tag
        )

    def devobj_stats(self):
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()


@ray_tpu.remote(tensor_transport="collective")
class Holder:
    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)
        return rank

    def make(self, n=4096):
        import jax.numpy as jnp

        return jnp.arange(float(n), dtype=jnp.float32)

    def residents(self):
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()["resident_count"]


# ---------------------------------------------------------------------------
# cpu_group payload parity
# ---------------------------------------------------------------------------


def test_ring_broadcast_sharded_payload_parity(coll_cluster):
    """broadcast() hands every rank the src's jax.Array AS POSTED: values
    bit-exact AND the 4-device sharding layout survives the hop."""
    a, b = Member.remote(), Member.remote()
    ray_tpu.get([a.init_collective.remote(2, 0, "cpu", "parity2"),
                 b.init_collective.remote(2, 1, "cpu", "parity2")], timeout=60)
    ra = a.ring_broadcast_sharded.remote("parity2", 0, True)
    rb = b.ring_broadcast_sharded.remote("parity2", 0, False)
    (va, _), (vb, dev_b) = ray_tpu.get([ra, rb], timeout=60)
    expected = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    np.testing.assert_array_equal(va, expected)
    np.testing.assert_array_equal(vb, expected)  # bit-exact across the hop
    assert dev_b == 4  # sharding layout re-landed on the receiver's devices


def test_ring_allgather_parity_and_typed_shape_error(coll_cluster):
    a, b = Member.remote(), Member.remote()
    ray_tpu.get([a.init_collective.remote(2, 0, "cpu", "gather2"),
                 b.init_collective.remote(2, 1, "cpu", "gather2")], timeout=60)
    ra = a.ring_allgather.remote("gather2", np.full((3,), 1.5, np.float32))
    rb = b.ring_allgather.remote("gather2", np.full((3,), 2.5, np.float32))
    va, vb = ray_tpu.get([ra, rb], timeout=60)
    expected = np.stack([np.full((3,), 1.5), np.full((3,), 2.5)]).astype(np.float32)
    np.testing.assert_array_equal(va, expected)
    np.testing.assert_array_equal(vb, expected)
    # Non-uniform shapes: every rank gets the TYPED error naming shapes.
    ra = a.ring_allgather_shaped.remote("gather2", (3,))
    rb = b.ring_allgather_shaped.remote("gather2", (4,))
    outs = ray_tpu.get([ra, rb], timeout=60)
    for out in outs:
        assert out.startswith("typed:CollectiveError"), out
        assert "uniform shapes" in out, out


# ---------------------------------------------------------------------------
# typed timeouts (chaos-matrix contract: no raw TimeoutError)
# ---------------------------------------------------------------------------


def test_collect_timeout_typed_names_missing_ranks(coll_cluster):
    from ray_tpu.util import collective as col

    group = col.init_collective_group(2, 0, backend="cpu", group_name="lonely2")
    try:
        group._post("allreduce", np.ones((2,), np.float32))
        with pytest.raises(CollectiveTimeoutError) as ei:
            group._collect("allreduce", timeout=0.3)
        assert ei.value.group == "lonely2"
        assert ei.value.ranks == [1]  # the rank that never posted, named
        assert isinstance(ei.value, RayTpuError)
        assert not isinstance(ei.value, TimeoutError)  # typed, not a bare timeout
    finally:
        col.destroy_collective_group("lonely2")


def test_mailbox_recv_timeout_typed_names_group_rank_tag(coll_cluster):
    from ray_tpu.util import collective as col

    group = col.init_collective_group(2, 0, backend="cpu", group_name="lonely3")
    try:
        with pytest.raises(CollectiveTimeoutError) as ei:
            group.recv(src_rank=1, tag="w17", timeout=0.3)
        assert ei.value.group == "lonely3"
        assert ei.value.ranks == [1]
        assert ei.value.tag == "w17"
    finally:
        col.destroy_collective_group("lonely3")


def test_bcast_recv_blocked_before_send_catches_direct_delivery(coll_cluster):
    """A receiver already parked in bcast_recv_payload when the sender
    starts (normal blocking-collective ordering) must catch the DIRECT
    delivery whenever it lands — the recv watches both landing zones for
    the whole window, not the direct mailbox for just the first second."""
    a, b = Member.remote(), Member.remote()
    ray_tpu.get([a.init_collective.remote(2, 0, "cpu", "recv2"),
                 b.init_collective.remote(2, 1, "cpu", "recv2")], timeout=60)
    pending = b.bcast_recv.remote("recv2", 0, "t1", 30.0)
    time.sleep(2.0)  # receiver is parked well past the old 1s direct probe
    info = ray_tpu.get(a.bcast_send.remote("recv2", "t1", 2048), timeout=60)
    assert info["ok_ranks"] == [1], info
    assert ray_tpu.get(pending, timeout=60) == 2048.0


# ---------------------------------------------------------------------------
# group-broadcast descriptor resolution: all three consumer paths
# ---------------------------------------------------------------------------


def test_broadcast_resolution_same_process(coll_cluster):
    import jax.numpy as jnp

    arr = jnp.arange(1024.0, dtype=jnp.float32)
    ref = ray_tpu.put(arr, tensor_transport="collective")
    assert ray_tpu.get(ref) is arr  # the live array, zero payload copies
    del ref
    gc.collect()


def test_broadcast_resolution_same_group_rides_inbox(coll_cluster):
    from ray_tpu.experimental import device_object

    holder = Holder.remote()
    consumers = [Member.remote() for _ in range(2)]
    ray_tpu.get(
        [holder.init_collective.remote(3, 0, "cpu", "res3")]
        + [c.init_collective.remote(3, i + 1, "cpu", "res3") for i, c in enumerate(consumers)],
        timeout=60,
    )
    ref = holder.make.remote(4096)
    info = device_object.broadcast(ref, "res3", timeout=60)
    assert sorted(info["ok_ranks"]) == [1, 2], info
    assert info["failed"] == {}
    vals = ray_tpu.get([c.consume.remote(ref) for c in consumers], timeout=60)
    assert vals == [(0.0, 4096), (0.0, 4096)]
    for c in consumers:
        stats = ray_tpu.get(c.coll_stats.remote(), timeout=30)
        assert stats["bcast_recvs"] >= 1, stats  # resolved FROM the landing zone
    # A second resolve of the same ref (inbox consumed) falls back to the
    # pull path and still produces the value.
    again = ray_tpu.get(consumers[0].consume.remote(ref), timeout=60)
    assert again == (0.0, 4096)
    del ref, info
    gc.collect()


def test_broadcast_resolution_host_fallback(coll_cluster):
    """A consumer OUTSIDE the group resolves the same broadcast ref over the
    host path; and the no-group broadcast() seals an arena copy the whole
    cluster's store plane can serve."""
    from ray_tpu._private import worker_context
    from ray_tpu.experimental import device_object

    holder = Holder.remote()
    outsider = Member.remote()  # never joins any group
    ray_tpu.get(holder.init_collective.remote(1, 0, "cpu", "solo1"), timeout=60)
    ref = holder.make.remote(4096)
    val = ray_tpu.get(outsider.consume.remote(ref), timeout=60)
    assert val == (0.0, 4096)  # pull/host fallback
    # Host-path broadcast: holder materializes, relay tree replicates (one
    # node here, so pushed_nodes == 0 but the arena copy must exist).
    info = device_object.broadcast(ref, timeout=60)
    assert info["kind"] == "plasma"
    cw = worker_context.get_core_worker()
    oid = ref.hex()
    deadline = time.monotonic() + 10
    while not cw.store.contains(oid) and time.monotonic() < deadline:
        time.sleep(0.1)
    assert cw.store.contains(oid)
    # Local-arena fast path: the driver (not the holder) resolves from its
    # node's store without waking the holder.
    got = ray_tpu.get(ref, timeout=60)
    assert float(np.asarray(got)[1]) == 1.0
    del ref
    gc.collect()


# ---------------------------------------------------------------------------
# relay-tree broadcast (ISSUE 16): topology, sub-O(K) root egress
# ---------------------------------------------------------------------------


def test_tree_broadcast_topology_and_sub_o_k_root_egress(coll_cluster):
    """A 5-rank group broadcast rides the binomial relay tree: the root
    streams only to its tree children (ranks 1, 2, 4 — sub-O(K) egress),
    rank 1 relays the payload onward to rank 3 (its COLL relay counters
    prove the mid-tree forward), and every member still lands the exact
    payload with a direct per-rank ack."""
    import jax.numpy as jnp

    from ray_tpu.util import collective as col

    members = [Member.remote() for _ in range(4)]
    group = "tree5"
    col.init_collective_group(5, 0, backend="cpu", group_name=group)
    try:
        ray_tpu.get(
            [m.init_collective.remote(5, i + 1, "cpu", group) for i, m in enumerate(members)],
            timeout=60,
        )
        payload = jnp.arange(448 * 1024, dtype=jnp.float32)  # 1.75 MiB -> 4 chunks
        info = col.get_group(group).bcast_send_payload(payload, "t16", timeout=60)
        assert info["topology"] == "tree", info
        assert info["root_children"] == [1, 2, 4], info
        assert sorted(info["ok_ranks"]) == [1, 2, 3, 4], info
        assert info["failed"] == {} and info["retried_ranks"] == []
        # Sub-O(K): the root pushed the payload to its 3 tree children,
        # not all 4 members — rank 3's copy came from the rank-1 relay.
        assert info["root_egress_bytes"] == 3 * info["bytes"], info
        sums = ray_tpu.get(
            [m.bcast_recv.remote(group, 0, "t16", 30.0) for m in members], timeout=60
        )
        expected = float(np.asarray(payload).sum())
        assert sums == [expected] * 4
        stats1 = ray_tpu.get(members[0].coll_stats.remote(), timeout=30)
        assert stats1["relay_forwards"] >= 1, stats1
        assert stats1["relay_bytes"] >= info["bytes"], stats1
    finally:
        col.destroy_collective_group(group)


# ---------------------------------------------------------------------------
# chaos: sampler SIGKILLed mid-broadcast (seeded kill plan)
# ---------------------------------------------------------------------------


def test_sampler_sigkill_mid_broadcast_names_dead_rank():
    """A seeded kill plan makes one sampler SIGKILL itself while answering
    the fan-out's p2p_ack — mid-broadcast, at a reproducible protocol
    point. The broadcast surfaces CollectiveBroadcastError NAMING the dead
    rank, the surviving ranks complete AND consume their payload, and the
    driver's device-object residents drain back to baseline."""
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental import device_object
    from ray_tpu.util import collective as col

    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=2, object_store_memory=96 * 1024 * 1024)
            for _ in range(2)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        samplers = [Member.remote() for _ in range(3)]
        group = "chaosg"
        col.init_collective_group(4, 0, backend="cpu", group_name=group)
        ray_tpu.get(
            [s.init_collective.remote(4, i + 1, "cpu", group) for i, s in enumerate(samplers)],
            timeout=60,
        )
        pids = ray_tpu.get([s.pid.remote() for s in samplers], timeout=60)
        victim_pid = pids[1]  # rank 2 dies
        plan = {
            "rules": [
                {"kind": "kill", "method": ["p2p_ack"], "side": "resp",
                 "after": 0, "times": 1}
            ]
        }
        io = EventLoopThread.get()
        pushed = False
        for n in nodes:
            for w in n.workers.values():
                if w.pid == victim_pid and w.client is not None:
                    io.run(
                        w.client.acall(
                            "chaos_set_plan", {"plan": plan, "seed": 7},
                            timeout=5, retries=0,
                        ),
                        timeout=6,
                    )
                    pushed = True
        assert pushed, "victim worker not found for plan push"

        import jax.numpy as jnp

        ref = ray_tpu.put(
            jnp.arange(65536.0, dtype=jnp.float32), tensor_transport="collective"
        )
        with pytest.raises(CollectiveBroadcastError) as ei:
            device_object.broadcast(ref, group, timeout=30)
        err = ei.value
        assert list(err.failed) == [2], err.failed  # dead rank NAMED
        assert sorted(err.info.get("ok_ranks", [])) == [1, 3], err.info  # survivors completed
        assert isinstance(err, RayTpuError) and not isinstance(err, TimeoutError)
        # Survivors hold the payload: their resolve comes from the inbox.
        vals = ray_tpu.get(
            [samplers[0].consume.remote(ref), samplers[2].consume.remote(ref)],
            timeout=60,
        )
        assert vals == [(0.0, 65536), (0.0, 65536)]
        # Teardown: drop the ref; the driver-held device object frees. The
        # ExceptionInfo must go too — its traceback pins broadcast()'s
        # frame, whose locals include the ref.
        from ray_tpu.experimental.device_object.manager import active_manager

        del ref, err, ei
        gc.collect()
        deadline = time.monotonic() + 30
        mgr = active_manager()
        while mgr.usage()["resident_count"] > 0 and time.monotonic() < deadline:
            time.sleep(0.2)
        usage = mgr.usage()
        assert usage["resident_count"] == 0, usage
        assert usage["spilled_count"] == 0, usage
    finally:
        cluster.shutdown()


def test_mid_tree_relay_sigkill_reparents_orphans():
    """A seeded kill plan SIGKILLs a MID-TREE relay rank at its first
    forward attempt (outbound p2p_data), so its subtree never gets the
    payload from the tree. The broadcast NAMES the dead relay with its
    orphaned subtree, re-delivers the orphan DIRECTLY (flat fallback —
    rank 3 lands in ``retried_ranks`` and succeeds), every survivor
    completes AND consumes, and the driver's residents drain."""
    from ray_tpu._private.rpc import EventLoopThread
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental import device_object
    from ray_tpu.util import collective as col

    cluster = Cluster()
    try:
        nodes = [
            cluster.add_node(num_cpus=3, object_store_memory=96 * 1024 * 1024)
            for _ in range(2)
        ]
        cluster.connect()
        cluster.wait_for_nodes()
        samplers = [Member.remote() for _ in range(4)]
        group = "chaostree"
        col.init_collective_group(5, 0, backend="cpu", group_name=group)
        ray_tpu.get(
            [s.init_collective.remote(5, i + 1, "cpu", group) for i, s in enumerate(samplers)],
            timeout=60,
        )
        pids = ray_tpu.get([s.pid.remote() for s in samplers], timeout=60)
        # Rank 1 is a RELAY (tree order [0,1,2,3,4]: rank 1 forwards to
        # rank 3). Its first outbound p2p_data IS that forward — the kill
        # fires there, before its own multi-chunk payload completes, so it
        # never acks and its subtree starves.
        victim_pid = pids[0]
        plan = {
            "rules": [
                {"kind": "kill", "method": ["p2p_data"], "side": "send",
                 "after": 0, "times": 1}
            ]
        }
        io = EventLoopThread.get()
        pushed = False
        for n in nodes:
            for w in n.workers.values():
                if w.pid == victim_pid and w.client is not None:
                    io.run(
                        w.client.acall(
                            "chaos_set_plan", {"plan": plan, "seed": 16},
                            timeout=5, retries=0,
                        ),
                        timeout=6,
                    )
                    pushed = True
        assert pushed, "victim worker not found for plan push"

        import jax.numpy as jnp

        n_elems = 448 * 1024  # 1.75 MiB -> 4 chunks: dies mid-payload
        ref = ray_tpu.put(
            jnp.arange(float(n_elems), dtype=jnp.float32),
            tensor_transport="collective",
        )
        with pytest.raises(CollectiveBroadcastError) as ei:
            device_object.broadcast(ref, group, timeout=12)
        err = ei.value
        assert list(err.failed) == [1], err.failed  # dead RELAY named
        reason = err.failed[1]
        assert "orphaned subtree ranks [3]" in reason, reason
        assert "re-delivered directly: [3]" in reason, reason
        assert sorted(err.info.get("ok_ranks", [])) == [2, 3, 4], err.info
        assert 3 in err.info.get("retried_ranks", []), err.info
        assert isinstance(err, RayTpuError) and not isinstance(err, TimeoutError)
        # Survivors — INCLUDING the re-parented orphan rank 3 — consume.
        vals = ray_tpu.get(
            [s.consume.remote(ref) for s in samplers[1:]], timeout=60
        )
        assert vals == [(0.0, n_elems)] * 3
        from ray_tpu.experimental.device_object.manager import active_manager

        del ref, err, ei
        gc.collect()
        deadline = time.monotonic() + 30
        mgr = active_manager()
        while mgr.usage()["resident_count"] > 0 and time.monotonic() < deadline:
            time.sleep(0.2)
        usage = mgr.usage()
        assert usage["resident_count"] == 0, usage
    finally:
        cluster.shutdown()
