"""DAGDriver multi-route graph ingress + HTTP adapters.

Reference: python/ray/serve/drivers.py:31 (DAGDriver), http_adapters.py.
One driver deployment serves several independently-deployed graph
branches by sub-route; each branch keeps its own replica scaling.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import DAGDriver


@pytest.fixture(scope="module")
def serve_instance():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _http(path, payload=None):
    host, port = serve.http_address()
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=data, method="POST" if data else "GET"
    )
    return urllib.request.urlopen(req, timeout=30).read().decode()


def test_dagdriver_routes_two_branches(serve_instance):
    @serve.deployment(num_replicas=2)
    class Doubler:
        def __call__(self, x):
            return {"doubled": 2 * x}

    @serve.deployment(num_replicas=1)
    class Negator:
        def __call__(self, x):
            return {"negated": -x}

    handle = serve.run(
        DAGDriver.bind({"/double": Doubler.bind(), "/neg": Negator.bind()}),
        route_prefix="/",
    )
    # HTTP: the driver dispatches by sub-route; default adapter parses JSON.
    assert json.loads(_http("/double", 21)) == {"doubled": 42}
    assert json.loads(_http("/neg", 21)) == {"negated": -21}
    # Python-side route entry points.
    assert ray_tpu.get(handle.predict_with_route.remote("/double", 7)) == {"doubled": 14}
    assert sorted(ray_tpu.get(handle.get_routes.remote())) == ["/double", "/neg"]
    # The branches are separate deployments with their OWN replica targets.
    st = serve.status()
    assert st["Doubler"]["num_replicas"] == 2
    assert st["Negator"]["num_replicas"] == 1
    assert st["DAGDriver"]["num_replicas"] == 1


def test_dagdriver_single_dag_and_adapters(serve_instance):
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"got": x}

    serve.run(
        DAGDriver.options(name="TextDriver").bind(
            Echo.options(name="EchoText").bind(),
            http_adapter="ray_tpu.serve.http_adapters.text_request",
        ),
        route_prefix="/text",
    )
    out = json.loads(_http("/text", "hello"))
    # text_request hands the RAW body through (json.dumps quoted it).
    assert out == {"got": '"hello"'}


def test_dagdriver_unknown_route_errors(serve_instance):
    @serve.deployment
    class Once:
        def __call__(self, x):
            return x

    handle = serve.run(
        DAGDriver.options(name="StrictDriver").bind({"/only": Once.options(name="OnlyBranch").bind()}),
        route_prefix="/strict",
    )
    with pytest.raises(Exception):
        ray_tpu.get(handle.predict_with_route.remote("/nope", 1))


def test_dagdriver_under_non_root_prefix(serve_instance):
    # Free the CPUs held by earlier tests' replicas — this module's fixture
    # cluster is sized for one app at a time.
    for name in ("Doubler", "Negator", "DAGDriver", "TextDriver", "EchoText",
                 "StrictDriver", "OnlyBranch"):
        try:
            serve.delete(name)
        except Exception:
            pass
    time.sleep(1.0)

    # The proxy forwards the matched route prefix, so sub-route dispatch
    # works at ANY mount point — not just "/".
    @serve.deployment
    class Up:
        def __call__(self, x):
            return {"up": x + 1}

    @serve.deployment
    class Down:
        def __call__(self, x):
            return {"down": x - 1}

    serve.run(
        DAGDriver.options(name="ApiDriver").bind(
            {"/up": Up.bind(), "/down": Down.bind()}
        ),
        route_prefix="/api",
    )
    assert json.loads(_http("/api/up", 10)) == {"up": 11}
    assert json.loads(_http("/api/down", 10)) == {"down": 9}
