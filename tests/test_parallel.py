"""Parallelism-strategy correctness tests on the virtual 8-device CPU mesh
(SURVEY.md §5.7: these strategies are absent in the reference and built
natively here)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import _xla_attention, flash_attention
from ray_tpu.parallel.mesh import MeshConfig, create_mesh, logical_to_spec
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.ulysses import ulysses_attention


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    B, T, H, D = 2, 64, 4, 16
    return [jax.random.normal(k, (B, T, H, D), jnp.float32) for k in jax.random.split(key, 3)]


def test_mesh_resolve():
    cfg = MeshConfig(dp=2, tp=-1)
    sizes = cfg.resolve(8)
    assert sizes["dp"] == 2 and sizes["tp"] == 4


def test_create_mesh_axes():
    mesh = create_mesh(MeshConfig(dp=2, sp=2, tp=2))
    assert mesh.shape["dp"] == 2
    assert mesh.shape["sp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["pp"] == 1


def test_logical_to_spec():
    spec = logical_to_spec(("batch", "seq", "embed"))
    assert spec[0] == ("dp", "fsdp")
    assert spec[1] == "sp"


def test_flash_attention_matches_reference(qkv):
    q, k, v = qkv
    ref = _xla_attention(q, k, v, True, q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, causal=True, interpret=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(qkv, causal):
    q, k, v = qkv
    mesh = create_mesh(MeshConfig(sp=4, dp=2))
    ref = _xla_attention(q, k, v, causal, q.shape[-1] ** -0.5)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_exact(qkv, causal):
    q, k, v = qkv
    mesh = create_mesh(MeshConfig(sp=4, dp=2))
    ref = _xla_attention(q, k, v, causal, q.shape[-1] ** -0.5)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pipeline_matches_sequential():
    from ray_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    n_stages, d = 4, 8
    key = jax.random.PRNGKey(1)
    ws = jax.random.normal(key, (n_stages, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(2), (8, d))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    # Sequential reference.
    ref = x
    for i in range(n_stages):
        ref = stage_fn(ws[i], ref)
    out = pipeline_apply(stage_fn, ws, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_microbatches_exceed_stages():
    """The GPipe schedule's bubble arithmetic (T = M + S - 1 steps) at
    M > S — more microbatches than stages, the regime that actually shrinks
    the bubble — was previously only exercised at M == S."""
    from ray_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    n_stages, d = 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(3), (n_stages, d, d)) * 0.3

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    for M in (8, 16):
        x = jax.random.normal(jax.random.PRNGKey(M), (M * 2, d))
        ref = x
        for i in range(n_stages):
            ref = stage_fn(ws[i], ref)
        out = pipeline_apply(stage_fn, ws, x, mesh, num_microbatches=M)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_non_divisible_batch_asserts():
    """A batch that doesn't divide into num_microbatches fails loudly at
    the assertion, not with a silent reshape error downstream."""
    from ray_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    ws = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 8))
    x = jax.random.normal(jax.random.PRNGKey(5), (10, 8))  # 10 % 4 != 0

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    with pytest.raises(AssertionError, match="not divisible"):
        pipeline_apply(stage_fn, ws, x, mesh, num_microbatches=4)


def test_moe_layer_shapes_and_balance():
    from ray_tpu.parallel.moe import init_moe_params, moe_layer

    key = jax.random.PRNGKey(0)
    params = init_moe_params(key, d_model=16, d_ff=32, num_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
    out, aux = moe_layer(params, x, capacity_factor=2.0)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))
    # With generous capacity, most tokens should be routed (non-zero output).
    assert float(jnp.abs(out).mean()) > 0


def test_moe_expert_parallel_sharding():
    """The MoE layer jits under a sharded-experts constraint (ep axis)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.moe import init_moe_params, moe_layer

    mesh = create_mesh(MeshConfig(ep=4, dp=2))
    params = init_moe_params(jax.random.PRNGKey(0), 16, 32, 4)
    params = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P("ep"))) if p.shape[0] == 4 and p.ndim == 3 else jax.device_put(p, NamedSharding(mesh, P())),
        params,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
    out, aux = jax.jit(lambda p, x: moe_layer(p, x, capacity_factor=2.0))(params, x)
    assert out.shape == x.shape


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_reference(causal):
    """Ring-level custom VJP: grads of the two-ring-pass implementation match
    plain attention's autodiff (both impls; pallas runs in interpret mode)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.ops.attention import _xla_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    B, T, H, D = 1, 512, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(k1, (B, T, H, D))
    k = jax.random.normal(k2, (B, T, H, D))
    v = jax.random.normal(k3, (B, T, H, D))
    sc = 1.0 / np.sqrt(D)

    ref = jax.grad(lambda q, k, v: _xla_attention(q, k, v, causal, sc).sum(), argnums=(0, 1, 2))(q, k, v)
    for impl, interp in (("xla", False), ("pallas", True)):
        got = jax.grad(
            lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=causal, impl=impl, interpret=interp
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3)
