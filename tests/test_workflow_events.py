"""workflow.wait / sleep / continuation / event system (reference
python/ray/workflow: api.py wait_for_event:557, continuation:712,
event_listener.py:11, http_event_provider.py)."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def workflow_storage(tmp_path):
    workflow.init(str(tmp_path / "wf"))
    yield str(tmp_path / "wf")
    workflow.init(None)


def test_workflow_wait(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def quick(x):
        return x

    @ray_tpu.remote
    def slow(x):
        import time

        time.sleep(8)
        return x

    @ray_tpu.remote
    def first_ready(wait_out):
        ready, remaining = wait_out
        return (sorted(ready), remaining)

    w = workflow.wait([quick.bind(1), quick.bind(2), slow.bind(99)], num_returns=2)
    dag = first_ready.bind(w)
    ready, remaining = workflow.run(dag, workflow_id="wait1")
    assert ready == [1, 2] and remaining == 1

    with pytest.raises(ValueError):
        workflow.wait([quick.bind(1)], num_returns=2)


def test_workflow_sleep_durable(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def after(_):
        return "woke"

    t0 = time.time()
    assert workflow.run(after.bind(workflow.sleep(1.0)), workflow_id="zz") == "woke"
    took = time.time() - t0
    assert took >= 1.0
    # a finished workflow replays from the log: no second sleep
    t0 = time.time()
    assert workflow.run(after.bind(workflow.sleep(1.0)), workflow_id="zz") == "woke"
    assert time.time() - t0 < 0.9


def test_workflow_continuation_dynamic_dag(ray_start_regular, workflow_storage):
    """Recursive factorial via continuations — the canonical dynamic-DAG
    shape (reference workflow docs)."""

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    @ray_tpu.remote
    def factorial(n):
        from ray_tpu import workflow as wf

        if n <= 1:
            return 1
        return wf.continuation(mul.bind(n, factorial.bind(n - 1)))

    assert workflow.run(factorial.bind(5), workflow_id="fact") == 120
    # idempotent replay from the log
    assert workflow.run(factorial.bind(5), workflow_id="fact") == 120


def test_continuation_outside_workflow_executes_eagerly(ray_start_regular):
    @ray_tpu.remote
    def one():
        return 1

    os.environ.pop("RAY_TPU_IN_WORKFLOW", None)
    assert workflow.continuation(one.bind()) == 1
    with pytest.raises(TypeError):
        workflow.continuation(42)


def test_wait_for_event_delivery(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def combine(event, x):
        return (event["msg"], x)

    dag = combine.bind(workflow.wait_for_event(workflow.KVEventListener, "topic-a"), 7)
    wid, thread = workflow.run_async(dag, workflow_id="ev1")
    time.sleep(1.0)  # the poll step is blocking on the KV now
    workflow.deliver_event("topic-a", {"msg": "hello"})
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert workflow.get_output("ev1") == ("hello", 7)

    with pytest.raises(TypeError):
        workflow.wait_for_event(object, "x")


_CHILD = r"""
import sys
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu import workflow

workflow.init({storage!r})
ray_tpu.init(num_cpus=2)

@ray_tpu.remote
def handle(event):
    return ["handled", event["n"]]

dag = handle.bind(workflow.wait_for_event(workflow.KVEventListener, "crash-topic"))
workflow.run(dag, workflow_id="crashy")   # blocks forever: nobody delivers
"""


def test_driver_killed_mid_wait_resume_delivers_completes(
    ray_start_regular, workflow_storage
):
    """VERDICT r4 #5's done-bar: kill the driver while it waits for an
    event; resume in another process; deliver the event; the workflow
    completes."""
    script = _CHILD.format(repo="/root/repo", storage=workflow_storage)
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_TPUS="0")
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # kill the whole child cluster at once
    )
    try:
        # wait until the child has durably started the workflow
        deadline = time.time() + 120
        wf_dir = os.path.join(workflow_storage, "crashy")
        while time.time() < deadline and not os.path.isdir(wf_dir):
            time.sleep(0.2)
        assert os.path.isdir(wf_dir), "child never started the workflow"
        time.sleep(3)  # let the poll step get in flight
        assert proc.poll() is None, "child exited early"
    finally:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    assert workflow.get_status("crashy") == "RUNNING"  # durably interrupted

    # deliver the event FIRST (it lands in this cluster's KV), then resume:
    # the re-run poll step finds it immediately.
    workflow.deliver_event("crash-topic", {"n": 42})
    assert workflow.resume("crashy") == ["handled", 42]
    assert workflow.get_status("crashy") == "SUCCESSFUL"


def test_workflow_cancel_mid_wait_then_resume(ray_start_regular, workflow_storage):
    """workflow.cancel (VERDICT Missing #3): a workflow blocked on an event
    is cancelled within seconds; completed prefix steps stay persisted;
    resume restarts it and it completes off a delivered event."""

    @ray_tpu.remote
    def prefix():
        return "pre"

    @ray_tpu.remote
    def combine(p, event):
        return (p, event["n"])

    dag = combine.bind(
        prefix.bind(), workflow.wait_for_event(workflow.KVEventListener, "cancel-topic")
    )
    wid, thread = workflow.run_async(dag, workflow_id="cancelme")
    time.sleep(1.5)  # prefix done; poll step blocking on the KV
    workflow.cancel("cancelme")
    thread.join(timeout=30)
    assert not thread.is_alive()
    assert workflow.get_status("cancelme") == "CANCELED"
    with pytest.raises(ValueError):
        workflow.get_output("cancelme")
    # the completed prefix step was persisted before the cancel
    meta = workflow.get_metadata("cancelme")
    assert meta["status"] == "CANCELED"
    assert any(t.startswith("prefix-") for t in meta["tasks"])

    # resume restarts the cancelled workflow; deliver first so the re-run
    # poll step finds the event immediately
    workflow.deliver_event("cancel-topic", {"n": 7})
    assert workflow.resume("cancelme") == ("pre", 7)
    assert workflow.get_status("cancelme") == "SUCCESSFUL"

    with pytest.raises(ValueError):
        workflow.cancel("no-such-workflow")


def test_workflow_get_metadata(ray_start_regular, workflow_storage):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(double.bind(3), double.bind(4))
    assert workflow.run(dag, workflow_id="meta1") == 14

    meta = workflow.get_metadata("meta1")
    assert meta["workflow_id"] == "meta1"
    assert meta["status"] == "SUCCESSFUL"
    assert meta["stats"]["end_time"] >= meta["stats"]["start_time"]
    assert len(meta["tasks"]) == 3  # two doubles + one add

    task_meta = workflow.get_metadata("meta1", task_id=meta["tasks"][0])
    assert task_meta["status"] == "SUCCESSFUL"
    assert task_meta["task_id"] == meta["tasks"][0]

    with pytest.raises(ValueError):
        workflow.get_metadata("meta1", task_id="nope")
    with pytest.raises(ValueError):
        workflow.get_metadata("never-ran")


def test_http_event_provider_routes(ray_start_regular, workflow_storage):
    """POST /api/workflows/events/<key> delivers; GET reads back; a polling
    workflow completes off the HTTP-delivered event."""
    from ray_tpu._private import worker_context
    from ray_tpu.dashboard.head import DashboardHead

    cw = worker_context.get_core_worker()
    head = DashboardHead(cw.gcs.address, cw.session_dir)
    try:
        base = "http://%s:%d" % head.address

        @ray_tpu.remote
        def unwrap(event):
            return event["v"]

        dag = unwrap.bind(workflow.wait_for_event(workflow.KVEventListener, "http-topic"))
        wid, thread = workflow.run_async(dag, workflow_id="httpev")
        time.sleep(0.5)

        body = json.dumps({"v": 13}).encode()
        req = urllib.request.Request(
            base + "/api/workflows/events/http-topic", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.load(r)["delivered"] == "http-topic"

        thread.join(timeout=60)
        assert workflow.get_output("httpev") == 13

        with urllib.request.urlopen(
            base + "/api/workflows/events/http-topic", timeout=10
        ) as r:
            assert json.load(r)["event"] == {"v": 13}
    finally:
        head.stop()
