"""Per-node Serve proxy fleet + ingress fault tolerance.

Reference: python/ray/serve/_private/http_state.py:32 (one HTTPProxyActor per
node, controller-managed, health-checked) and the ingress-HA behavior the
single-proxy round-2 design could not provide (VERDICT r2, Missing #2).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


def _get(addr, path, timeout=30):
    with urllib.request.urlopen(f"http://{addr[0]}:{addr[1]}{path}", timeout=timeout) as r:
        return r.read()


@pytest.fixture
def serve_cluster(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2, object_store_memory=96 * 1024 * 1024)
    cluster.connect()
    cluster.wait_for_nodes()
    try:
        yield cluster
    finally:
        serve.shutdown()


def test_proxy_per_node(serve_cluster):
    serve.start()

    @serve.deployment(num_replicas=2, route_prefix="/hello")
    def hello(request):
        return "world"

    serve.run(hello.bind(), _blocking=True)
    deadline = time.time() + 60
    addrs = {}
    while time.time() < deadline:
        addrs = serve.http_addresses()
        if len(addrs) >= 3:
            break
        time.sleep(0.5)
    assert len(addrs) >= 3, f"expected a proxy on each of 3 nodes, got {addrs}"
    # Every node's ingress serves the same app.
    for node_id, addr in addrs.items():
        assert _get(addr, "/hello") == b"world"


def test_ingress_survives_proxy_node_death(serve_cluster):
    cluster = serve_cluster
    serve.start()

    @serve.deployment(num_replicas=3, route_prefix="/ping")
    def ping(request):
        return "pong"

    serve.run(ping.bind(), _blocking=True)
    deadline = time.time() + 60
    while len(serve.http_addresses()) < 3 and time.time() < deadline:
        time.sleep(0.5)
    addrs = serve.http_addresses()
    assert len(addrs) >= 3

    # Kill a node that hosts a proxy — but never the head (node index 0
    # hosts the driver's raylet).
    head_id = cluster.nodes[0].node_id
    victim = next(nid for nid in addrs if nid != head_id)
    victim_raylet = next(r for r in cluster.nodes if r.node_id == victim)
    cluster.remove_node(victim_raylet)

    # Requests keep flowing through surviving proxies the whole time.
    survivors = {nid: a for nid, a in addrs.items() if nid != victim}
    for addr in survivors.values():
        assert _get(addr, "/ping") == b"pong"

    # The controller notices the dead node and drops its proxy from the
    # routing surface.
    deadline = time.time() + 60
    while time.time() < deadline:
        now = serve.http_addresses()
        if victim not in now and len(now) >= len(survivors):
            break
        time.sleep(0.5)
    assert victim not in serve.http_addresses()
    for addr in serve.http_addresses().values():
        assert _get(addr, "/ping") == b"pong"
