"""Ray-Train-equivalent tests: the BASELINE minimum slice (JaxTrainer MNIST
MLP, 1 CPU worker) and multi-worker data-parallel training with gradient
allreduce through the collective plane."""

import numpy as np
import pytest

import ray_tpu
from conftest import skip_without_multiprocess_collectives
from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.train.jax import JaxTrainer


def _synthetic_mnist(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 784)).astype(np.float32)
    w_true = rng.standard_normal((784, 10)).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    return x, y


def mnist_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.mlp import init_mlp, mlp_loss

    x, y = _synthetic_mnist()
    params = init_mlp(jax.random.PRNGKey(0), (784, 64, 10))
    opt = optax.adam(config.get("lr", 1e-2))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        (loss, acc), grads = jax.value_and_grad(mlp_loss, has_aux=True)(params, batch)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    for epoch in range(config.get("epochs", 5)):
        params, opt_state, loss, acc = step(params, opt_state, batch)
        session.report(
            {"epoch": epoch, "loss": float(loss), "acc": float(acc)},
            checkpoint=Checkpoint.from_dict({"epoch": epoch}) if epoch % 2 == 0 else None,
        )


def test_jax_trainer_minimum_slice(ray_start_regular):
    """BASELINE config #1: JaxTrainer MNIST MLP, 1 CPU worker, end-to-end."""
    trainer = JaxTrainer(
        mnist_loop,
        train_loop_config={"epochs": 6, "lr": 1e-2},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path="/tmp/rtpu_train_test",
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["epoch"] == 5
    assert result.metrics["loss"] < 2.0
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["epoch"] == 4


def dp_loop(config):
    """2-worker data-parallel loop: grads allreduced over the XLA world."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.air import session
    from ray_tpu.util import collective as col

    rank = session.get_world_rank()
    world = session.get_world_size()
    # Per-rank shard of a quadratic problem: minimise sum over all shards.
    w = jnp.zeros((4,))
    targets = jnp.full((4,), float(rank + 1))

    def loss_fn(w):
        return jnp.sum((w - targets) ** 2)

    for step_i in range(10):
        g = jax.grad(loss_fn)(w)
        g_sum = jnp.asarray(col.allreduce(g, group_name="train"))
        w = w - 0.1 * (g_sum / world)
        session.report({"step": step_i, "w0": float(w[0]), "rank": rank})


@skip_without_multiprocess_collectives
def test_jax_trainer_multi_worker_dp(ray_start_regular):
    trainer = JaxTrainer(
        dp_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path="/tmp/rtpu_train_test"),
    )
    result = trainer.fit()
    assert result.error is None
    # Optimum of the summed objective: mean of targets = (1+2)/2 = 1.5.
    assert abs(result.metrics["w0"] - 1.5) < 0.2


def test_trainer_failure_restart(ray_start_regular):
    """Worker failure restarts the whole gang from the last checkpoint
    (reference: BackendExecutor failure path + FailureConfig)."""
    import os

    marker = f"/tmp/rtpu_train_fail_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    def flaky_loop(config):
        import os as _os
        import time as _time

        from ray_tpu.air import session

        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["epoch"] + 1 if ckpt else 0
        for epoch in range(start, 4):
            if epoch == 2 and not _os.path.exists(config["marker"]):
                with open(config["marker"], "w") as f:
                    f.write("1")
                _os._exit(1)
            session.report(
                {"epoch": epoch, "resumed": start > 0},
                checkpoint=Checkpoint.from_dict({"epoch": epoch}),
            )
            _time.sleep(0.3)  # let the driver poll before a crash (like a real step)

    from ray_tpu.air.config import FailureConfig

    trainer = JaxTrainer(
        flaky_loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path="/tmp/rtpu_train_test",
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    result = trainer.fit()
    assert result.metrics["epoch"] == 3
    assert result.metrics["resumed"] is True
    os.unlink(marker)


def test_sklearn_trainer(ray_start_regular):
    """SklearnTrainer fits remotely on a Dataset and checkpoints the
    estimator (reference: train/sklearn/sklearn_trainer.py)."""
    from sklearn.linear_model import LogisticRegression

    from ray_tpu import data as rdata
    from ray_tpu.train import SklearnTrainer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    rows = [{"a": X[i, 0], "b": X[i, 1], "c": X[i, 2], "label": int(y[i])} for i in range(200)]
    train_ds = rdata.from_items(rows[:150])
    valid_ds = rdata.from_items(rows[150:])
    trainer = SklearnTrainer(
        estimator=LogisticRegression(),
        label_column="label",
        datasets={"train": train_ds, "valid": valid_ds},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_score"] > 0.85
    assert result.metrics["valid_score"] > 0.75
    est = result.checkpoint.to_dict()["estimator"]
    pred = est.predict(X[:5])
    assert pred.shape == (5,)


def test_gbdt_trainers_gated():
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer

    with pytest.raises(ImportError, match="xgboost"):
        XGBoostTrainer(datasets={})
    with pytest.raises(ImportError, match="lightgbm"):
        LightGBMTrainer(datasets={})
