"""Compiled execution graphs (dag/compiled.py + experimental/channel/).

Covers the acceptance surface of the subsystem: correct repeated dispatch
with ZERO raylet RPCs / ObjectRef allocations per iteration, the per-DAG
actor cache shared with classic execute(), application-error flow,
backpressure past max_buffered_results, read timeouts, teardown (channel
slots released back to the arena) and the chaos path — SIGKILL of a
mid-pipeline actor surfaces a typed error naming the dead stage instead of
hanging, and teardown still completes without leaking shm.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError, TaskError
from ray_tpu.experimental.channel import ChannelTimeoutError


@pytest.fixture(scope="module")
def compiled_cluster():
    """One cluster for the whole module: compiled-graph tests are isolated
    per-DAG (own actors, own channels, per-test before/after assertions),
    and a shared boot keeps this module's tier-1 wall-time small."""
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


@ray_tpu.remote
class Stage:
    def __init__(self, inc=1):
        self.inc = inc

    def work(self, x):
        return x + self.inc

    def mul(self, x):
        return x * 10

    def add(self, x, y):
        return x + y

    def boom(self, x):
        if x == 3:
            raise ValueError("x was 3")
        return x

    def slow(self, x):
        time.sleep(1.5)
        return x

    def pid(self):
        return os.getpid()


def _linear_dag(n_stages):
    stages = [Stage.bind() for _ in range(n_stages)]
    with InputNode() as inp:
        d = inp
        for s in stages:
            d = s.work.bind(d)
    return d, stages


def test_compiled_linear_pipeline_zero_control_plane(compiled_cluster):
    from ray_tpu._private import worker_context

    d, _ = _linear_dag(4)
    compiled = d.experimental_compile()
    try:
        assert compiled.execute(0).get() == 4  # warm the loop
        cw = worker_context.get_core_worker()
        raylet_seq0 = cw.raylet._seq
        owned0 = len(cw.owned)
        pending0 = len(cw.pending_tasks)
        for i in range(25):
            assert compiled.execute(i).get() == i + 4
        # The steady-state iteration touches neither the raylet nor the
        # ObjectRef/ownership plane — the whole point of compiling.
        assert cw.raylet._seq - raylet_seq0 == 0
        assert len(cw.owned) - owned0 == 0
        assert len(cw.pending_tasks) - pending0 == 0
    finally:
        compiled.teardown()


def test_compiled_out_of_order_get_and_pipelining(compiled_cluster):
    d, _ = _linear_dag(2)
    compiled = d.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(8)]
        # Consume newest-first: earlier results buffer driver-side.
        assert [r.get() for r in reversed(refs)] == [i + 2 for i in reversed(range(8))]
        # Repeated get returns the cached value.
        assert refs[0].get() == 2
    finally:
        compiled.teardown()


def test_compiled_multi_output_and_input_attributes(compiled_cluster):
    a, b = Stage.bind(), Stage.bind()
    with InputNode() as inp:
        dag = MultiOutputNode([a.work.bind(inp["x"]), b.mul.bind(inp["y"])])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute({"x": 1, "y": 2}).get() == [2, 20]
        assert compiled.execute({"x": 5, "y": 7}).get() == [6, 70]
    finally:
        compiled.teardown()


def test_compiled_fan_in_and_const_args(compiled_cluster):
    a, b, c = Stage.bind(), Stage.bind(), Stage.bind()
    with InputNode() as inp:
        left = a.work.bind(inp)
        right = b.mul.bind(inp)
        dag = c.add.bind(left, right)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(3).get() == (3 + 1) + (3 * 10)
    finally:
        compiled.teardown()


def test_compiled_validation_errors(compiled_cluster):
    @ray_tpu.remote
    def task(x):
        return x

    with InputNode() as inp:
        fn_dag = task.bind(inp)
    with pytest.raises(ValueError, match="actor-method nodes only"):
        fn_dag.experimental_compile()

    s = Stage.bind()
    no_input = s.work.bind(1)
    with pytest.raises(ValueError, match="InputNode"):
        no_input.experimental_compile()

    with InputNode() as inp:
        dangling_src = Stage.bind()
        used = s.work.bind(inp)
        dangling = dangling_src.work.bind(inp)  # produced, never consumed
        dag = MultiOutputNode([used])
    del dangling
    # (dangling node is unreachable from the root, so this compiles fine)
    dag.experimental_compile(max_buffered_results=2).teardown()


def test_compiled_application_error_flows_and_dag_survives(compiled_cluster):
    a, b = Stage.bind(), Stage.bind()
    with InputNode() as inp:
        dag = b.work.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(2).get() == 3
        with pytest.raises(TaskError, match="x was 3"):
            compiled.execute(3).get()
        # Per-iteration failure only: the pipeline keeps serving.
        assert compiled.execute(4).get() == 5
    finally:
        compiled.teardown()


def test_compiled_backpressure_blocks_producer(compiled_cluster):
    d, _ = _linear_dag(1)
    compiled = d.experimental_compile(max_buffered_results=2, submit_timeout_s=0.5)
    try:
        refs = [compiled.execute(i) for i in range(2)]
        time.sleep(0.3)  # drain the input ring into the output ring
        compiled.execute(2)
        with pytest.raises(ChannelTimeoutError, match="unconsumed"):
            for i in range(3, 8):  # must jam within num_slots extra writes
                compiled.execute(i)
        assert refs[0].get() == 1  # buffered results still retrievable
    finally:
        compiled.teardown()


def test_compiled_get_honors_timeout(compiled_cluster):
    s = Stage.bind()
    with InputNode() as inp:
        dag = s.slow.bind(inp)
    compiled = dag.experimental_compile()
    try:
        ref = compiled.execute(7)
        t0 = time.monotonic()
        with pytest.raises(GetTimeoutError):
            ref.get(timeout=0.2)
        assert time.monotonic() - t0 < 1.0
        assert ref.get() == 7  # late result still lands
    finally:
        compiled.teardown()


def test_compiled_multi_output_get_timeout_keeps_iterations_paired(compiled_cluster):
    """A get(timeout=) that expires after consuming SOME output channels of
    an iteration must not skew pairing: the partially-drained envelopes
    stage, and the retry resumes with the same iteration."""
    fast, slow = Stage.bind(), Stage.bind()
    with InputNode() as inp:
        dag = MultiOutputNode([fast.work.bind(inp), slow.slow.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        ref = compiled.execute(1)
        with pytest.raises(GetTimeoutError):
            ref.get(timeout=0.3)  # fast output consumed, slow still pending
        assert ref.get() == [2, 1]
        assert compiled.execute(5).get() == [6, 5]  # pairing intact
    finally:
        compiled.teardown()


def test_compiled_abandoned_results_raise_instead_of_leaking(compiled_cluster):
    """Skipping refs cannot grow the driver-side result buffer without
    bound: draining past max_buffered_results unconsumed results raises."""
    d, _ = _linear_dag(1)
    compiled = d.experimental_compile(max_buffered_results=2)
    try:
        refs = [compiled.execute(i) for i in range(3)]
        with pytest.raises(ValueError, match="buffered"):
            refs[2].get(timeout=10)
        # Nothing was lost: consuming in order recovers every result.
        assert [refs[i].get(timeout=10) for i in range(3)] == [1, 2, 3]
    finally:
        compiled.teardown()


def test_compiled_execute_after_teardown_raises(compiled_cluster):
    d, _ = _linear_dag(1)
    compiled = d.experimental_compile()
    assert compiled.execute(1).get() == 2
    compiled.teardown()
    compiled.teardown()  # idempotent
    with pytest.raises(ValueError, match="torn down"):
        compiled.execute(2)


def test_compiled_actor_death_chaos(compiled_cluster):
    """SIGKILL a mid-pipeline actor during compiled execution: get() raises
    a typed error naming the dead stage, teardown() completes, and the
    channel slots return to the arena (no leaked shm)."""
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    stages = [Stage.bind() for _ in range(3)]
    pids = [ray_tpu.get(s.resolve_actor_handle().pid.remote()) for s in stages]
    with InputNode() as inp:
        d = inp
        for s in stages:
            d = s.work.bind(d)
    store0 = cw.raylet.call("get_state")["store"]
    compiled = d.experimental_compile()
    assert compiled.execute(0).get() == 3
    assert cw.raylet.call("get_state")["store"]["num_channels"] > 0

    os.kill(pids[1], signal.SIGKILL)
    ref = compiled.execute(1)
    with pytest.raises(ActorDiedError, match="1:work"):
        ref.get(timeout=30)
    with pytest.raises(ActorDiedError):
        compiled.execute(2)

    compiled.teardown()
    store1 = cw.raylet.call("get_state")["store"]
    assert store1["num_channels"] == store0["num_channels"]
    assert store1["used"] <= store0["used"]


def test_classic_calls_still_served_while_compiled(compiled_cluster):
    """The resident loop runs on its own thread: an actor bound into a
    compiled graph still answers classic method calls (and classic
    execute() of the same DAG) instead of queuing behind the loop forever."""
    d, stages = _linear_dag(2)
    compiled = d.experimental_compile()
    try:
        assert compiled.execute(1).get() == 3
        handle = stages[0].resolve_actor_handle()
        assert ray_tpu.get(handle.work.remote(10), timeout=20) == 11
        assert ray_tpu.get(d.execute(1), timeout=30) == 3  # classic walk
        assert compiled.execute(2).get() == 4  # compiled path unaffected
    finally:
        compiled.teardown()


def test_compiled_oversize_payload_side_channel(compiled_cluster):
    """Envelopes larger than a ring slot ride the chunked side-channel
    (marker slot + acked channel_data chunks) and still arrive in order."""
    np = pytest.importorskip("numpy")

    @ray_tpu.remote
    class Big:
        def double(self, arr):
            return arr * 2

    b = Big.bind()
    with InputNode() as inp:
        dag = b.double.bind(inp)
    # 8 KiB slots vs ~1 MiB payloads: every hop goes side-channel.
    compiled = dag.experimental_compile(slot_size_bytes=8 * 1024)
    try:
        arr = np.arange(256 * 1024, dtype=np.int32)
        for i in range(3):
            out = compiled.execute(arr + i).get()
            assert out.dtype == np.int32 and out[1] == (1 + i) * 2
        assert compiled.execute(np.int32(21)).get() == 42  # small again
    finally:
        compiled.teardown()


def test_channel_remote_mode_fallback(compiled_cluster):
    """Cross-node (no shared arena) channels: every envelope rides the
    chunked RPC path with channel_query backpressure. Exercised directly
    with both endpoints in this process and a remote-only descriptor."""
    from ray_tpu._private import worker_context
    from ray_tpu.experimental.channel import (
        KIND_VALUE,
        ChannelReader,
        ChannelTimeoutError as CTE,
        ChannelWriter,
        make_descriptor,
    )
    from ray_tpu._private import serialization

    cw = worker_context.get_core_worker()
    desc = make_descriptor(
        "rm" * 12, arena=None, offset=0, num_slots=2, slot_size=8 * 1024,
        reader_addr=cw.address, label="remote-test",
    )
    writer = ChannelWriter(desc, cw)
    reader = ChannelReader(desc, cw)
    assert not writer.shm and not reader.shm
    kinds_vals = []
    for i in range(3):
        writer.write(KIND_VALUE, serialization.serialize(i * 7).to_bytes())
        kind, data, _hop = reader.read(timeout=5)
        kinds_vals.append((kind, serialization.deserialize(data)))
    assert kinds_vals == [(KIND_VALUE, 0), (KIND_VALUE, 7), (KIND_VALUE, 14)]
    # Backpressure: 2 unconsumed envelopes fill the remote queue bound.
    writer.write(KIND_VALUE, serialization.serialize(1).to_bytes())
    writer.write(KIND_VALUE, serialization.serialize(2).to_bytes())
    with pytest.raises(CTE):
        writer.write(KIND_VALUE, serialization.serialize(3).to_bytes(), timeout=0.5)
    cw.channels.drop([desc["cid"]])


def test_classic_execute_reuses_actor_gang(compiled_cluster):
    """Satellite: classic dag.execute() on ClassNode graphs reuses the
    per-DAG actor cache instead of spawning fresh actors per call."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def add(self, x):
            self.v += x
            return self.v

        def pid(self):
            return os.getpid()

    with InputNode() as inp:
        counter = Counter.bind()
        dag = counter.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 5
    # Same actor: state accumulates and the pid is stable across executes.
    assert ray_tpu.get(dag.execute(5)) == 10
    pid_dag = counter.pid.bind()
    assert ray_tpu.get(pid_dag.execute()) == ray_tpu.get(pid_dag.execute())


def test_compile_rejects_double_binding(compiled_cluster):
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    d, stages = _linear_dag(1)
    compiled = d.experimental_compile()
    try:
        channels_live = cw.raylet.call("get_state")["store"]["num_channels"]
        with InputNode() as inp:
            other = stages[0].mul.bind(inp)
        with pytest.raises(ValueError, match="already participates"):
            other.experimental_compile()
        # The failed compile released every channel it had allocated.
        assert (
            cw.raylet.call("get_state")["store"]["num_channels"] == channels_live
        )
    finally:
        compiled.teardown()
    # After teardown the actor is free to join a new compiled graph.
    compiled2 = other.experimental_compile()
    try:
        assert compiled2.execute(3).get() == 30
    finally:
        compiled2.teardown()
