"""Streaming generator returns (reference: StreamingObjectRefGenerator,
_raylet.pyx:227 + num_returns="streaming"): yielded values become objects as
they are produced; the caller iterates WHILE the task runs."""

import time

import numpy as np
import pytest

import ray_tpu


def test_streaming_task_yields_refs(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = gen.remote(5)
    assert isinstance(out, ray_tpu.ObjectRefGenerator)
    values = [ray_tpu.get(ref) for ref in out]
    assert values == [0, 10, 20, 30, 40]


def test_streaming_overlaps_with_producer(ray_start_regular):
    """The first item must be consumable long before the producer finishes."""

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.5)

    t0 = time.monotonic()
    it = slow_gen.remote()
    first = ray_tpu.get(it.next_with_timeout(30.0))
    first_latency = time.monotonic() - t0
    rest = [ray_tpu.get(r) for r in it]
    assert first == 0 and rest == [1, 2, 3]
    # Producer takes ~2s total; the first item arrived well before that.
    assert first_latency < 1.5, first_latency


def test_streaming_large_items_ride_plasma(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def chunks():
        for i in range(3):
            yield np.full(256 * 1024, i, dtype=np.int64)  # 2 MiB each

    arrays = [ray_tpu.get(r) for r in chunks.remote()]
    for i, a in enumerate(arrays):
        np.testing.assert_array_equal(a, np.full(256 * 1024, i, dtype=np.int64))


def test_streaming_error_propagates(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise RuntimeError("stream blew up")

    it = bad.remote()
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(Exception, match="stream blew up"):
        for ref in it:
            ray_tpu.get(ref)


def test_streaming_non_generator_raises(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def not_a_gen():
        return 42

    it = not_a_gen.remote()
    with pytest.raises(Exception, match="generator"):
        next(it)
