"""Tests for util shims: ActorPool, Queue, multiprocessing.Pool, iter
(analog of the reference's test_actor_pool.py, test_queue.py,
util/multiprocessing tests, test_iter.py)."""

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class _Doubler:
    def double(self, x):
        return x * 2


def test_actor_pool_map(ray_start_regular):
    pool = ActorPool([_Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert out == [2, 4, 6, 8]


def test_actor_pool_map_unordered(ray_start_regular):
    pool = ActorPool([_Doubler.remote() for _ in range(2)])
    out = list(pool.map_unordered(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert sorted(out) == [2, 4, 6, 8]


def test_actor_pool_submit_get_next(ray_start_regular):
    pool = ActorPool([_Doubler.remote()])
    pool.submit(lambda a, v: a.double.remote(v), 1)
    pool.submit(lambda a, v: a.double.remote(v), 2)  # queued: 1 actor
    assert pool.has_next()
    assert pool.get_next() == 2
    assert pool.get_next() == 4
    assert not pool.has_next()


def test_actor_pool_pop_push(ray_start_regular):
    actors = [_Doubler.remote() for _ in range(2)]
    pool = ActorPool(actors)
    a = pool.pop_idle()
    assert a is not None
    pool.push(a)
    assert pool.has_free()


def test_queue_basic(ray_start_regular):
    q = Queue()
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert not q.empty()
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()


def test_queue_nowait_and_limits(ray_start_regular):
    q = Queue(maxsize=2)
    q.put_nowait(1)
    q.put_nowait(2)
    assert q.full()
    with pytest.raises(Full):
        q.put(3, block=False)
    with pytest.raises(Empty):
        Queue().get_nowait()


def test_queue_batch(ray_start_regular):
    q = Queue()
    q.put_nowait_batch([1, 2, 3])
    assert q.get_nowait_batch(2) == [1, 2]
    with pytest.raises(Empty):
        q.get_nowait_batch(5)
    assert q.qsize() == 1  # failed batch get must not consume


def test_queue_get_timeout(ray_start_regular):
    q = Queue()
    with pytest.raises(Empty):
        q.get(timeout=0.2)


def test_queue_shared_between_tasks(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(queue, n):
        for i in range(n):
            queue.put(i)
        return n

    ray_tpu.get(producer.remote(q, 3))
    assert [q.get(timeout=5) for _ in range(3)] == [0, 1, 2]


def test_mp_pool_map(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(lambda x: x * x, range(6)) == [0, 1, 4, 9, 16, 25]


def test_mp_pool_starmap_apply(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    pool = Pool(processes=2)
    assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
    assert pool.apply(lambda a: a * 10, (4,)) == 40
    res = pool.apply_async(lambda a: a + 1, (1,))
    assert res.get(timeout=30) == 2
    pool.close()
    pool.join()


def test_mp_pool_imap(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    pool = Pool(processes=2)
    assert list(pool.imap(lambda x: x + 1, range(5), chunksize=2)) == [1, 2, 3, 4, 5]
    assert sorted(pool.imap_unordered(lambda x: x + 1, range(5), chunksize=2)) == [1, 2, 3, 4, 5]


def test_parallel_iterator(ray_start_regular):
    from ray_tpu.util import iter as par_iter

    it = par_iter.from_range(8, num_shards=2)
    assert it.num_shards() == 2
    out = sorted(it.for_each(lambda x: x * 2).gather_sync())
    assert out == [0, 2, 4, 6, 8, 10, 12, 14]

    out2 = sorted(par_iter.from_items([1, 2, 3, 4], num_shards=2).filter(lambda x: x % 2 == 0).gather_async())
    assert out2 == [2, 4]

    batches = list(par_iter.from_range(4, num_shards=1).batch(2).gather_sync())
    assert batches == [[0, 1], [2, 3]]

    assert par_iter.from_range(10, num_shards=2).take(3) == [0, 1, 2]


def test_joblib_backend(ray_start_regular):
    """joblib Parallel over ray_tpu tasks (reference: util/joblib)."""
    import joblib

    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        out = joblib.Parallel()(joblib.delayed(lambda x: x * x)(i) for i in range(12))
    assert out == [i * i for i in range(12)]
