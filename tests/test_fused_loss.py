"""ops/losses.fused_lm_loss numerics vs the materialized log-softmax path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.transformer import TransformerConfig, init_params, loss_fn
from ray_tpu.ops.losses import fused_lm_loss


def _naive(x, head, targets):
    logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0].mean()


@pytest.mark.parametrize("chunk", [64, 128, 1000])  # 1000: non-dividing -> _pick_chunk
def test_fused_matches_naive_forward_and_grad(chunk):
    key = jax.random.PRNGKey(0)
    N, D, V = 256, 64, 512
    x = jax.random.normal(key, (N, D), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(1), (D, V), jnp.float32) * 0.1
    targets = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)

    f_fused = lambda x, h: fused_lm_loss(x, h, targets, chunk_size=chunk)
    f_naive = lambda x, h: _naive(x, h, targets)

    lf = f_fused(x, head)
    ln = f_naive(x, head)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-5)

    gf = jax.grad(f_fused, argnums=(0, 1))(x, head)
    gn = jax.grad(f_naive, argnums=(0, 1))(x, head)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gn[0]), rtol=2e-4, atol=2e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gn[1]), rtol=2e-4, atol=2e-6)


def test_fused_bf16_inputs_finite_and_close():
    N, D, V = 128, 32, 256
    x = (jax.random.normal(jax.random.PRNGKey(0), (N, D)) * 2).astype(jnp.bfloat16)
    head = (jax.random.normal(jax.random.PRNGKey(1), (D, V)) * 0.2).astype(jnp.bfloat16)
    targets = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    loss = fused_lm_loss(x, head, targets)
    naive = _naive(x.astype(jnp.float32), head.astype(jnp.float32), targets)
    assert jnp.isfinite(loss)
    np.testing.assert_allclose(float(loss), float(naive), rtol=3e-2)


def test_model_loss_fused_matches_unfused():
    cfg_base = dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=False,
    )
    cfg_f = TransformerConfig(**cfg_base, fused_loss=True)
    cfg_u = TransformerConfig(**cfg_base, fused_loss=False)
    params = init_params(jax.random.PRNGKey(0), cfg_f)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 128)
    batch = {"tokens": tokens}
    lf = loss_fn(params, batch, cfg_f)
    lu = loss_fn(params, batch, cfg_u)
    np.testing.assert_allclose(float(lf), float(lu), rtol=1e-5)
    gf = jax.grad(lambda p: loss_fn(p, batch, cfg_f))(params)
    gu = jax.grad(lambda p: loss_fn(p, batch, cfg_u))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gu)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6)


def test_fused_under_jit_and_mesh():
    """Compiles under jit with a tp-sharded head (sharding propagation must
    handle the chunked scan; 8-device CPU mesh from conftest)."""
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs multi-device CPU mesh")
    mesh = Mesh(_np.array(devs[:2]), ("tp",))
    N, D, V = 128, 32, 256
    x = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(1), (D, V), jnp.float32) * 0.1
    targets = jax.random.randint(jax.random.PRNGKey(2), (N,), 0, V)
    head = jax.device_put(head, NamedSharding(mesh, P(None, "tp")))
    loss = jax.jit(lambda x, h: fused_lm_loss(x, h, targets))(x, head)
    naive = _naive(x, jax.device_put(head, NamedSharding(mesh, P(None, None))), targets)
    np.testing.assert_allclose(float(loss), float(naive), rtol=1e-5)


def test_sliding_window_train_step_runs_and_differs():
    """Training path with sliding_window: loss_fn is finite, grads flow,
    and the window genuinely changes the loss vs full attention."""
    from ray_tpu.models.transformer import TransformerConfig, init_params, loss_fn

    base = dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    cfg_w = TransformerConfig(**base, sliding_window=8)
    cfg_f = TransformerConfig(**base)
    params = init_params(jax.random.PRNGKey(0), cfg_w)
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 128)}
    lw = loss_fn(params, batch, cfg_w)
    lf = loss_fn(params, batch, cfg_f)
    assert jnp.isfinite(lw) and jnp.isfinite(lf)
    assert abs(float(lw) - float(lf)) > 1e-6, "window had no effect on loss"
    grads = jax.grad(lambda p: loss_fn(p, batch, cfg_w))(params)
    for g in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(g)).all()
