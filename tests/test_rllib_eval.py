"""Evaluation machinery: dedicated eval workers + Algorithm.evaluate().

Reference: rllib/algorithms/algorithm.py:850 (Algorithm.evaluate with its
own evaluation WorkerSet), algorithm_config.py:383 (.evaluation() config
section). Eval rollouts must be greedy (explore=False) and never mix into
training episode stats.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_cluster():
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_ppo_evaluation_with_dedicated_workers(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=1, num_envs_per_worker=2)
        .training(lr=3e-4, train_batch_size=256, sgd_minibatch_size=128, num_sgd_iter=2)
        .evaluation(evaluation_interval=2, evaluation_num_workers=1, evaluation_duration=3)
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        r1 = algo.train()  # iteration 1: eval not due (interval=2)
        assert "evaluation" not in r1
        r2 = algo.train()  # iteration 2: eval fires
        ev = r2["evaluation"]
        assert np.isfinite(ev["episode_reward_mean"])
        assert ev["episodes_this_iter"] >= 3
        assert np.isfinite(ev["episode_len_mean"])
        # Dedicated worker set, distinct from the training workers.
        assert algo._eval_workers is not None
        assert algo._eval_workers is not algo.workers
        # Training reward key is still reported separately.
        assert "episode_reward_mean" in r2
    finally:
        algo.cleanup()


def test_custom_stack_algorithm_evaluates_locally(ray_cluster):
    # DQN builds its own learner stack (no base WorkerSet/LearnerGroup), so
    # evaluate() falls back to driver-local greedy episodes through
    # compute_single_action.
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .training(lr=1e-3, train_batch_size=32, learning_starts=100, rollout_steps_per_iter=200)
        .evaluation(evaluation_interval=1, evaluation_duration=2)
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        r = algo.train()
        ev = r["evaluation"]
        assert np.isfinite(ev["episode_reward_mean"])
        assert ev["episodes_this_iter"] == 2
        # No dedicated worker set was built for the local path.
        assert getattr(algo, "_eval_workers", None) is None
    finally:
        algo.cleanup()


def test_eval_rollouts_are_greedy(ray_cluster):
    # sample(explore=False) must pick argmax actions: recompute the greedy
    # action for every observation in the batch straight from the weights
    # and compare (this is what distinguishes evaluation from training
    # rollouts in the reference).
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
    from ray_tpu.rllib.models import ModelCatalog
    from ray_tpu.rllib.policy.sample_batch import ACTIONS, OBS

    probe = gym.make("CartPole-v1")
    spec = ModelCatalog.get_model_spec(
        probe.observation_space, probe.action_space,
        {"fcnet_hiddens": (32,), "conv_filters": None},
    )
    probe.close()
    worker = RolloutWorker("CartPole-v1", spec, worker_index=0, num_envs=1, seed=3)
    params = rl_module.init_params(jax.random.PRNGKey(0), spec)
    worker.set_weights(params)
    batch = worker.sample(40, explore=False)
    logits, _ = rl_module.forward(
        jax.tree_util.tree_map(jnp.asarray, params), jnp.asarray(batch[OBS]), spec
    )
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    assert np.array_equal(np.asarray(batch[ACTIONS]).ravel(), greedy.ravel())
    worker.stop()


def test_evaluation_duration_timesteps(ray_cluster):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=1, num_envs_per_worker=2)
        .training(lr=3e-4, train_batch_size=256, sgd_minibatch_size=128, num_sgd_iter=2)
        .evaluation(
            evaluation_interval=1,
            evaluation_num_workers=1,
            evaluation_duration=64,
            evaluation_duration_unit="timesteps",
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    try:
        r = algo.train()
        assert "evaluation" in r
        assert np.isfinite(r["evaluation"]["episode_reward_mean"]) or (
            r["evaluation"]["episodes_this_iter"] == 0
        )
    finally:
        algo.cleanup()


def _make_team_env_classes():
    import gymnasium as gym

    from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

    class DiscreteTeam(MultiAgentEnv):
        """Two agents, fixed 4-step episodes, discrete actions."""

        possible_agents = ["a", "b"]

        def __init__(self, config=None):
            self._obs_space = gym.spaces.Box(-1, 1, (2,), np.float32)
            self._act_space = gym.spaces.Discrete(2)
            self.t = 0

        @property
        def observation_space(self):
            return self._obs_space

        @property
        def action_space(self):
            return self._act_space

        def reset(self, *, seed=None):
            self.t = 0
            obs = np.zeros(2, np.float32)
            return {"a": obs, "b": obs}, {}

        def step(self, actions):
            self.t += 1
            obs = np.full(2, self.t / 4.0, np.float32)
            done = self.t >= 4
            rew = {a: float(actions[a]) for a in self.possible_agents}
            return (
                {"a": obs, "b": obs},
                rew,
                {"__all__": done},
                {"__all__": False},
                {},
            )

        def close(self):
            pass

    class ContinuousTeam(DiscreteTeam):
        def __init__(self, config=None):
            super().__init__(config)
            self._act_space = gym.spaces.Box(-1, 1, (1,), np.float32)

        def step(self, actions):
            self.t += 1
            obs = np.full(2, self.t / 4.0, np.float32)
            done = self.t >= 4
            rew = {a: -abs(float(actions[a][0])) for a in self.possible_agents}
            return (
                {"a": obs, "b": obs},
                rew,
                {"__all__": done},
                {"__all__": False},
                {},
            )

    return DiscreteTeam, ContinuousTeam


def test_qmix_and_maddpg_evaluate(ray_cluster):
    # Multi-agent algorithms override _evaluate_local (action DICTS, team
    # reward); one train+eval iteration each, learning gated off via a high
    # learning_starts so the test stays fast.
    import jax

    jax.config.update("jax_platforms", "cpu")
    DiscreteTeam, ContinuousTeam = _make_team_env_classes()
    from ray_tpu.rllib import QMIXConfig
    from ray_tpu.rllib.algorithms.maddpg import MADDPGConfig

    qcfg = (
        QMIXConfig()
        .environment(DiscreteTeam)
        .training(rollout_steps_per_iter=16, learning_starts=10_000)
        .evaluation(evaluation_interval=1, evaluation_duration=2)
        .debugging(seed=0)
    )
    qalgo = qcfg.build()
    try:
        r = qalgo.train()
        ev = r["evaluation"]
        assert ev["episodes_this_iter"] == 2
        assert np.isfinite(ev["episode_reward_mean"])
        assert ev["episode_len_mean"] == 4.0
    finally:
        qalgo.cleanup()

    mcfg = (
        MADDPGConfig()
        .environment(ContinuousTeam)
        .training(rollout_steps_per_iter=16, learning_starts=10_000)
        .evaluation(evaluation_interval=1, evaluation_duration=2)
        .debugging(seed=0)
    )
    malgo = mcfg.build()
    try:
        r = malgo.train()
        ev = r["evaluation"]
        assert ev["episodes_this_iter"] == 2
        assert np.isfinite(ev["episode_reward_mean"])
    finally:
        malgo.cleanup()
