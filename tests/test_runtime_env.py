"""Runtime-env tests.

Modeled on the reference's python/ray/tests/test_runtime_env*.py: env_vars
visible in tasks and actors, working_dir/py_modules imports, job-level env
merging, dedicated workers per env, and unsupported-field rejection.
"""

import os
import sys

import pytest

import ray_tpu
from ray_tpu.runtime_env import RuntimeEnv


def test_env_vars_in_task(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_PROBE": "hello"}})
    def read_env():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(read_env.remote()) == "hello"

    # A plain task must NOT see that env (dedicated workers per env).
    @ray_tpu.remote
    def read_plain():
        return os.environ.get("RTENV_PROBE")

    assert ray_tpu.get(read_plain.remote()) is None


def test_env_vars_in_actor(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_ACTOR": "yes"}})
    class A:
        def probe(self):
            return os.environ.get("RTENV_ACTOR")

    assert ray_tpu.get(A.remote().probe.remote()) == "yes"


def test_py_modules_import(ray_start_regular, tmp_path):
    mod_dir = tmp_path / "mods"
    mod_dir.mkdir()
    (mod_dir / "rtenv_probe_mod.py").write_text("VALUE = 'imported-from-py-modules'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_module():
        import rtenv_probe_mod

        return rtenv_probe_mod.VALUE

    assert ray_tpu.get(use_module.remote()) == "imported-from-py-modules"


def test_working_dir(ray_start_regular, tmp_path):
    (tmp_path / "data.txt").write_text("working-dir-content")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_file():
        with open("data.txt") as f:
            return f.read()

    assert ray_tpu.get(read_file.remote()) == "working-dir-content"


def test_env_worker_evicts_idle_plain_worker():
    """With the pool at the CPU cap and only plain idle workers, a task
    needing a dedicated runtime env must still run promptly (the pool evicts
    a surplus idle worker of another env)."""
    import time

    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def plain():
            return "plain"

        assert ray_tpu.get(plain.remote()) == "plain"  # pool now has 1 idle plain worker

        @ray_tpu.remote(runtime_env={"env_vars": {"EVICT_PROBE": "v"}})
        def dedicated():
            return os.environ.get("EVICT_PROBE")

        start = time.time()
        assert ray_tpu.get(dedicated.remote(), timeout=60) == "v"
        assert time.time() - start < 30
    finally:
        ray_tpu.shutdown()


def test_nested_task_inherits_env(ray_start_regular):
    """A task submitted from inside a runtime-env task inherits that env."""

    @ray_tpu.remote(runtime_env={"env_vars": {"NEST_PROBE": "outer"}})
    def outer():
        @ray_tpu.remote
        def inner():
            return os.environ.get("NEST_PROBE")

        return ray_tpu.get(inner.remote())

    assert ray_tpu.get(outer.remote(), timeout=120) == "outer"


def test_bad_working_dir_rejected_at_submission(ray_start_regular):
    @ray_tpu.remote(runtime_env={"working_dir": "/no/such/dir"})
    def f():
        return 1

    with pytest.raises(ValueError, match="working_dir"):
        f.remote()


def test_pip_rejected_at_submission(ray_start_regular):
    @ray_tpu.remote(runtime_env={"pip": ["requests"]})
    def f():
        return 1

    with pytest.raises(ValueError, match="pip"):
        f.remote()


def test_job_level_runtime_env_merges():
    ray_tpu.init(
        num_cpus=2,
        object_store_memory=64 * 1024 * 1024,
        runtime_env={"env_vars": {"JOB_LEVEL": "j", "BOTH": "job"}},
    )
    try:

        @ray_tpu.remote
        def inherits():
            return os.environ.get("JOB_LEVEL"), os.environ.get("BOTH")

        assert ray_tpu.get(inherits.remote()) == ("j", "job")

        @ray_tpu.remote(runtime_env={"env_vars": {"BOTH": "task"}})
        def overrides():
            return os.environ.get("JOB_LEVEL"), os.environ.get("BOTH")

        # task env_vars merge over job env_vars
        assert ray_tpu.get(overrides.remote()) == ("j", "task")
    finally:
        ray_tpu.shutdown()


def test_runtime_env_class_validation():
    r = RuntimeEnv(env_vars={"A": "1"}, py_modules=["/x"])
    assert r == {"env_vars": {"A": "1"}, "py_modules": ["/x"]}
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})


def test_runtime_env_plugin_protocol(ray_start_regular, tmp_path):
    """Plugin seam (reference: _private/runtime_env/plugin.py): a custom
    field is validated at submission, materialized ONCE per node into the
    per-URI cache, and applied at every worker start."""
    plugin_mod = tmp_path / "greeting_plugin.py"
    plugin_mod.write_text(
        """
import json
import os

from ray_tpu._private.runtime_env_plugins import RuntimeEnvPlugin


class GreetingPlugin(RuntimeEnvPlugin):
    name = "greeting"

    def validate(self, value, runtime_env):
        if not isinstance(value, str):
            raise ValueError("greeting must be a string")

    def create(self, uri, value, runtime_env, target_dir):
        # Expensive-materialization stand-in; runs once per (node, value).
        with open(os.path.join(target_dir, "payload.json"), "w") as f:
            json.dump({"greeting": value.upper(), "pid": os.getpid()}, f)

    def apply(self, value, runtime_env, cached_dirs):
        (cache_dir,) = cached_dirs.values()
        with open(os.path.join(cache_dir, "payload.json")) as f:
            payload = json.load(f)
        os.environ["GREETING_RESULT"] = payload["greeting"]
        os.environ["GREETING_CACHE_DIR"] = cache_dir
"""
    )
    from ray_tpu._private import runtime_env_plugins

    import sys

    sys.path.insert(0, str(tmp_path))
    try:
        import greeting_plugin

        runtime_env_plugins.register_plugin(greeting_plugin.GreetingPlugin())

        @ray_tpu.remote(runtime_env={"greeting": "hello", "py_modules": [str(tmp_path)]})
        def greeted():
            return os.environ.get("GREETING_RESULT"), os.environ.get("GREETING_CACHE_DIR")

        result, cache1 = ray_tpu.get(greeted.remote(), timeout=120)
        assert result == "HELLO"
        assert cache1 and os.path.exists(os.path.join(cache1, "payload.json"))

        # Same value from another worker reuses the SAME cache dir.
        _, cache2 = ray_tpu.get(greeted.remote(), timeout=120)
        assert cache2 == cache1

        # Submission-time validation runs in the driver.
        @ray_tpu.remote(runtime_env={"greeting": 42, "py_modules": [str(tmp_path)]})
        def bad():
            return 1

        with pytest.raises(ValueError):
            bad.remote()
    finally:
        runtime_env_plugins.unregister_plugin("greeting")
        sys.path.remove(str(tmp_path))


def test_unregistered_plugin_field_still_rejected(ray_start_regular):
    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["x"]}})
    def f():
        return 1

    with pytest.raises(ValueError):
        f.remote()
