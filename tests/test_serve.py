"""ray_tpu.serve tests.

Modeled on the reference's python/ray/serve/tests/ (test_standalone.py,
test_deploy.py, test_autoscaling_policy.py, test_batching.py): deployment
lifecycle, handle + HTTP paths, scaling, rolling updates, batching.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _http(path, payload=None, method=None):
    host, port = serve.http_address()
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=data, method=method or ("POST" if data else "GET")
    )
    return urllib.request.urlopen(req, timeout=30).read().decode()


def test_deploy_and_handle(serve_instance):
    @serve.deployment(num_replicas=2)
    class Adder:
        def __init__(self, inc):
            self.inc = inc

        def __call__(self, request):
            return {"v": request.json()["v"] + self.inc}

        def add(self, x):
            return x + self.inc

    handle = serve.run(Adder.bind(10), route_prefix="/adder")
    assert ray_tpu.get(handle.add.remote(5)) == 15
    st = serve.status()
    assert st["Adder"]["num_replicas"] == 2
    out = json.loads(_http("/adder", {"v": 1}))
    assert out == {"v": 11}


def test_function_deployment_and_404(serve_instance):
    @serve.deployment
    def pong(request):
        return "pong"

    serve.run(pong.bind(), route_prefix="/ping")
    assert _http("/ping") == "pong"
    with pytest.raises(urllib.error.HTTPError):
        _http("/nonexistent-route")


def test_scale_up_down(serve_instance):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, request):
            return "ok"

        def who(self):
            import os

            return os.getpid()

    h = serve.run(S.bind(), route_prefix="/scale")
    assert serve.status()["S"]["num_replicas"] == 1
    serve.run(S.options(num_replicas=3).bind(), route_prefix="/scale")
    deadline = time.time() + 30
    while time.time() < deadline and serve.status()["S"]["num_replicas"] != 3:
        time.sleep(0.2)
    assert serve.status()["S"]["num_replicas"] == 3
    pids = {ray_tpu.get(h.who.remote()) for _ in range(12)}
    assert len(pids) >= 2  # requests spread over replicas
    serve.run(S.options(num_replicas=1).bind(), route_prefix="/scale")
    deadline = time.time() + 30
    while time.time() < deadline and serve.status()["S"]["num_replicas"] != 1:
        time.sleep(0.2)
    assert serve.status()["S"]["num_replicas"] == 1


def test_rolling_update_new_version(serve_instance):
    @serve.deployment(version="1")
    class V:
        def __call__(self, request):
            return "v1"

    serve.run(V.bind(), route_prefix="/v")
    assert _http("/v") == "v1"

    @serve.deployment(version="2")
    class V:  # noqa: F811 — redeployment with same name, new version
        def __call__(self, request):
            return "v2"

    serve.run(V.bind(), route_prefix="/v")
    # During the rollout both versions may serve (zero-downtime update);
    # wait for a stable cutover: several consecutive v2 responses.
    deadline = time.time() + 30
    streak = 0
    while time.time() < deadline and streak < 5:
        streak = streak + 1 if _http("/v") == "v2" else 0
        time.sleep(0.1)
    assert streak >= 5, "rollout to v2 did not complete"


def test_delete_deployment(serve_instance):
    @serve.deployment
    def temp(request):
        return "here"

    serve.run(temp.bind(), route_prefix="/temp")
    assert _http("/temp") == "here"
    serve.delete("temp")
    deadline = time.time() + 15
    while time.time() < deadline and "temp" in serve.status():
        time.sleep(0.2)
    assert "temp" not in serve.status()


def test_user_config_reconfigure(serve_instance):
    @serve.deployment(user_config={"threshold": 5})
    class C:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, cfg):
            self.threshold = cfg["threshold"]

        def __call__(self, request):
            return {"threshold": self.threshold}

    serve.run(C.bind(), route_prefix="/cfg")
    assert json.loads(_http("/cfg")) == {"threshold": 5}


def test_batching():
    calls = []

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    def process(items):
        calls.append(len(items))
        return [i * 2 for i in items]

    import threading

    results = [None] * 8

    def call(i):
        results[i] = process(i)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [i * 2 for i in range(8)]
    assert max(calls) > 1  # actually batched


def test_deployment_composition(serve_instance):
    """Deployment graphs: Applications bound as init args become child
    deployments materialized as handles (reference: deployment graph args)."""

    @serve.deployment
    class Doubler:
        def double(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, request):
            return ray_tpu.get(self.doubler.double.remote(request.json()["v"]))

        def compute(self, x):
            return ray_tpu.get(self.doubler.double.remote(x)) + 1

    h = serve.run(Ingress.bind(Doubler.bind()), route_prefix="/compose")
    try:
        assert ray_tpu.get(h.compute.remote(5), timeout=60) == 11
        assert json.loads(_http("/compose", {"v": 4})) == 8
        st = serve.status()
        assert "Doubler" in st and "Ingress" in st
    finally:
        # Free this test's replicas: the module fixture's CPU budget is
        # shared by every deployment in the file.
        serve.delete("Ingress")
        serve.delete("Doubler")


def test_multiplexing(serve_instance):
    """Model multiplexing: per-replica LRU + stable model->replica routing."""

    @serve.deployment(num_replicas=2)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": int(model_id.split("-")[1])}

        def __call__(self, request):
            model = self.get_model(serve.get_multiplexed_model_id())
            return {"result": request.json()["v"] * model["scale"]}

        def predict(self, v):
            model = self.get_model(serve.get_multiplexed_model_id())
            return v * model["scale"]

        def num_loads(self):
            return len(self.loads)

    h = serve.run(MultiModel.bind(), route_prefix="/multi")
    try:
        # Same model id repeatedly: routed to one replica, loaded once.
        for _ in range(4):
            assert ray_tpu.get(h.options(multiplexed_model_id="m-3").predict.remote(2)) == 6
        assert ray_tpu.get(h.options(multiplexed_model_id="m-5").predict.remote(2)) == 10
        # HTTP path with the header.
        host, port = serve.http_address()
        req = urllib.request.Request(
            f"http://{host}:{port}/multi",
            data=json.dumps({"v": 4}).encode(),
            headers={"serve_multiplexed_model_id": "m-2"},
        )
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert out == {"result": 8}
        # m-3 was requested 4x but loaded at most once per replica: two
        # un-multiplexed calls round-robin across BOTH replicas, so the sum
        # covers the whole cache population (3 distinct models + at most one
        # saturation-fallback reload).
        total_loads = sum(ray_tpu.get(h.num_loads.remote()) for _ in range(2))
        assert total_loads <= 4
    finally:
        serve.delete("MultiModel")


def test_streaming_response(serve_instance):
    """Generator handlers stream the HTTP body chunk by chunk (reference:
    serve streaming responses); bytes pass through, other values are
    JSON-lines."""
    import urllib.request

    from ray_tpu import serve

    @serve.deployment
    class Streamer:
        def __call__(self, request):
            def gen():
                for i in range(5):
                    yield f"tok{i} "

            return serve.StreamingResponse(gen(), content_type="text/plain")

    serve.run(Streamer.bind(), name="streamer", route_prefix="/stream")
    url = "http://%s:%d/stream" % serve.http_address()
    with urllib.request.urlopen(url, timeout=60) as resp:
        assert resp.headers.get("Content-Type", "").startswith("text/plain")
        body = resp.read().decode()
    assert body == "tok0 tok1 tok2 tok3 tok4 "

    @serve.deployment
    class BareGen:
        def __call__(self, request):
            yield {"n": 1}
            yield {"n": 2}

    serve.run(BareGen.bind(), name="baregen", route_prefix="/baregen")
    with urllib.request.urlopen("http://%s:%d/baregen" % serve.http_address(), timeout=60) as resp:
        lines = [l for l in resp.read().decode().splitlines() if l]
    assert [json.loads(l)["n"] for l in lines] == [1, 2]
