"""TPUPodProvider against a mock GCE TPU API (VERDICT r1: 'the TPU pod
provider should at least be exercised against a mock GCE API'). The mock
implements the v2 REST surface the provider uses: node create (returns an
operation that completes after one poll), list with labels, get, delete."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest


class _MockTpuApi:
    def __init__(self):
        self.nodes: dict = {}     # node_id -> node dict
        self.ops: dict = {}       # op name -> {polls_left, done, ...}
        self.requests: list = []  # (method, path) log
        self._op_counter = 0

    def start(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                api.requests.append(("GET", self.path))
                parsed = urlparse(self.path)
                parts = parsed.path.strip("/").split("/")
                if "operations" in parts:
                    name = parsed.path.strip("/")
                    if name.startswith("v2/"):
                        name = name[3:]
                    op = api.ops.get(name)
                    if op is None:
                        return self._send(404, {"error": "no such operation"})
                    if op["polls_left"] > 0:
                        op["polls_left"] -= 1
                    else:
                        op["done"] = True
                        if op.get("on_done"):
                            op["on_done"]()
                            op["on_done"] = None
                    return self._send(200, {k: v for k, v in op.items() if k != "on_done"})
                if parts[-1] == "nodes":
                    return self._send(200, {"nodes": list(api.nodes.values())})
                node_id = parts[-1]
                node = api.nodes.get(node_id)
                if node is None:
                    return self._send(404, {"error": "not found"})
                return self._send(200, node)

            def do_POST(self):
                api.requests.append(("POST", self.path))
                parsed = urlparse(self.path)
                qs = parse_qs(parsed.query)
                node_id = qs["nodeId"][0]
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                auth = self.headers.get("Authorization", "")
                api.nodes[node_id] = {
                    "name": f"projects/p/locations/z/nodes/{node_id}",
                    "state": "CREATING",
                    "acceleratorType": body.get("acceleratorType"),
                    "runtimeVersion": body.get("runtimeVersion"),
                    "labels": body.get("labels", {}),
                    "auth": auth,
                }
                op = self._make_op(lambda nid=node_id: api.nodes[nid].__setitem__("state", "READY"))
                return self._send(200, op)

            def do_DELETE(self):
                api.requests.append(("DELETE", self.path))
                node_id = urlparse(self.path).path.strip("/").split("/")[-1]
                api.nodes.pop(node_id, None)
                return self._send(200, self._make_op(None))

            def _make_op(self, on_done):
                api._op_counter += 1
                # Real operation names carry NO version prefix.
                name = f"projects/p/locations/z/operations/op-{api._op_counter}"
                op = {"name": name, "done": False, "polls_left": 1, "on_done": on_done}
                api.ops[name] = op
                return {k: v for k, v in op.items() if k != "on_done"}

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        threading.Thread(target=self.server.serve_forever, daemon=True).start()
        return "http://%s:%d" % self.server.server_address

    def stop(self):
        self.server.shutdown()


@pytest.fixture
def mock_api():
    api = _MockTpuApi()
    api.endpoint = api.start()
    yield api
    api.stop()


def _provider(api, **over):
    from ray_tpu.autoscaler.node_provider import TPUPodProvider

    config = {
        "project_id": "p",
        "zone": "z",
        "api_endpoint": api.endpoint,
        "access_token": "test-token",
        "poll_interval_s": 0.01,
        "create_timeout_s": 10.0,
        "wait_for_ready": True,
        **over,
    }
    return TPUPodProvider(config, "testcluster")


def test_create_list_terminate_lifecycle(mock_api):
    p = _provider(mock_api)
    ids = p.create_node(
        {"accelerator_type": "v5e-8", "runtime_version": "tpu-vm-v4-base"},
        {"ray-node-type": "worker"},
        2,
    )
    assert len(ids) == 2
    # Operation polling drove the nodes to READY.
    assert all(p.is_running(i) for i in ids)
    assert sorted(p.non_terminated_nodes()) == sorted(ids)
    tags = p.node_tags(ids[0])
    assert tags["ray-cluster-name"] == "testcluster"
    assert tags["ray-node-type"] == "worker"
    # Requests carried the bearer token and the accelerator shape.
    node = mock_api.nodes[ids[0]]
    assert node["auth"] == "Bearer test-token"
    assert node["acceleratorType"] == "v5e-8"

    p.terminate_node(ids[0])
    assert p.non_terminated_nodes() == [ids[1]]
    assert not p.is_running(ids[0])


def test_list_filters_other_clusters(mock_api):
    p = _provider(mock_api)
    p.create_node({"accelerator_type": "v5e-8"}, {"ray-node-type": "worker"}, 1)
    # A node from another cluster must be invisible.
    mock_api.nodes["other"] = {
        "name": "projects/p/locations/z/nodes/other",
        "state": "READY",
        "labels": {"ray-cluster-name": "not-ours"},
    }
    assert "other" not in p.non_terminated_nodes()
    assert len(p.non_terminated_nodes()) == 1


def test_real_endpoint_requires_credentials():
    from ray_tpu.autoscaler.node_provider import TPUPodProvider

    with pytest.raises(RuntimeError, match="credentials"):
        TPUPodProvider({"project_id": "p", "zone": "z"}, "c")


def test_demand_scheduler_drives_tpu_provider(mock_api):
    """The demand scheduler's launch plan drives the mock-GCE provider:
    TPU-shaped demand creates v5e-8 nodes (the same plan->create path
    StandardAutoscaler.update runs; reference: ResourceDemandScheduler over
    the GCP provider)."""
    from ray_tpu.autoscaler.resource_demand_scheduler import ResourceDemandScheduler

    node_types = {
        "tpu_worker": {
            "resources": {"TPU": 8, "CPU": 8},
            "node_config": {"accelerator_type": "v5e-8"},
            "max_workers": 4,
        },
    }
    sched = ResourceDemandScheduler(node_types, max_workers=4)
    plan = sched.get_nodes_to_launch(
        existing_avail=[],
        demands=[{"TPU": 8}, {"TPU": 8}],
        counts_by_type={},
        total_existing=0,
    )
    assert plan == {"tpu_worker": 2}

    p = _provider(mock_api)
    for node_type, count in plan.items():
        p.create_node(
            node_types[node_type]["node_config"],
            {"ray-node-type": node_type, "node_type": node_type},
            count,
        )
    assert len(p.non_terminated_nodes()) == 2
    assert all(n["acceleratorType"] == "v5e-8" for n in mock_api.nodes.values())
