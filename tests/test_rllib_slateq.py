"""SlateQ on the synthetic RecSim-style slate environment.

Learning-gated: the decomposed slate Q must clearly beat the random-slate
baseline (~17.6 mean session reward on this env/seed family) within test
time (reference: rllib/algorithms/slateq/ + RecSim interest evolution)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray_cluster():
    import jax

    jax.config.update("jax_platforms", "cpu")
    ray_tpu.init(num_cpus=2, object_store_memory=96 * 1024 * 1024)
    try:
        yield
    finally:
        ray_tpu.shutdown()


def test_slateq_learns_interest_evolution(ray_cluster):
    from ray_tpu.rllib import SlateQConfig
    from ray_tpu.rllib.env.recsys import SlateRecEnv

    cfg = (
        SlateQConfig()
        .environment(SlateRecEnv)
        .training(
            rollout_steps_per_iter=400,
            learning_starts=400,
            train_intensity=2,
            epsilon_timesteps=4000,
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    best = -1e9
    try:
        for _ in range(25):
            r = algo.step()
            erm = r.get("episode_reward_mean")
            if erm == erm:  # not NaN
                best = max(best, erm)
            if best >= 24:
                break
        # Random slates score ~17.6 on this env; the decomposition must
        # push well past it.
        assert best >= 24, f"SlateQ failed to beat random slates (best={best})"
        # Greedy slate API: K distinct candidate indices.
        obs, _ = algo.env.reset(seed=7)
        slate = algo.compute_single_action(obs)
        assert len(set(int(i) for i in slate)) == algo.K
        assert all(0 <= int(i) < algo.C for i in slate)
    finally:
        algo.cleanup()


def test_slateq_checkpoint_roundtrip(ray_cluster):
    from ray_tpu.rllib import SlateQConfig
    from ray_tpu.rllib.env.recsys import SlateRecEnv

    cfg = (
        SlateQConfig()
        .environment(SlateRecEnv)
        .training(rollout_steps_per_iter=100, learning_starts=50, train_intensity=4)
        .debugging(seed=0)
    )
    algo = cfg.build()
    algo.setup(cfg.to_dict())
    algo.step()
    ckpt = algo.save_checkpoint()
    algo2 = cfg.build()
    algo2.setup(cfg.to_dict())
    algo2.load_checkpoint(ckpt)
    assert algo2._timesteps_total == algo._timesteps_total
    import jax

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        algo.params, algo2.params,
    )
    algo.cleanup()
    algo2.cleanup()
