"""ray_tpu.cancel() semantics (analog of the reference's cancellation tests
in python/ray/tests/test_cancel.py; semantics per _private/worker.py:2773 and
core_worker.cc CancelTask)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


def _interruptible(seconds):
    # Many short sleeps: the cancellation async-exc lands on a bytecode
    # boundary between iterations.
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        time.sleep(0.01)


def test_cancel_running_task(ray_start_regular):
    @ray_tpu.remote
    def slow():
        _interruptible(60)
        return "done"

    ref = slow.remote()
    time.sleep(1.5)  # let it start
    ray_tpu.cancel(ref)
    start = time.monotonic()
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - start < 25  # interrupted, not run to completion


def test_cancel_interrupts_c_blocked_sleep(ray_start_regular):
    # ONE long time.sleep: blocks in C, so an async-exc alone would never
    # land (no bytecode boundary for 60s). Tasks run on the worker's main
    # thread and cancel delivers SIGUSR2 whose handler raises — PEP 475
    # aborts the in-flight sleep (reference: KeyboardInterrupt into the
    # worker main thread via PyErr_SetInterrupt).
    @ray_tpu.remote
    def c_blocked():
        time.sleep(60)
        return "done"

    ref = c_blocked.remote()
    time.sleep(1.5)  # let it start
    ray_tpu.cancel(ref)
    start = time.monotonic()
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - start < 20  # sleep aborted, not run out


def test_cancel_pending_task_lease_path(ray_start_regular):
    # Saturate the CPUs so extra tasks stay queued owner-side/raylet-side.
    @ray_tpu.remote
    def hog():
        _interruptible(8)
        return "hogged"

    @ray_tpu.remote
    def queued():
        return "ran"

    hogs = [hog.remote() for i in range(4)]
    time.sleep(0.5)
    ref = queued.remote()
    time.sleep(0.2)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    # The hogs are unaffected.
    assert ray_tpu.get(hogs, timeout=60) == ["hogged"] * 4


def test_cancel_pending_task_classic_path(ray_start_regular):
    @ray_tpu.remote
    def hog():
        _interruptible(8)

    @ray_tpu.remote
    def queued():
        return "ran"

    hogs = [hog.options(scheduling_strategy="SPREAD").remote() for i in range(4)]
    time.sleep(0.5)
    # SPREAD keeps this off the direct-lease transport (classic raylet queue).
    ref = queued.options(scheduling_strategy="SPREAD").remote()
    time.sleep(0.2)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    ray_tpu.get(hogs, timeout=60)


def test_cancel_finished_task_is_noop(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 7

    ref = fast.remote()
    assert ray_tpu.get(ref, timeout=30) == 7
    ray_tpu.cancel(ref)  # no-op
    assert ray_tpu.get(ref, timeout=30) == 7


def test_cancel_force_kills_worker(ray_start_regular):
    @ray_tpu.remote(max_retries=2)
    def stubborn():
        # Swallows the graceful interrupt — only force gets it.
        while True:
            try:
                _interruptible(60)
            except TaskCancelledError:
                pass

    ref = stubborn.remote()
    time.sleep(1.5)
    ray_tpu.cancel(ref, force=True)
    # Force-kill must surface as cancellation, not retry (despite max_retries).
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_cancel_task_waiting_on_args(ray_start_regular):
    @ray_tpu.remote
    def slow_producer():
        _interruptible(8)
        return 1

    @ray_tpu.remote
    def consumer(x):
        return x + 1

    dep = slow_producer.remote()
    ref = consumer.remote(dep)
    time.sleep(0.2)
    ray_tpu.cancel(ref)  # still owner-local, resolving args
    start = time.monotonic()
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - start < 5  # failed locally, didn't wait for dep
    assert ray_tpu.get(dep, timeout=60) == 1


def test_cancel_queued_actor_task(ray_start_regular):
    @ray_tpu.remote
    class Worker:
        def slow(self):
            _interruptible(6)
            return "slow"

        def fast(self):
            return "fast"

    w = Worker.remote()
    slow_ref = w.slow.remote()
    time.sleep(0.5)
    queued_ref = w.fast.remote()  # queued behind slow() at the actor
    ray_tpu.cancel(queued_ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued_ref, timeout=30)
    assert ray_tpu.get(slow_ref, timeout=60) == "slow"
    # The actor survives and serves later calls.
    assert ray_tpu.get(w.fast.remote(), timeout=30) == "fast"


def test_cancel_running_actor_task(ray_start_regular):
    @ray_tpu.remote
    class Worker:
        def slow(self):
            _interruptible(60)
            return "slow"

        def ping(self):
            return "pong"

    w = Worker.remote()
    ref = w.slow.remote()
    time.sleep(1.5)
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert ray_tpu.get(w.ping.remote(), timeout=30) == "pong"


def test_cancel_actor_task_force_raises(ray_start_regular):
    @ray_tpu.remote
    class Worker:
        def slow(self):
            _interruptible(30)

    w = Worker.remote()
    ref = w.slow.remote()
    time.sleep(0.5)
    with pytest.raises(ValueError):
        ray_tpu.cancel(ref, force=True)
    ray_tpu.cancel(ref)  # clean up


def test_cancel_recursive(ray_start_regular, tmp_path):
    marker = str(tmp_path / "child_finished")

    @ray_tpu.remote
    def child(path):
        _interruptible(5)
        with open(path, "w") as f:
            f.write("done")
        return "child"

    @ray_tpu.remote
    def parent(path):
        ref = child.remote(path)
        return ray_tpu.get(ref, timeout=60)

    ref = parent.remote(marker)
    time.sleep(2.0)  # parent started and submitted the child
    ray_tpu.cancel(ref, recursive=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    # The child was cancelled too: its completion marker never appears.
    time.sleep(6.0)
    assert not os.path.exists(marker)


def test_cancel_wrong_type_raises(ray_start_regular):
    with pytest.raises(TypeError):
        ray_tpu.cancel("not a ref")
